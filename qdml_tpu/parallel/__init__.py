from qdml_tpu.parallel.dp import (  # noqa: F401
    replicate,
    shard_flat_batch,
    shard_grid_batch,
)
from qdml_tpu.parallel.federated import (  # noqa: F401
    hdce_state_shardings,
    shard_hdce_state,
)
from qdml_tpu.parallel.mesh import (  # noqa: F401
    init_distributed,
    make_mesh,
    single_device_mesh,
)
from qdml_tpu.parallel.multihost import (  # noqa: F401
    init_distributed_from_env,
    local_grid_batch_to_global,
    process_batch_slice,
)
