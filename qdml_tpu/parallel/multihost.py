"""Multi-host SPMD: process-local data generation -> global sharded arrays.

The reference's distribution never leaves one process
(``torch.nn.DataParallel``, ``Runner_P128_QuantumNAT_onchipQNN.py:144-148``).
The TPU-native multi-host design (SURVEY.md §5.8): every host runs the same
program under ``jax.distributed``; the mesh spans all hosts' devices (ICI
within a slice, DCN across slices); each host synthesizes ONLY its slice of
the global batch (the generator is deterministic in the sample index, so no
coordination or data exchange is needed); and
``jax.make_array_from_process_local_data`` assembles the global ``jax.Array``
without any host ever materializing the full batch.

Single-process (tests, the one-chip dev loop) is the degenerate case: the
local slice IS the global batch, and the assembly reduces to a device_put —
verified equivalent in ``tests/test_parallel.py``.
"""

from __future__ import annotations

import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from qdml_tpu.parallel.dp import _pad


def init_distributed_from_env() -> bool:
    """``jax.distributed.initialize`` from the standard env triple
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``);
    on TPU pods jax autodetects all three from the metadata server, so plain
    ``initialize()`` is attempted when only a coordinator is set. Returns
    whether a multi-process runtime was initialised (False = single process,
    a no-op)."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if addr is None:
        return False
    try:
        if nproc is not None and pid is not None:
            jax.distributed.initialize(
                coordinator_address=addr,
                num_processes=int(nproc),
                process_id=int(pid),
            )
        else:
            jax.distributed.initialize(coordinator_address=addr)
        return jax.process_count() > 1
    except RuntimeError:
        return jax.process_count() > 1  # already initialised


def process_batch_slice(global_bs: int, mesh: Mesh, axis: str = "data") -> tuple[int, int]:
    """(start, length) of THIS process's slice of the global batch axis.

    The data axis is laid out contiguously over processes (each host owns the
    devices ``jax.local_devices()``), so with P processes each generates
    ``global_bs / P`` consecutive sample indices of every (scenario, user)
    cell — the deterministic index-seeded generator makes the slices globally
    consistent with zero coordination.
    """
    nproc = jax.process_count()
    if global_bs % nproc:
        raise ValueError(f"global batch {global_bs} not divisible by {nproc} processes")
    local = global_bs // nproc
    return jax.process_index() * local, local


def local_grid_batch_to_global(batch: dict, mesh: Mesh, fed: bool = False) -> dict:
    """Assemble per-process local ``(S, U, local_B, ...)`` grid batches into
    global arrays with B sharded over ``data`` (and optionally S over ``fed``)
    — the multi-host twin of :func:`qdml_tpu.parallel.dp.shard_grid_batch`.
    """
    s_axis = "fed" if fed and mesh.shape.get("fed", 1) > 1 else None

    def put(x):
        x = np.asarray(x)
        spec = _pad((s_axis, None, "data"), x.ndim)
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, batch)
