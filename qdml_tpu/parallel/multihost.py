"""Multi-host SPMD: process-local data generation -> global sharded arrays.

The reference's distribution never leaves one process
(``torch.nn.DataParallel``, ``Runner_P128_QuantumNAT_onchipQNN.py:144-148``).
The TPU-native multi-host design (SURVEY.md §5.8): every host runs the same
program under ``jax.distributed``; the mesh spans all hosts' devices (ICI
within a slice, DCN across slices); each host synthesizes ONLY its slice of
the global batch (the generator is deterministic in the sample index, so no
coordination or data exchange is needed); and
``jax.make_array_from_process_local_data`` assembles the global ``jax.Array``
without any host ever materializing the full batch.

Single-process (tests, the one-chip dev loop) is the degenerate case: the
local slice IS the global batch, and the assembly reduces to a device_put —
verified equivalent in ``tests/test_parallel.py``.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding

from qdml_tpu.parallel.dp import grid_batch_spec


def _runtime_initialized() -> bool:
    """Whether ``jax.distributed`` already has a live coordination client."""
    try:
        from jax._src import distributed as _dist

        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # lint: disable=broad-except(private-API liveness probe — a moved API reads as not-initialized; never fatal)
        return False


def _ensure_cpu_collectives() -> bool:
    """Select the Gloo CPU-collectives implementation for a multi-process
    cluster on the CPU backend; returns whether the config was changed.

    jax 0.4.x defaults the option to "none", under which any cross-process
    computation fails with "Multiprocess computations aren't implemented on
    the CPU backend"; newer jax defaults to gloo and may drop the option —
    both the lookup and the update are therefore best-effort. Only callers
    that KNOW a multi-process init is happening may flip it: with gloo
    selected but no distributed client, plain single-process CPU backend
    init itself fails (make_gloo_tcp_collectives rejects a None client)."""
    if (os.environ.get("JAX_PLATFORMS") or "").split(",")[0] != "cpu":
        return False
    try:
        from jax._src import config as _config

        if _config.config.values.get("jax_cpu_collectives_implementation") == "none":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            return True
    except Exception:  # lint: disable=broad-except(best-effort jax config probe — the option may not exist in this jax version)
        pass
    return False


def ensure_initialized(**kwargs) -> None:
    """Idempotent ``jax.distributed.initialize``: a no-op when the runtime is
    already live (probed, with a message-matched RuntimeError fallback in case
    the private probe API moves), while genuine failures — unreachable
    coordinator, barrier timeout — still propagate."""
    if _runtime_initialized():
        return
    # Explicitly multi-process on the CPU backend: select Gloo collectives
    # (jax 0.4.x default "none" cannot run cross-process computations). The
    # no-kwargs autodetection path must NOT flip it — autodetection failing
    # benignly (single process) would leave a poisoned config that breaks
    # plain CPU backend init.
    nproc = kwargs.get("num_processes")
    flipped = (
        _ensure_cpu_collectives()
        if isinstance(nproc, int) and nproc > 1
        else False
    )
    try:
        jax.distributed.initialize(**kwargs)
    except Exception as e:
        # Benign repeat call. jax's message is "distributed.initialize should
        # only be called once." (jax/_src/distributed.py); "already" covers
        # older/newer phrasings.
        if isinstance(e, RuntimeError):
            msg = str(e).lower()
            if "already" in msg or "only be called once" in msg:
                return
        if flipped:  # don't leave gloo configured without a client
            try:
                jax.config.update("jax_cpu_collectives_implementation", "none")
            except Exception:  # lint: disable=broad-except(config rollback on the failure path must not mask the init error re-raised below)
                pass
        raise


# Env-marker PREFIXES that indicate this host is (or may be) part of an
# accelerator cluster where jax's pod autodetection is worth attempting. On
# hosts with none of them (laptops, CI, CPU boxes) the bare initialize()
# attempt is skipped entirely: its benign-fallback contract rests on
# autodetection raising exactly ValueError, and a slow metadata probe would
# change plain startup for nothing. The net is deliberately WIDE over TPU
# environments — a GCE (non-GKE) pod advertises its topology only via the
# metadata server, but its runtime image still exports TPU_* variables, so
# prefix matching keeps autodetection live there (a 1-process initialize on
# a single-host TPU VM is benign); QDML_POD_AUTODETECT=1 covers anything
# exotic (docs/MULTIHOST.md).
_POD_ENV_HINT_PREFIXES = ("TPU_", "MEGASCALE_", "CLOUD_TPU_")


def pod_env_hint() -> bool:
    """Whether the environment looks like an accelerator-cluster worker.

    Platform markers count on any non-empty value (``TPU_WORKER_ID=0`` is a
    real rank); the explicit ``QDML_POD_AUTODETECT`` opt-in is parsed as a
    boolean so ``=0``/``=false`` means what it says.
    """
    optin = os.environ.get("QDML_POD_AUTODETECT", "").strip().lower()
    if optin:
        return optin in ("1", "true", "yes")
    return any(
        k.startswith(_POD_ENV_HINT_PREFIXES) and v
        for k, v in os.environ.items()
    )


def init_distributed_from_env() -> bool:
    """``jax.distributed.initialize`` from the standard env triple
    (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``, ``JAX_PROCESS_ID``);
    on TPU pods jax autodetects all three from the metadata server, so plain
    ``initialize()`` is attempted when only a coordinator is set. Returns
    whether a multi-process runtime was initialised (False = single process,
    a no-op).

    A genuine initialize failure (unreachable coordinator, barrier timeout)
    propagates: swallowing it would silently degrade a pod run to N
    independent single-process trainings on identical data."""
    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    nproc = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if addr is None:
        return False
    if nproc is not None and pid is not None:
        ensure_initialized(
            coordinator_address=addr,
            num_processes=int(nproc),
            process_id=int(pid),
        )
    else:
        ensure_initialized(coordinator_address=addr)
    return jax.process_count() > 1


def process_batch_slice(global_bs: int, mesh: Mesh, axis: str = "data") -> tuple[int, int]:
    """(start, length) of THIS process's slice of the global batch axis.

    The contract (validated below, not assumed): the mesh lays the ``axis``
    coordinates out process-contiguously and no OTHER mesh axis crosses a
    process boundary — then with P processes each generates ``global_bs / P``
    consecutive sample indices of every (scenario, user) cell, and the
    deterministic index-seeded generator makes the slices globally consistent
    with zero coordination. A mesh that interleaves processes along ``axis``
    (e.g. a hybrid DCN mesh with reordered devices) would silently permute
    the global batch, so it is rejected here.
    """
    nproc = jax.process_count()
    if global_bs % nproc:
        raise ValueError(f"global batch {global_bs} not divisible by {nproc} processes")
    if nproc > 1:
        rows = np.moveaxis(mesh.devices, list(mesh.axis_names).index(axis), 0)
        n_coord = rows.shape[0]
        if n_coord % nproc:
            raise ValueError(
                f"mesh axis {axis!r} has {n_coord} coordinates over {nproc} "
                "processes — uneven ownership breaks the equal per-process "
                "slice contract"
            )
        for i in range(n_coord):
            procs = {d.process_index for d in rows[i].flat}
            expect = {i * nproc // n_coord}
            if procs != expect:
                raise ValueError(
                    f"mesh axis {axis!r} is not process-contiguous: coordinate "
                    f"{i} lives on processes {sorted(procs)}, expected {expect} "
                    "— process-local generation would permute the global batch"
                )
    local = global_bs // nproc
    return jax.process_index() * local, local


def process_grid_slice(
    global_bs: int, n_scenarios: int, mesh: Mesh, fed: bool
) -> tuple[int, int, int, int]:
    """The contiguous ``(scenario, batch)`` rectangle THIS process generates.

    Returns ``(scen_start, scen_count, b_start, b_count)``. Generalizes
    :func:`process_batch_slice` to federated multi-host layouts (BASELINE
    config 4: federated scenario trunks ACROSS pod slices): with the grid
    batch sharded S-over-``fed`` and B-over-``data``, a process's devices
    must occupy a full contiguous rectangle of (fed, data) coordinates —
    then it synthesizes exactly the scenario rows and batch columns its
    addressable shards need, and the slice is derived from the OWNED
    COORDINATES (not the process index), so any block assignment of
    processes to the grid works. ``model``-axis devices of one (fed, data)
    cell must stay within one process. Violations fail fast with the
    offending layout instead of silently permuting the global batch.
    """
    nproc = jax.process_count()
    if nproc == 1:
        return 0, n_scenarios, 0, global_bs
    if not fed or mesh.shape.get("fed", 1) == 1:
        b0, blen = process_batch_slice(global_bs, mesh)
        return 0, n_scenarios, b0, blen
    names = list(mesh.axis_names)
    devs = np.moveaxis(
        mesh.devices, [names.index("fed"), names.index("data")], [0, 1]
    )
    n_fed, n_data = devs.shape[0], devs.shape[1]
    if n_scenarios % n_fed:
        raise ValueError(
            f"{n_scenarios} scenarios do not shard evenly over the fed axis ({n_fed})"
        )
    if global_bs % n_data:
        raise ValueError(
            f"global batch {global_bs} not divisible by the mesh data axis ({n_data})"
        )
    cell_proc = np.empty((n_fed, n_data), dtype=np.int64)
    for f in range(n_fed):
        for d in range(n_data):
            procs = {dev.process_index for dev in np.ravel(devs[f, d])}
            if len(procs) != 1:
                raise ValueError(
                    f"mesh cell (fed={f}, data={d}) spans processes "
                    f"{sorted(procs)} along the model axis — a cell's "
                    "tensor-parallel group must live within one process"
                )
            cell_proc[f, d] = procs.pop()
    mine = np.argwhere(cell_proc == jax.process_index())
    if mine.size == 0:
        raise ValueError(f"process {jax.process_index()} owns no devices of this mesh")
    rows, cols = np.unique(mine[:, 0]), np.unique(mine[:, 1])
    contiguous = lambda a: np.array_equal(a, np.arange(a[0], a[0] + len(a)))  # noqa: E731
    if len(rows) * len(cols) != len(mine) or not (contiguous(rows) and contiguous(cols)):
        raise ValueError(
            f"process {jax.process_index()}'s (fed, data) cells {mine.tolist()} "
            "do not form a contiguous rectangle — process-local generation "
            "needs one contiguous (scenario, batch) block per process"
        )
    spf = n_scenarios // n_fed
    bpd = global_bs // n_data
    return int(rows[0]) * spf, len(rows) * spf, int(cols[0]) * bpd, len(cols) * bpd


def make_grid_placer(loader, mesh: Mesh | None, fed: bool = False):
    """Batch-placement policy shared by the production trainers.

    Returns a callable ``batch -> batch`` for one ``DMLGridLoader``:

    - no mesh: identity (single-device);
    - batch divides the ``data`` axis: the multi-host assembly path — under
      multiple processes the loader is switched to per-process slice
      generation first (:meth:`DMLGridLoader.set_process_slice`), and
      single-process degenerates to a plain sharded device_put (equivalence
      covered in ``tests/test_parallel.py``);
    - batch does NOT divide (split-clamped tiny validation loaders): stay
      host-side replicated on one process — and refuse outright on several,
      where replicated placement cannot work.
    """
    if mesh is None:
        return lambda b: b
    bs = loader.batch_size
    data = mesh.shape["data"]
    nproc = jax.process_count()
    if bs % data:
        if nproc > 1:
            raise ValueError(
                f"batch {bs} (split-clamped) not divisible by the mesh data "
                f"axis ({data}); cannot place it on a multi-process mesh"
            )
        print(
            f"note: batch {bs} not divisible by mesh data axis ({data}); "
            "running this loader replicated (no data parallelism)"
        )
        return lambda b: b
    if nproc == 1:
        # Plain sharded device_put: batches are already on-device jitted
        # outputs; the process-local assembly path below would round-trip
        # them through host numpy every step for nothing.
        from qdml_tpu.parallel.dp import shard_grid_batch

        return lambda b: shard_grid_batch(b, mesh, fed=fed)
    s0, sc, b0, blen = process_grid_slice(bs, loader.cfg.n_scenarios, mesh, fed)
    loader.set_process_slice(b0, blen, s0, sc)
    return lambda b: local_grid_batch_to_global(b, mesh, fed=fed)


def local_grid_batch_to_global(batch: dict, mesh: Mesh, fed: bool = False) -> dict:
    """Assemble per-process local ``(S, U, local_B, ...)`` grid batches into
    global arrays with B sharded over ``data`` (and optionally S over ``fed``)
    — the multi-host twin of :func:`qdml_tpu.parallel.dp.shard_grid_batch`
    (both derive their layout from :func:`qdml_tpu.parallel.dp.grid_batch_spec`).
    """

    def put(x):
        # Pass jax arrays straight through — np.asarray would force a
        # device-to-host transfer of every leaf every step; the assembly
        # slices device-to-device where it can.
        sharding = NamedSharding(mesh, grid_batch_spec(mesh, fed, jnp.ndim(x)))
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(put, batch)
