"""Data-parallel placement: batch sharding over the mesh ``data`` axis.

Replaces ``torch.nn.DataParallel``'s per-forward scatter/replicate/gather
(``Runner_P128_QuantumNAT_onchipQNN.py:144-148``) with SPMD: the batch is
device_put with a ``NamedSharding`` splitting the batch dimension, params are
replicated, and the jitted train step — the SAME function used single-chip
(:func:`qdml_tpu.train.hdce.make_hdce_train_step`) — runs with XLA inserting
the gradient all-reduce (psum over ICI) automatically. There is no explicit
communication code anywhere; the annotations are the communication layer.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P


def _pad(spec: tuple, ndim: int) -> P:
    return P(*(spec + (None,) * (ndim - len(spec))))


def grid_batch_spec(mesh: Mesh, fed: bool, ndim: int) -> P:
    """PartitionSpec for one ``(S, U, B, ...)`` DML grid-batch leaf: B over
    ``data``, optionally S over ``fed``. Single source for both the
    single-process placement (:func:`shard_grid_batch`) and the multi-host
    assembly (:func:`qdml_tpu.parallel.multihost.local_grid_batch_to_global`),
    so the two paths cannot drift apart on the grid layout."""
    s_axis = "fed" if fed and mesh.shape.get("fed", 1) > 1 else None
    return _pad((s_axis, None, "data"), ndim)


def shard_grid_batch(batch: dict, mesh: Mesh, fed: bool = False) -> dict:
    """Place a DML grid batch ``(S, U, B, ...)``: B over ``data``; optionally
    S over ``fed`` (federated training, see :mod:`qdml_tpu.parallel.federated`)."""

    def put(x):
        spec = grid_batch_spec(mesh, fed, jax.numpy.ndim(x))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)


def shard_flat_batch(batch: Any, mesh: Mesh) -> Any:
    """Place a flat-batch pytree ``(B, ...)`` with B over ``data``."""

    def put(x):
        return jax.device_put(x, NamedSharding(mesh, _pad(("data",), jax.numpy.ndim(x))))

    return jax.tree.map(put, batch)


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh (params, opt state for pure DP)."""
    if jax.process_count() > 1:
        # device_put rejects non-addressable shardings; a jitted identity
        # with out_shardings is the multi-controller way to place state.
        sharding = NamedSharding(mesh, P())
        return jax.jit(lambda t: t, out_shardings=jax.tree.map(lambda _: sharding, tree))(tree)
    return jax.tree.map(lambda x: jax.device_put(x, NamedSharding(mesh, P())), tree)
