"""Device-mesh construction: the communication layer IS the mesh.

The reference's only distribution is single-process ``torch.nn.DataParallel``
over 4 CUDA GPUs (``Runner_P128_QuantumNAT_onchipQNN.py:144-148`` — per-forward
scatter/replicate/gather; no NCCL/MPI anywhere, SURVEY.md §2.7). TPU-native
replacement: a named ``jax.sharding.Mesh`` with three logical axes —

- ``data``  — batch sharding (data parallel; gradient psum compiler-inserted),
- ``model`` — tensor/statevector sharding (the 2^n amplitudes, the 4096x2048
  head),
- ``fed``   — the federated scenario axis (per-BS trunks local, shared head
  psum-aggregated; BASELINE.json config 4),

with XLA collectives riding ICI within a slice and DCN across slices. For
multi-host, call :func:`init_distributed` first (``jax.distributed``).
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh

from qdml_tpu.config import MeshConfig


def init_distributed(**kwargs) -> None:
    """Idempotent multi-host init. Delegates to
    :func:`qdml_tpu.parallel.multihost.ensure_initialized`: benign repeat
    calls are no-ops, but genuine coordinator failures propagate instead of
    silently degrading a pod run to independent single-process trainings."""
    from qdml_tpu.parallel.multihost import ensure_initialized

    try:
        ensure_initialized(**kwargs)
    except ValueError:
        # "coordinator_address should be defined": no cluster configured —
        # the documented single-process no-op. Coordinator *failures* are
        # RuntimeError and still propagate.
        pass


def make_mesh(cfg: MeshConfig | None = None, devices=None) -> Mesh:
    """Build a (fed, data, model) mesh from the available devices.

    ``data_axis=-1`` consumes all devices left over after the model/fed axes.
    """
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    model = max(cfg.model_axis, 1)
    fed = max(cfg.fed_axis, 1)
    if cfg.data_axis == -1:
        data = max(n // (model * fed), 1)
    else:
        data = max(cfg.data_axis, 1)
    need = fed * data * model
    if need > n:
        raise ValueError(f"mesh {fed}x{data}x{model} needs {need} devices, have {n}")
    arr = np.array(devices[:need]).reshape(fed, data, model)
    return Mesh(arr, (cfg.fed_axis_name, cfg.data_axis_name, cfg.model_axis_name))


def training_mesh(cfg) -> Mesh | None:
    """Mesh for the production trainers, or ``None`` on a single device.

    Validates what is knowable up front with clear messages (axis names, the
    federated axis vs the scenario count); batch divisibility is judged
    per-loader by :func:`qdml_tpu.parallel.multihost.make_grid_placer`, which
    sees the split-clamped batch size this function cannot know.

    Multi-process runs must initialize ``jax.distributed`` BEFORE any JAX
    computation (the CLI does this at startup) — it cannot be initialized
    once the XLA backend is live, and by the time a trainer reaches this
    function its loaders/model init have already touched jax.
    """
    names = (cfg.mesh.fed_axis_name, cfg.mesh.data_axis_name, cfg.mesh.model_axis_name)
    if names != ("fed", "data", "model"):
        raise ValueError(
            f"mesh axis names are fixed to ('fed', 'data', 'model'); got {names} — "
            "the sharding specs in qdml_tpu.parallel use the names literally"
        )
    devices = jax.devices()
    if len(devices) == 1:
        return None
    mesh = make_mesh(cfg.mesh, devices)
    fed = mesh.shape[cfg.mesh.fed_axis_name]
    if fed > 1 and fed != cfg.data.n_scenarios:
        raise ValueError(
            f"mesh fed axis ({fed}) must equal data.n_scenarios "
            f"({cfg.data.n_scenarios}) to shard the scenario grid"
        )
    return mesh


def serve_mesh(cfg) -> Mesh | None:
    """Mesh for the serving engine, or ``None`` for the single-device layout.

    The request path's twin of :func:`training_mesh`: ``serve.shard="auto"``
    (default) builds the (fed, data, model) mesh whenever more than one
    device is visible, so every AOT bucket executable is lowered with its
    batch axis data-parallel across the whole topology; ``"off"`` pins the
    single-device PR-2 layout regardless of device count. Expert sharding
    (``serve.expert_sharding``) additionally requires the fed axis to equal
    the scenario count — validated here, before any bucket compiles, with
    the same message contract as training.
    """
    if cfg.serve.shard not in ("auto", "off"):
        raise ValueError(
            f"serve.shard must be 'auto' or 'off', got {cfg.serve.shard!r}"
        )
    if cfg.serve.shard == "off":
        if cfg.serve.expert_sharding:
            # contradictory on its face — never silently un-shard the experts
            raise ValueError(
                "serve.expert_sharding=true requires sharding: remove "
                "serve.shard='off' (or drop expert_sharding)"
            )
        return None
    mesh = training_mesh(cfg)
    if mesh is None:
        if cfg.serve.expert_sharding:
            # portable configs run on laptops too: degrade loudly, not
            # silently (the single visible device serves every expert)
            print(
                "note: serve.expert_sharding requested but only one device "
                "is visible — serving single-device, experts unsharded"
            )
        return None
    if cfg.serve.expert_sharding and mesh.shape[cfg.mesh.fed_axis_name] != cfg.data.n_scenarios:
        raise ValueError(
            f"serve.expert_sharding needs mesh.fed_axis == data.n_scenarios "
            f"({cfg.data.n_scenarios}); the mesh has fed="
            f"{mesh.shape[cfg.mesh.fed_axis_name]}"
        )
    return mesh


def single_device_mesh() -> Mesh:
    return Mesh(np.array(jax.devices()[:1]).reshape(1, 1, 1), ("fed", "data", "model"))
