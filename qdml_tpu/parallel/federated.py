"""Federated/DML sharding: per-scenario trunks local, shared head aggregated.

The reference's signature "distributed ML" pattern (SURVEY.md §2.7): three
scenario-specific ``Conv_P128`` trunks + ONE shared ``FC_P128`` head, gradients
accumulated across the 3x3 scenario/user grid every step
(``Runner_P128_QuantumNAT_onchipQNN.py:139-142, 181-204``). In the TPU
re-design each "base station" (scenario) lives on its own ``fed`` mesh slice:

- stacked trunk params/opt-state/batch-stats shard their leading scenario axis
  over ``fed`` — trunk gradients never leave their slice (local models),
- the shared head is replicated; because its gradient sums contributions from
  the fed-sharded scenario axis, GSPMD inserts exactly one psum over ``fed``
  per step — the federated aggregation, compiled, over ICI,
- the grid batch shards S over ``fed`` and B over ``data`` (DP composes).

Optionally the 4096x2048 head is ALSO tensor-parallel over ``model``
(column-sharded kernel), demonstrating tp x dp x fed on one tiny model.

No hand-written collectives: this module only builds ``NamedSharding`` trees
for the existing train step.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from qdml_tpu.train.state import TrainState


def _path_str(path) -> str:
    return "/".join(str(getattr(p, "key", getattr(p, "idx", p))) for p in path)


def hdce_state_shardings(
    state: TrainState, mesh: Mesh, n_scenarios: int = 3, tensor_parallel: bool = False
) -> Any:
    """NamedSharding tree for a full HDCE TrainState (params + opt state +
    batch stats — optax's Adam moments mirror the param tree, so one rule set
    covers everything)."""
    fed_ok = mesh.shape.get("fed", 1) == n_scenarios
    tp_ok = tensor_parallel and mesh.shape.get("model", 1) > 1

    def spec_for(path, leaf) -> NamedSharding:
        nd = jax.numpy.ndim(leaf)
        ps = _path_str(path)
        if fed_ok and "StackedConvP128" in ps and nd >= 1 and leaf.shape[0] == n_scenarios:
            return NamedSharding(mesh, P("fed", *(None,) * (nd - 1)))
        if tp_ok and "FCP128" in ps and ps.endswith("kernel") and nd == 2:
            return NamedSharding(mesh, P(None, "model"))
        if tp_ok and "FCP128" in ps and ps.endswith("bias") and nd == 1:
            return NamedSharding(mesh, P("model"))
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(spec_for, state)


def place_tree(tree: Any, shardings: Any) -> Any:
    """Place any pytree against a matching NamedSharding tree — the ONE
    placement choke point for params/opt-state/eval vars AND the serving
    engine's committed checkpoints (warmup placement and every hot-swap
    re-placement route here, so a multihost serve frontend places exactly
    like multihost training does). Single-controller: plain ``device_put``
    per leaf. Multi-controller (``jax.process_count() > 1``): ``device_put``
    rejects non-addressable shardings, so a jitted identity with
    ``out_shardings`` places the globally-sharded state — one compile per
    tree structure, OFF the request path (warmup/swap time)."""
    if jax.process_count() > 1:
        return jax.jit(lambda s: s, out_shardings=shardings)(tree)
    return jax.tree.map(jax.device_put, tree, shardings)


# internal alias kept for existing callers/tests
_place = place_tree


def shard_hdce_state(
    state: TrainState, mesh: Mesh, n_scenarios: int = 3, tensor_parallel: bool = False
) -> TrainState:
    return _place(state, hdce_state_shardings(state, mesh, n_scenarios, tensor_parallel))


def shard_hdce_vars(vars_: Any, mesh: Mesh, n_scenarios: int = 3) -> Any:
    """Place a raw HDCE variable dict (``{"params", "batch_stats"}`` as the
    eval sweep consumes it) with stacked-trunk leaves sharded over ``fed``.

    The eval-side twin of :func:`shard_hdce_state`: the sweep's
    all-hypotheses pass (`eval/sweep.py` — every sample through every
    scenario trunk, routing by predicted scenario afterwards,
    ``Test.py:167-214``) is expert-parallel once the trunk-stacked axis is
    fed-sharded — each scenario's trunk weights live on, and its hypothesis
    batch is computed by, only its own mesh slice; the routing gather is the
    single cross-slice collective XLA inserts.

    Same rule set as training placement (:func:`hdce_state_shardings`
    tree-maps over any pytree), so train- and eval-time layouts cannot
    drift.
    """
    return _place(vars_, hdce_state_shardings(vars_, mesh, n_scenarios))
