"""Scan-fused dispatch: K train steps in ONE device program.

``lax.scan`` over a trainer's fused step with batch synthesis *inside* the
scan body — the jitted channel generator makes the whole K-step block a
single XLA program, so the host enters the loop once per K steps instead of
once per step. On the tunnelled single-chip backend the per-step dispatch
gap is comparable to the step itself (docs/ROOFLINE.md: 1.42 ms device-busy
vs 2.9 ms wall at K=1); fusing the dispatch lifted the measured end-to-end
training throughput from 800k to 966k samples/sec even though the scan pays
for data synthesis every step and the fixed-batch measurement never did.

One factory serves every trainer (HDCE, classifier, DCE); the per-trainer
makers in :mod:`qdml_tpu.train.hdce` / :mod:`qdml_tpu.train.qsc` /
:mod:`qdml_tpu.train.dce` bind their step body and batch fields here so the
dispatch machinery cannot drift between them. Equivalence to per-step
dispatch (same losses, same params, same QuantumNAT noise stream) is pinned
by ``tests/test_train.py``.
"""

from __future__ import annotations

from typing import Callable, Sequence

from functools import partial

import jax
import jax.numpy as jnp

from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch


def grid_batch_constrainer(mesh, fed: bool) -> Callable:
    """Sharding constraint for an in-scan generated grid batch: B over
    ``data`` (and optionally S over ``fed``), the same layout the per-step
    placer produces (:func:`qdml_tpu.parallel.dp.grid_batch_spec`). Inside
    jit this makes XLA partition the batch SYNTHESIS itself across the mesh —
    each device generates only its own shard, the intra-process twin of the
    multi-host per-slice generation path."""
    from jax.sharding import NamedSharding

    from qdml_tpu.parallel.dp import grid_batch_spec

    def constrain(batch: dict) -> dict:
        return {
            k: jax.lax.with_sharding_constraint(
                v, NamedSharding(mesh, grid_batch_spec(mesh, fed, v.ndim))
            )
            for k, v in batch.items()
        }

    return constrain


def make_scan_steps(
    step_fn: Callable,
    geom: ChannelGeometry,
    fields: Sequence[str],
    mesh=None,
    fed: bool = False,
    with_rng: bool = False,
) -> Callable:
    """Build the scan-fused runner for one trainer.

    ``step_fn(state, batch)`` (or ``(state, batch, rng)`` with ``with_rng``)
    is the trainer's traceable fused step; ``fields`` names the
    :func:`make_network_batch` outputs it consumes. With a (single-process)
    ``mesh`` the synthesized batch is sharding-constrained to the per-step
    placer's (fed, data) layout, so the whole scan runs SPMD.

    Returned callable: ``run(state, seed, scen, user, idx, snrs[, rngs])``
    with ``idx (K, S, U, B) i32`` per-step sample indices, ``snrs (K,) f32``
    per-step training SNRs and (``with_rng``) ``rngs (K, 2)`` pre-split
    per-step PRNG keys; returns ``(state, metrics)`` where every metric leaf
    has a leading ``(K,)`` axis — the same per-step values the K individual
    dispatches would have produced.
    """
    from qdml_tpu.utils.platform import donation_argnums

    constrain = grid_batch_constrainer(mesh, fed) if mesh is not None else (lambda b: b)

    def _make_batch(seed, scen, user, idx_k, snr):
        batch = make_network_batch(seed, scen, user, idx_k, snr, geom)
        return constrain({k: batch[k] for k in fields})

    if with_rng:

        @partial(jax.jit, donate_argnums=donation_argnums(0))
        def run(state, seed, scen, user, idx, snrs, rngs):
            def body(state, inp):
                idx_k, snr, rng = inp
                return step_fn(state, _make_batch(seed, scen, user, idx_k, snr), rng)

            return jax.lax.scan(body, state, (idx, snrs, rngs))

    else:

        @partial(jax.jit, donate_argnums=donation_argnums(0))
        def run(state, seed, scen, user, idx, snrs):
            def body(state, inp):
                idx_k, snr = inp
                return step_fn(state, _make_batch(seed, scen, user, idx_k, snr))

            return jax.lax.scan(body, state, (idx, snrs))

    return run


def scan_eligible(cfg, mesh, loader, logger) -> bool:
    """Whether the scan-fused dispatch path may own the data for this run.

    Shared gate for every trainer: eligible single-device, or on a
    single-process mesh whose ``data`` axis divides the batch — INCLUDING
    ``scan_steps=1``: the K=1 program is the same ``lax.scan`` body with a
    donated carry and on-device batch synthesis, so even step-per-dispatch
    training pays no host-side batch build or placement (the BENCH_r05
    all-dispatch-gap shape). ``scan_steps=0`` explicitly disables fusion
    (the legacy per-step placer path); multi-process runs (per-host slice
    generation + global assembly), non-dividing mesh batches (the placer
    runs those replicated) and ``train.checkify`` keep the per-step path too.

    Every decision — eligible or not — is emitted as a structured
    ``scan_dispatch`` record (kind/eligible/scan_steps/reason) into the run's
    JSONL, so a dispatch-bound run is diagnosable from the artifact alone;
    declines additionally log a human-readable warning."""
    k = cfg.train.scan_steps

    def decide(eligible: bool, reason: str, warn: str | None = None) -> bool:
        logger.log(kind="scan_dispatch", eligible=eligible, scan_steps=k, reason=reason)
        if warn is not None:
            logger.log(warning=warn)
        return eligible

    if k < 1:
        return decide(False, "disabled: scan_steps=0 selects the per-step placer path")
    if cfg.train.checkify:
        # the sanitizer's contract is a per-step error fetch; a K-step fused
        # program would aggregate K steps' checks into one opaque trip
        return decide(
            False,
            "checkify: per-step error fetch is the sanitizer's contract",
            warn=f"scan_steps={k} ignored: train.checkify forces per-step dispatch",
        )
    if mesh is None:
        return decide(True, "fused: single-device, synthesis inside the scan body")
    if jax.process_count() > 1:
        return decide(
            False,
            "loader shape: multi-process per-host slice generation owns the data",
            warn=f"scan_steps={k} ignored: multi-process "
            "or non-dividing batch uses the per-step placer data path",
        )
    if loader.batch_size % mesh.shape["data"] == 0:
        return decide(True, "fused: single-process mesh, data axis divides the batch")
    return decide(
        False,
        f"loader shape: batch {loader.batch_size} does not divide over "
        f"data axis {mesh.shape['data']} (placer runs it replicated)",
        warn=f"scan_steps={k} ignored: multi-process "
        "or non-dividing batch uses the per-step placer data path",
    )


def presplit_keys(rng: jax.Array, k: int) -> tuple[jax.Array, jnp.ndarray]:
    """Reproduce a per-step ``rng, sub = split(rng)`` loop as a stacked
    ``(k, 2)`` key array (so the scanned noise stream matches the per-step
    dispatch loop exactly). Returns the advanced carry key and the stack."""
    subs = []
    for _ in range(k):
        rng, sub = jax.random.split(rng)
        subs.append(sub)
    return rng, jnp.stack(subs)
