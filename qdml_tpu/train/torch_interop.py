"""Reference (PyTorch) checkpoint interop: import/export ``.pth`` state dicts.

A user of the reference repo has checkpoints saved by ``torch.save`` under
filename-encoded names (``Runner_P128_QuantumNAT_onchipQNN.py:237-266,
417-426``) in one of three dict formats, possibly with DataParallel
``module.`` prefixes (the loader quirks live in ``Test.py:23-62``). This
module converts those state dicts to/from the Flax variable trees of the
equivalent qdml_tpu models so trained weights move across frameworks in both
directions.

Reference layer naming (``Estimators_QuantumNAT_onchipQNN.py``):

- ``Conv_P128``  (:237-268): ``cnn.{0,3,6}.weight`` convs (O,I,kh,kw),
  ``cnn.{1,4,7}.*`` BatchNorms.
- ``FC_P128``    (:272-279): ``FC.weight`` (2048, 4096), ``FC.bias``.
- ``SC_P128``    (:79-101):  ``conv1/conv2`` (bias-free), ``FC``.
- ``QSC_P128``   (:107-228): ``preprocess.{0,3}`` convs (with bias),
  ``preprocess.7`` linear, ``qlayer.weights`` (L, n, 2), ``classifier``.

Layout conversions (torch NCHW / C-major flatten -> Flax NHWC / H-major
flatten): conv kernels transpose (O,I,kh,kw)->(kh,kw,I,O); every Linear that
consumes a flattened conv map needs its input axis permuted because torch
flattens (C,H,W) C-major while NHWC flattens (H,W,C) H-major.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np


# ---------------------------------------------------------------------------
# State-dict normalisation (the Test.py:23-62 quirks)
# ---------------------------------------------------------------------------


def normalize_state_dict(obj: Any, fallback_key: str | None = None) -> dict[str, np.ndarray]:
    """Accept the three reference checkpoint formats and strip ``module.``.

    Formats: ``{fallback_key: sd}``, ``{'state_dict': sd}``, or a raw state
    dict; values may be torch tensors or numpy arrays.
    """
    sd = obj
    if isinstance(obj, Mapping):
        if fallback_key is not None and fallback_key in obj and isinstance(
            obj[fallback_key], Mapping
        ):
            sd = obj[fallback_key]
        elif "state_dict" in obj and isinstance(obj["state_dict"], Mapping):
            sd = obj["state_dict"]
    out = {}
    for k, v in sd.items():
        if k.startswith("module."):
            k = k[len("module.") :]
        if hasattr(v, "detach"):  # torch tensor without importing torch
            v = v.detach().cpu().numpy()
        out[k] = np.asarray(v)
    return out


def load_pth(path: str, fallback_key: str | None = None) -> dict[str, np.ndarray]:
    """``torch.load`` a reference checkpoint file and normalise it."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=False)
    return normalize_state_dict(obj, fallback_key)


def save_pth(path: str, sd: dict[str, np.ndarray], wrap_key: str | None = None) -> None:
    """Save a state dict as a reference-loadable ``.pth``, optionally wrapped
    as ``{wrap_key: sd}`` (the reference wraps HDCE checkpoints that way,
    ``Runner...py:237-264``)."""
    import torch

    obj: dict = {k: torch.from_numpy(np.ascontiguousarray(v)) for k, v in sd.items()}
    if wrap_key is not None:
        obj = {wrap_key: obj}
    torch.save(obj, path)


# ---------------------------------------------------------------------------
# Flatten-order permutations
# ---------------------------------------------------------------------------


def _flat_perm(h: int, w: int, c: int) -> np.ndarray:
    """perm[k_nhwc] = k_torch for a flattened (C,H,W)->(H,W,C) feature map."""
    k = np.arange(h * w * c)
    hh = k // (w * c)
    ww = (k // c) % w
    cc = k % c
    return cc * (h * w) + hh * w + ww


def _linear_to_kernel(weight: np.ndarray, perm: np.ndarray | None) -> np.ndarray:
    """torch Linear weight (out, in) -> Flax Dense kernel (in, out), with an
    optional input-axis permutation for flattened conv inputs."""
    kernel = weight.T.copy()
    if perm is not None:
        kernel = kernel[perm]
    return kernel


def _kernel_to_linear(kernel: np.ndarray, perm: np.ndarray | None) -> np.ndarray:
    w = np.asarray(kernel)
    if perm is not None:
        inv = np.empty_like(perm)
        inv[perm] = np.arange(len(perm))
        w = w[inv]
    return w.T.copy()


def _conv_to_flax(weight: np.ndarray) -> np.ndarray:
    return np.transpose(weight, (2, 3, 1, 0)).copy()  # (O,I,kh,kw)->(kh,kw,I,O)


def _conv_to_torch(kernel: np.ndarray) -> np.ndarray:
    return np.transpose(np.asarray(kernel), (3, 2, 0, 1)).copy()


# ---------------------------------------------------------------------------
# Conv_P128 trunk  (cnn.{0,3,6} convs + cnn.{1,4,7} BNs)
# ---------------------------------------------------------------------------


def import_conv_trunk(sd: dict[str, np.ndarray]) -> tuple[dict, dict]:
    """Reference ``Conv_P128`` state dict -> (params, batch_stats) matching
    :class:`qdml_tpu.models.cnn.ConvP128`."""
    params: dict = {}
    stats: dict = {}
    for i, idx in enumerate((0, 3, 6)):
        block = f"ConvBlock_{i}"
        params[block] = {
            "Conv_0": {"kernel": _conv_to_flax(sd[f"cnn.{idx}.weight"])},
            "BatchNorm_0": {
                "scale": sd[f"cnn.{idx + 1}.weight"].copy(),
                "bias": sd[f"cnn.{idx + 1}.bias"].copy(),
            },
        }
        stats[block] = {
            "BatchNorm_0": {
                "mean": sd[f"cnn.{idx + 1}.running_mean"].copy(),
                "var": sd[f"cnn.{idx + 1}.running_var"].copy(),
            }
        }
    return params, stats


def export_conv_trunk(params: dict, stats: dict) -> dict[str, np.ndarray]:
    sd: dict[str, np.ndarray] = {}
    for i, idx in enumerate((0, 3, 6)):
        block_p = params[f"ConvBlock_{i}"]
        block_s = stats[f"ConvBlock_{i}"]
        sd[f"cnn.{idx}.weight"] = _conv_to_torch(block_p["Conv_0"]["kernel"])
        sd[f"cnn.{idx + 1}.weight"] = np.asarray(block_p["BatchNorm_0"]["scale"]).copy()
        sd[f"cnn.{idx + 1}.bias"] = np.asarray(block_p["BatchNorm_0"]["bias"]).copy()
        sd[f"cnn.{idx + 1}.running_mean"] = np.asarray(
            block_s["BatchNorm_0"]["mean"]
        ).copy()
        sd[f"cnn.{idx + 1}.running_var"] = np.asarray(block_s["BatchNorm_0"]["var"]).copy()
        sd[f"cnn.{idx + 1}.num_batches_tracked"] = np.asarray(0, np.int64)
    return sd


# ---------------------------------------------------------------------------
# HDCE  (3 Conv_P128 state dicts + 1 FC_P128 state dict <-> stacked variables)
# ---------------------------------------------------------------------------

_TRUNK_HW = (16, 8)


def import_hdce(
    conv_sds: list[dict[str, np.ndarray]], fc_sd: dict[str, np.ndarray]
) -> dict:
    """Reference per-scenario ``Conv{0,1,2}_*`` + shared ``Linear_*`` dicts ->
    ``{"params": ..., "batch_stats": ...}`` for :class:`qdml_tpu.train.hdce.HDCE`."""
    per = [import_conv_trunk(sd) for sd in conv_sds]

    def stack(trees):
        return _tree_stack([t for t in trees])

    params = {
        "StackedConvP128_0": {"VmapConvP128_0": stack([p for p, _ in per])},
        "FCP128_0": {
            "Dense_0": {
                "kernel": _linear_to_kernel(
                    fc_sd["FC.weight"], _flat_perm(*_TRUNK_HW, 32)
                ),
                "bias": fc_sd["FC.bias"].copy(),
            }
        },
    }
    batch_stats = {"StackedConvP128_0": {"VmapConvP128_0": stack([s for _, s in per])}}
    return {"params": params, "batch_stats": batch_stats}


def export_hdce(variables: dict) -> tuple[list[dict[str, np.ndarray]], dict[str, np.ndarray]]:
    """Inverse of :func:`import_hdce`: stacked Flax variables -> (3 trunk
    state dicts, 1 head state dict) in reference naming."""
    stacked_p = variables["params"]["StackedConvP128_0"]["VmapConvP128_0"]
    stacked_s = variables["batch_stats"]["StackedConvP128_0"]["VmapConvP128_0"]
    n_scen = np.asarray(
        stacked_p["ConvBlock_0"]["Conv_0"]["kernel"]
    ).shape[0]
    conv_sds = []
    for s in range(n_scen):
        p = _tree_index(stacked_p, s)
        st = _tree_index(stacked_s, s)
        conv_sds.append(export_conv_trunk(p, st))
    dense = variables["params"]["FCP128_0"]["Dense_0"]
    fc_sd = {
        "FC.weight": _kernel_to_linear(dense["kernel"], _flat_perm(*_TRUNK_HW, 32)),
        "FC.bias": np.asarray(dense["bias"]).copy(),
    }
    return conv_sds, fc_sd


# ---------------------------------------------------------------------------
# SC_P128  (conv1, conv2, FC)
# ---------------------------------------------------------------------------

_SC_HW = (4, 2)  # feature map after two maxpools of (16, 8)


def import_sc(sd: dict[str, np.ndarray]) -> dict:
    """Reference ``SC_P128`` state dict -> params for :class:`SCP128`."""
    return {
        "Conv_0": {"kernel": _conv_to_flax(sd["conv1.weight"])},
        "Conv_1": {"kernel": _conv_to_flax(sd["conv2.weight"])},
        "Dense_0": {
            "kernel": _linear_to_kernel(sd["FC.weight"], _flat_perm(*_SC_HW, 32)),
            "bias": sd["FC.bias"].copy(),
        },
    }


def export_sc(params: dict) -> dict[str, np.ndarray]:
    return {
        "conv1.weight": _conv_to_torch(params["Conv_0"]["kernel"]),
        "conv2.weight": _conv_to_torch(params["Conv_1"]["kernel"]),
        "FC.weight": _kernel_to_linear(
            params["Dense_0"]["kernel"], _flat_perm(*_SC_HW, 32)
        ),
        "FC.bias": np.asarray(params["Dense_0"]["bias"]).copy(),
    }


# ---------------------------------------------------------------------------
# QSC_P128  (preprocess.{0,3,7}, qlayer.weights, classifier)
# ---------------------------------------------------------------------------


def import_qsc(sd: dict[str, np.ndarray]) -> dict:
    """Reference ``QSC_P128`` state dict -> params for :class:`QSCP128`."""
    return {
        "QSCPreprocess_0": {
            "Conv_0": {
                "kernel": _conv_to_flax(sd["preprocess.0.weight"]),
                "bias": sd["preprocess.0.bias"].copy(),
            },
            "Conv_1": {
                "kernel": _conv_to_flax(sd["preprocess.3.weight"]),
                "bias": sd["preprocess.3.bias"].copy(),
            },
            "Dense_0": {
                "kernel": _linear_to_kernel(
                    sd["preprocess.7.weight"], _flat_perm(*_SC_HW, 32)
                ),
                "bias": sd["preprocess.7.bias"].copy(),
            },
        },
        "qweights": sd["qlayer.weights"].copy(),
        "Dense_0": {
            "kernel": sd["classifier.weight"].T.copy(),
            "bias": sd["classifier.bias"].copy(),
        },
    }


def export_qsc(params: dict) -> dict[str, np.ndarray]:
    pre = params["QSCPreprocess_0"]
    return {
        "preprocess.0.weight": _conv_to_torch(pre["Conv_0"]["kernel"]),
        "preprocess.0.bias": np.asarray(pre["Conv_0"]["bias"]).copy(),
        "preprocess.3.weight": _conv_to_torch(pre["Conv_1"]["kernel"]),
        "preprocess.3.bias": np.asarray(pre["Conv_1"]["bias"]).copy(),
        "preprocess.7.weight": _kernel_to_linear(
            pre["Dense_0"]["kernel"], _flat_perm(*_SC_HW, 32)
        ),
        "preprocess.7.bias": np.asarray(pre["Dense_0"]["bias"]).copy(),
        "qlayer.weights": np.asarray(params["qweights"]).copy(),
        "classifier.weight": np.asarray(params["Dense_0"]["kernel"]).T.copy(),
        "classifier.bias": np.asarray(params["Dense_0"]["bias"]).copy(),
    }


# ---------------------------------------------------------------------------
# Reference checkpoint-file naming + high-level conversion
# ---------------------------------------------------------------------------


def reference_ckpt_name(role: str, batch_size: int, snr_db: int, tag: str) -> str:
    """Filename-encoded reference checkpoint scheme
    (``Runner...py:237-266, 417-426``): role in {Conv0, Conv1, Conv2, Linear,
    QSC_OPT}; tag in {'best', 'epochN'}. The SC classifier uses a different
    pattern — see :func:`reference_sc_ckpt_name` (``Test.py:71-72``)."""
    return f"{role}_{batch_size}_{snr_db}dB_{tag}_DML.pth"


def reference_sc_ckpt_name(batch_size: int, snr_db: int, tag: str) -> str:
    """Reference SC classifier filename: ``{bs}_{snr}dB_{tag}_DML_SC.pth``
    (``Test.py:71-72`` loads ``..._epoch99_DML_SC.pth`` with key 'cnn')."""
    return f"{batch_size}_{snr_db}dB_{tag}_DML_SC.pth"


def import_reference_dir(
    src_dir: str, batch_size: int = 256, snr_db: int = 10, tag: str = "best"
) -> dict[str, dict]:
    """Load every reference checkpoint present in ``src_dir`` -> Flax trees.

    Returns a dict with any of "hdce", "sc", "qsc" keys (missing files are
    skipped, mirroring the eval harness's graceful fallback, ``Test.py:81-86``).

    Wrapper keys follow what the reference actually writes/reads: Conv trunks
    are saved as ``{'conv': sd}`` and the head as ``{'linear': sd}``
    (``Runner...py:237-264``; ``Test.py:100-106``); the SC classifier loads
    with key ``'cnn'`` (``Test.py:73``); the QSC is saved raw
    (``Runner...py:417-426``) but Test.py also probes a stale
    ``QSC_optimized_best.pth`` wrapped as ``{'model_state_dict': sd}``
    (``Test.py:79-84``) — both are accepted here.
    """
    import os

    out: dict[str, dict] = {}
    convs = []
    for i in range(3):
        p = os.path.join(src_dir, reference_ckpt_name(f"Conv{i}", batch_size, snr_db, tag))
        if os.path.exists(p):
            convs.append(load_pth(p, fallback_key="conv"))
    fc_path = os.path.join(src_dir, reference_ckpt_name("Linear", batch_size, snr_db, tag))
    if len(convs) == 3 and os.path.exists(fc_path):
        out["hdce"] = import_hdce(convs, load_pth(fc_path, fallback_key="linear"))
    sc_paths = [
        os.path.join(src_dir, reference_sc_ckpt_name(batch_size, snr_db, tag)),
        os.path.join(src_dir, reference_sc_ckpt_name(batch_size, snr_db, "epoch99")),
        os.path.join(src_dir, reference_ckpt_name("SC", batch_size, snr_db, tag)),
    ]
    for sc_path in sc_paths:
        if os.path.exists(sc_path):
            out["sc"] = {"params": import_sc(load_pth(sc_path, fallback_key="cnn"))}
            break
    qsc_paths = [
        (os.path.join(src_dir, reference_ckpt_name("QSC_OPT", batch_size, snr_db, tag)), None),
        (os.path.join(src_dir, "QSC_optimized_best.pth"), "model_state_dict"),
    ]
    for qsc_path, key in qsc_paths:
        if os.path.exists(qsc_path):
            out["qsc"] = {"params": import_qsc(load_pth(qsc_path, fallback_key=key))}
            break
    return out


def export_reference_dir(
    out_dir: str,
    hdce_vars: dict | None = None,
    sc_params: dict | None = None,
    qsc_params: dict | None = None,
    batch_size: int = 256,
    snr_db: int = 10,
    tag: str = "best",
) -> list[str]:
    """Write ``.pth`` files the reference's own loaders accept: HDCE parts
    wrapped ``{'conv'|'linear': sd}`` (``Runner...py:237-264``), the SC under
    the ``..._DML_SC.pth`` scheme with key ``'cnn'`` (``Test.py:71-73``), and
    the QSC both raw under ``QSC_OPT_*`` (``Runner...py:417-426``) and as the
    ``QSC_optimized_best.pth``/``model_state_dict`` form Test.py probes
    (``Test.py:79-84``)."""
    import os

    os.makedirs(out_dir, exist_ok=True)
    written = []

    def put(filename, sd, wrap_key=None):
        path = os.path.join(out_dir, filename)
        save_pth(path, sd, wrap_key)
        written.append(path)

    if hdce_vars is not None:
        conv_sds, fc_sd = export_hdce(hdce_vars)
        for i, sd in enumerate(conv_sds):
            put(reference_ckpt_name(f"Conv{i}", batch_size, snr_db, tag), sd, "conv")
        put(reference_ckpt_name("Linear", batch_size, snr_db, tag), fc_sd, "linear")
    if sc_params is not None:
        put(reference_sc_ckpt_name(batch_size, snr_db, tag), export_sc(sc_params), "cnn")
    if qsc_params is not None:
        qsc_sd = export_qsc(qsc_params)
        put(reference_ckpt_name("QSC_OPT", batch_size, snr_db, tag), qsc_sd)
        put("QSC_optimized_best.pth", qsc_sd, "model_state_dict")
    return written


# ---------------------------------------------------------------------------
# small tree helpers (stack/index a leading scenario axis)
# ---------------------------------------------------------------------------


def _tree_stack(trees: list) -> Any:
    if isinstance(trees[0], Mapping):
        return {k: _tree_stack([t[k] for t in trees]) for k in trees[0]}
    return np.stack([np.asarray(t) for t in trees])


def _tree_index(tree: Any, i: int) -> Any:
    if isinstance(tree, Mapping):
        return {k: _tree_index(v, i) for k, v in tree.items()}
    return np.asarray(tree)[i]
