"""DCE training: the monolithic (non-hierarchical) direct channel estimator.

The reference defines ``DCE_P128`` (``Estimators_QuantumNAT_onchipQNN.py:40-75``)
— a single Conv trunk + linear head with no per-scenario branching — as the
baseline the hierarchical HDCE design improves on. Its snapshot ships no
training loop for it (the shipped runner trains only Conv/FC and QSC), so this
module provides one with the same hyperparameters as the HDCE loop
(``Runner_P128_QuantumNAT_onchipQNN.py:20-46``): one jitted step over the
flattened 3x3 grid batch, Adam + halving LR schedule, best/last checkpoints.
"""

from __future__ import annotations

from typing import Callable

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.models.cnn import DCEP128, activation_dtype
from qdml_tpu.models.losses import nmse_loss
from qdml_tpu.train.checkpoint import save_checkpoint, save_train_state, try_resume
from qdml_tpu.train.optim import get_optimizer
from qdml_tpu.telemetry import FlightRecorder, StepClock, probe_tree, span
from qdml_tpu.telemetry.cost import maybe_emit_cost
from qdml_tpu.train.state import TrainState
from qdml_tpu.utils.metrics import MetricsLogger, nmse_db


def _dce_step(
    model: DCEP128, state: TrainState, batch: dict, probes: bool = True
) -> tuple[TrainState, dict]:
    """One DCE grid step (traceable; jitted by the makers below).
    ``probes=False`` compiles the numerics probe out (static flag)."""
    x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
    label = batch["h_label"].reshape(x.shape[0], -1)
    perf = batch["h_perf"].reshape(x.shape[0], -1)

    def loss_fn(params):
        pred, upd = model.apply(
            {"params": params, "batch_stats": state.batch_stats},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        loss = nmse_loss(pred, label)
        return loss, (upd["batch_stats"], nmse_loss(pred, perf))

    (loss, (new_stats, loss_perf)), grads = jax.value_and_grad(
        loss_fn, has_aux=True
    )(state.params)
    # optax applied explicitly (flax's apply_gradients verbatim) so the
    # numerics probe sees the actual per-step UPDATES, not a params diff
    updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
    m = {"loss": loss, "loss_perf": loss_perf}
    if probes:
        m["probe"] = probe_tree(grads, state.params, updates)
    state = state.replace(
        step=state.step + 1,
        params=optax.apply_updates(state.params, updates),
        opt_state=new_opt_state,
        batch_stats=new_stats,
    )
    return state, m


def make_dce_train_step(
    model: DCEP128, probes: bool = True, checkify_errors: bool = False
) -> Callable:
    from qdml_tpu.utils.platform import donation_argnums

    if checkify_errors:
        # runtime sanitizer (train.checkify): same signature/returns, with
        # the checkify error riding the metrics dict for the flight recorder
        from qdml_tpu.telemetry.sanitizer import checkify_step

        return checkify_step(
            partial(_dce_step, model, probes=probes),
            donate=donation_argnums(0),
        )

    @partial(jax.jit, donate_argnums=donation_argnums(0))
    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        return _dce_step(model, state, batch, probes=probes)

    return step


def make_dce_scan_steps(
    model: DCEP128, geom: ChannelGeometry, probes: bool = True
) -> Callable:
    """K DCE train steps in ONE device dispatch via the shared scan machinery
    (:func:`qdml_tpu.train.scan.make_scan_steps`)."""
    from qdml_tpu.train.scan import make_scan_steps

    return make_scan_steps(
        partial(_dce_step, model, probes=probes), geom, ("yp_img", "h_label", "h_perf")
    )


def make_dce_eval_step(model: DCEP128) -> Callable:
    @jax.jit
    def step(state: TrainState, batch: dict) -> dict:
        x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
        label = batch["h_label"].reshape(x.shape[0], -1)
        pred = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats}, x, train=False
        )
        return {"err": jnp.sum((pred - label) ** 2), "pow": jnp.sum(label**2)}

    return step


def init_dce_state(cfg: ExperimentConfig, steps_per_epoch: int):
    model = DCEP128(
        features=cfg.model.features,
        out_dim=cfg.h_out_dim,
        dtype=activation_dtype(cfg.model.dtype),
        conv_impl=cfg.model.conv_impl,
    )
    dummy = jnp.zeros((2, *cfg.image_hw, 2), jnp.float32)
    variables = model.init(jax.random.PRNGKey(cfg.train.seed), dummy, train=False)
    tx = get_optimizer(cfg.train, steps_per_epoch)
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables["batch_stats"],
    )
    return model, state


def train_dce(
    cfg: ExperimentConfig,
    logger: MetricsLogger | None = None,
    workdir: str | None = None,
) -> tuple[TrainState, dict]:
    """Train the monolithic DCE baseline over the same DML data grid."""
    logger = logger or MetricsLogger(echo=False)
    geom = ChannelGeometry.from_config(cfg.data)
    train_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    val_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "val", geom)
    model, state = init_dce_state(cfg, train_loader.steps_per_epoch)
    probes_on = cfg.train.probe_every > 0  # 0 compiles the probes out
    train_step = make_dce_train_step(
        model, probes=probes_on, checkify_errors=cfg.train.checkify
    )
    eval_step = make_dce_eval_step(model)

    start_epoch = 0
    best = float("inf")
    if cfg.train.resume:
        state, start_epoch, rmeta = try_resume(workdir, "dce_resume", state)
        best = float(rmeta.get("best", best))

    # Scan-fused dispatch, same machinery as train_hdce — the DEFAULT, K=1
    # included (this trainer is single-device, so eligibility reduces to
    # scan_steps >= 1 without checkify; 0 opts out).
    from qdml_tpu.train.scan import scan_eligible

    scan_run = None
    if scan_eligible(cfg, None, train_loader, logger):
        scan_run = make_dce_scan_steps(model, geom, probes=probes_on)

    clock = StepClock("dce_train")
    # Numerics flight recorder + one lowered-cost record (docs/FLIGHTREC.md)
    rec = FlightRecorder("dce_train", cfg, workdir=workdir)
    rec.note_good(state.params)
    cost_done = False
    history: dict[str, list] = {"train_loss": [], "val_nmse": []}
    for epoch in range(start_epoch, cfg.train.n_epochs):
        tot, n = 0.0, 0
        with span("train_epoch", epoch=epoch):
            if scan_run is not None:
                seed = jnp.uint32(cfg.data.seed)
                scen, user = train_loader.grid_coords
                tot_dev = None  # on-device loss accumulator, fetched once per epoch
                for idx, snrs in train_loader.epoch_chunks(epoch, cfg.train.scan_steps):
                    if not cost_done:
                        maybe_emit_cost(
                            "dce_train_scan", scan_run, state, seed, scen,
                            user, idx, snrs, scan_steps=cfg.train.scan_steps,
                        )
                        cost_done = True
                    fetch = rec.should_fetch()
                    losses = None
                    with clock.step() as st:
                        state, ms = scan_run(state, seed, scen, user, idx, snrs)
                        if fetch:
                            # sole steady-state sync, on the probe cadence
                            # only (zero with probe_every=0) — see train_hdce
                            st.transfer()
                            losses = np.asarray(jax.device_get(ms["loss"]))
                    chunk = jnp.sum(ms["loss"])
                    tot_dev = chunk if tot_dev is None else tot_dev + chunk
                    rec.on_step(
                        epoch, ms, loss=losses, params=state.params,
                        batch_info={"dispatch": "scan", "idx": idx, "snrs": snrs},
                    )
                    n += idx.shape[0]
                if tot_dev is not None:
                    tot = float(jax.device_get(tot_dev))
                    # epoch-aggregate watchdog check — see train_hdce
                    rec.on_epoch_loss(epoch, tot)
            else:
                for batch in train_loader.epoch(epoch):
                    if not cost_done:
                        maybe_emit_cost("dce_train_step", train_step, state, batch)
                        cost_done = True
                    with clock.step() as st:
                        state, m = train_step(state, batch)
                        st.transfer()
                        loss = float(m["loss"])
                        tot = tot + loss
                    rec.on_step(
                        epoch, m, loss=loss, params=state.params,
                        batch_info={"dispatch": "step", "step_in_epoch": n},
                    )
                    n += 1
        clock.epoch_end(epoch=epoch)
        train_loss = tot / max(n, 1)

        sums = {"err": 0.0, "pow": 0.0}
        with span("val_epoch", epoch=epoch):
            for batch in val_loader.epoch(epoch, shuffle=False):
                out = eval_step(state, batch)
                for k in sums:
                    sums[k] += float(out[k])
        val_nmse = sums["err"] / max(sums["pow"], 1e-30)
        history["train_loss"].append(train_loss)
        history["val_nmse"].append(val_nmse)
        logger.log(
            epoch=epoch, train_loss=train_loss, val_nmse=val_nmse, val_nmse_db=nmse_db(val_nmse)
        )
        if workdir is not None:
            meta = {"epoch": epoch, "val_nmse": val_nmse, "name": cfg.name}
            if val_nmse < best:
                best = val_nmse
                payload = {"params": state.params, "batch_stats": state.batch_stats}
                save_checkpoint(workdir, "dce_best", payload, meta)
            save_train_state(workdir, "dce_resume", state, {**meta, "best": best})
    if workdir is not None:
        save_checkpoint(
            workdir,
            "dce_last",
            {"params": state.params, "batch_stats": state.batch_stats},
            {"epoch": cfg.train.n_epochs - 1, "name": cfg.name},
        )
    return state, history
