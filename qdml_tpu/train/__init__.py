from qdml_tpu.train.checkpoint import (  # noqa: F401
    has_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from qdml_tpu.train.dce import (  # noqa: F401
    init_dce_state,
    make_dce_eval_step,
    make_dce_train_step,
    train_dce,
)
from qdml_tpu.train.hdce import (  # noqa: F401
    HDCE,
    cell_nmse,
    init_hdce_state,
    make_hdce_eval_step,
    make_hdce_train_step,
    train_hdce,
)
from qdml_tpu.train.optim import get_optimizer, lr_schedule  # noqa: F401
from qdml_tpu.train.qsc import (  # noqa: F401
    build_classifier,
    init_sc_state,
    make_sc_eval_step,
    make_sc_train_step,
    train_classifier,
)
from qdml_tpu.train.state import TrainState  # noqa: F401
