"""Noise-aware training sweep: a vmapped ensemble over QuantumNAT noise levels.

BASELINE.json config 5 ("noise-aware training sweep batched over TPU hosts").
The reference can only explore noise levels by re-running its trainer with a
different ``noise_level`` kwarg (``Estimators_QuantumNAT_onchipQNN.py:118``) —
one sequential GPU run per level. TPU-native: every noise level is an ensemble
member with its own (params, optimizer state, PRNG stream); ONE jitted,
``vmap``-ed train step advances all members simultaneously — the member axis
batches the CNN convs and the circuit matmuls onto the MXU. Under a mesh the
stacked ensemble is replicated and the BATCH shards over ``data`` (the same
placement policy as the other trainers; the per-member gradients all-reduce
alongside each other in one fused collective).

QuantumNAT semantics per member (SURVEY.md §3.4): the loss/gradient is taken
at ``qweights + sigma * N(0,1)`` (noisy point) while optimizer state and
params stay clean — :func:`qdml_tpu.ops.quantumnat.perturb` applied to the
circuit-weight leaves only.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.models.losses import accuracy, nll_loss
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.ops.quantumnat import perturb
from qdml_tpu.train.checkpoint import (
    has_checkpoint,
    restore_checkpoint,
    save_checkpoint,
)
from qdml_tpu.telemetry import FlightRecorder, StepClock, probe_tree, span
from qdml_tpu.telemetry.cost import maybe_emit_cost
from qdml_tpu.train.optim import get_optimizer
from qdml_tpu.utils.metrics import MetricsLogger


def _is_qweight(path, _leaf) -> bool:
    return any("qweights" in str(getattr(p, "key", p)) for p in path)


def build_sweep_model(cfg: ExperimentConfig) -> QSCP128:
    # Noise is injected externally (per-member sigma is a traced value; the
    # module attribute would be static), so quantumnat is OFF in the module.
    return QSCP128(
        n_qubits=cfg.quantum.n_qubits,
        n_layers=cfg.quantum.n_layers,
        n_classes=cfg.quantum.n_classes,
        use_quantumnat=False,
        backend=cfg.quantum.backend,
        impl=cfg.quantum.impl,
        mps_chi=cfg.quantum.mps_chi,
        input_norm=cfg.quantum.input_norm,
    )


def init_sweep(cfg: ExperimentConfig, noise_levels: Sequence[float], steps_per_epoch: int):
    """Stacked per-member params + optimizer states (leading ensemble axis)."""
    import dataclasses

    model = build_sweep_model(cfg)
    # Same optimizer semantics as the single-model QSC trainer: AdamW
    # (reference ``Runner...py:320``) plus the gradient-pruning transform when
    # the quantum config requests it.
    train_cfg = dataclasses.replace(cfg.train, optimizer="adamw")
    tx = get_optimizer(train_cfg, steps_per_epoch, cfg.quantum)
    dummy = jnp.zeros((2, *cfg.image_hw, 2), jnp.float32)

    def init_one(key):
        params = model.init(key, dummy, train=False)["params"]
        return params, tx.init(params)

    keys = jax.random.split(jax.random.PRNGKey(cfg.train.seed), len(noise_levels))
    params, opt_state = jax.vmap(init_one)(keys)
    sigmas = jnp.asarray(list(noise_levels), jnp.float32)
    return model, tx, params, opt_state, sigmas


def _make_vstep(model: QSCP128, tx, probes: bool = True) -> Callable:
    """vmap over the ensemble of one member's QuantumNAT train step — the
    single definition both dispatch paths bind, so the noise-injection /
    optimizer logic cannot drift between them. ``probes=False`` compiles the
    numerics probe out (static flag)."""

    def member_step(params, opt_state, rng, sigma, x, labels):
        def loss_fn(p):
            noisy = perturb(p, rng, sigma, where=_is_qweight)
            log_probs = model.apply({"params": noisy}, x, train=True)
            return nll_loss(log_probs, labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        # metrics dict (vmapped to per-member leaves): loss + numerics probe
        m = {"loss": loss}
        if probes:
            m["probe"] = probe_tree(grads, params, updates)
        params = optax.apply_updates(params, updates)
        return params, opt_state, m

    return jax.vmap(member_step, in_axes=(0, 0, 0, 0, None, None))


def make_sweep_train_step(
    model: QSCP128, tx, probes: bool = True, checkify_errors: bool = False
) -> Callable:
    """jit(vmap(member step)): (E-stacked params/opt/rng/sigma, shared batch)
    -> ``(params, opt_state, metrics)`` with per-member ``loss``/``probe``
    leaves in the metrics dict. ``checkify_errors`` wraps the whole vmapped
    ensemble step in the runtime sanitizer — ANY member tripping a check
    trips the error (the same any-member-poisons-the-dispatch semantics as
    the watchdog)."""
    vstep = _make_vstep(model, tx, probes=probes)

    from functools import partial

    from qdml_tpu.utils.platform import donation_argnums

    def step_fn(params, opt_state, rngs, sigmas, batch):
        x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
        labels = batch["indicator"].reshape(-1)
        return vstep(params, opt_state, rngs, sigmas, x, labels)

    if checkify_errors:
        from qdml_tpu.telemetry.sanitizer import checkify_step

        return checkify_step(step_fn, donate=donation_argnums(0, 1))

    return partial(jax.jit, donate_argnums=donation_argnums(0, 1))(step_fn)


def make_sweep_scan_steps(
    model: QSCP128, tx, sigmas, geom, mesh=None, probes: bool = True
) -> Callable:
    """K ensemble train steps in ONE device dispatch via the shared scan
    machinery (:func:`qdml_tpu.train.scan.make_scan_steps`). The scan carry
    is the ``(params, opt_state)`` stacked-ensemble pair; ``rngs`` has shape
    ``(K, n_members, 2)`` — one pre-split key per (step, member), matching
    the per-step dispatch loop's noise stream."""
    from qdml_tpu.train.scan import make_scan_steps

    vstep = _make_vstep(model, tx, probes=probes)

    def step_body(state, batch, rngs):
        params, opt_state = state
        x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
        labels = batch["indicator"].reshape(-1)
        params, opt_state, ms = vstep(params, opt_state, rngs, sigmas, x, labels)
        return (params, opt_state), ms

    return make_scan_steps(
        step_body, geom, ("yp_img", "indicator"), mesh=mesh, with_rng=True
    )


def make_sweep_eval_step(model: QSCP128) -> Callable:
    def member_eval(params, x, labels):
        log_probs = model.apply({"params": params}, x, train=False)
        return nll_loss(log_probs, labels), accuracy(log_probs, labels)

    veval = jax.vmap(member_eval, in_axes=(0, None, None))

    @jax.jit
    def step(params, batch):
        x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
        labels = batch["indicator"].reshape(-1)
        return veval(params, x, labels)

    return step


def train_nat_sweep(
    cfg: ExperimentConfig,
    noise_levels: Sequence[float] = (0.0, 0.01, 0.05, 0.1),
    logger: MetricsLogger | None = None,
    workdir: str | None = None,
):
    """Train one quantum classifier per noise level, all in one vmapped step.

    Returns ``(params_stacked, history)`` where history holds per-member
    per-epoch train loss / val loss / val accuracy arrays. Parity with the
    single-model trainers (VERDICT round 1, weak #8): resume-capable
    (``cfg.train.resume``), per-member JSONL metrics every epoch, and a
    ``nat_sweep_best`` checkpoint holding the single best member's params
    (loadable into one :class:`QSCP128`) alongside the stacked
    ``nat_sweep_last``/``nat_sweep_resume``.

    ``nat_sweep_member_best`` (ADVICE r3): EVERY member's best-validation
    params as one stacked tree (meta: per-member best acc + epoch), so
    ensemble studies can score best-val selections — the same rule the
    single-model seed studies use (``qsc_best``) — instead of final-epoch
    params; the last-vs-best asymmetry confounded small clean-accuracy
    comparisons across the two artifact families.
    """
    logger = logger or MetricsLogger(echo=False)
    geom = ChannelGeometry.from_config(cfg.data)
    train_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    val_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "val", geom)
    model, tx, params, opt_state, sigmas = init_sweep(
        cfg, noise_levels, train_loader.steps_per_epoch
    )
    # Autotuned circuit dispatch, same contract as train_classifier: tune at
    # this run's flattened-grid circuit batch BEFORE the vmapped step traces
    # (the ensemble axis batches the same per-member shape; the table keys on
    # the member shape the dispatcher resolves at trace time).
    from qdml_tpu.quantum import autotune

    at_entry = autotune.prewarm(
        cfg, batch=cfg.data.n_scenarios * cfg.data.n_users * cfg.train.batch_size
    )
    if at_entry is not None:
        logger.log(
            kind="quantum_autotune",
            key=at_entry["key"],
            impl=at_entry["best_train"],
            impl_infer=at_entry["best_fwd"],
            candidates=at_entry["candidates"],
        )
    probes_on = cfg.train.probe_every > 0  # 0 compiles the probes out
    train_step = make_sweep_train_step(
        model, tx, probes=probes_on, checkify_errors=cfg.train.checkify
    )
    eval_step = make_sweep_eval_step(model)
    n_members = len(noise_levels)
    # Same architecture-fact record the QSC trainer writes (train/qsc.py):
    # a member extracted from the stacked checkpoint must be rebuildable
    # without guessing the training config (input_norm has no params, so a
    # mismatch at eval would otherwise be silent).
    quantum_meta = {
        "n_qubits": cfg.quantum.n_qubits,
        "n_layers": cfg.quantum.n_layers,
        "n_classes": cfg.quantum.n_classes,
        "input_norm": cfg.quantum.input_norm,
    }

    start_epoch = 0
    best_acc = -1.0
    if cfg.train.resume and workdir is not None and has_checkpoint(workdir, "nat_sweep_resume"):
        restored, rmeta = restore_checkpoint(
            workdir, "nat_sweep_resume", {"params": params, "opt_state": opt_state}
        )
        stored_levels = rmeta.get("noise_levels")
        if stored_levels is not None and list(stored_levels) != list(map(float, noise_levels)):
            raise ValueError(
                f"resume noise_levels mismatch: checkpoint has {stored_levels}, "
                f"requested {list(map(float, noise_levels))} — members would keep "
                "training under the wrong sigma"
            )
        params, opt_state = restored["params"], restored["opt_state"]
        start_epoch = int(rmeta.get("epoch", -1)) + 1
        best_acc = float(rmeta.get("best_acc", best_acc))

    # Per-member best-validation tracking (stacked, like the params). COPY,
    # not alias: the train step donates its params argument on accelerator
    # backends, so an aliased member_best would reference deleted buffers at
    # the first best-update.
    member_best = jax.tree.map(jnp.copy, params)
    member_best_acc = np.full(n_members, -1.0)
    member_best_epoch = np.full(n_members, -1)
    # The epoch best-val selection STARTS considering. Normally 0; resuming a
    # workdir trained before member-best tracking existed (nat_sweep_resume
    # present, nat_sweep_member_best absent) makes it start_epoch — the
    # pre-resume epochs were never scored, so the meta must say the selection
    # window excludes them instead of silently reporting post-resume maxima
    # as all-run bests (ADVICE r4).
    member_best_from_epoch = start_epoch
    # Only trust a member_best checkpoint when it belongs to the run being
    # resumed (start_epoch > 0 — i.e. nat_sweep_resume was restored, which
    # already validated noise_levels) AND its own levels match: a stale
    # member_best from an abandoned workdir would otherwise suppress
    # `improved` with a previous run's accs and ship that run's params.
    if start_epoch > 0 and has_checkpoint(workdir, "nat_sweep_member_best"):
        restored_mb, mb_meta = restore_checkpoint(
            workdir, "nat_sweep_member_best", {"params": params}
        )
        mb_levels = mb_meta.get("noise_levels")
        if mb_levels is not None and list(mb_levels) != list(map(float, noise_levels)):
            raise ValueError(
                f"nat_sweep_member_best noise_levels mismatch: checkpoint has "
                f"{mb_levels}, requested {list(map(float, noise_levels))}"
            )
        member_best = restored_mb["params"]
        member_best_acc = np.asarray(mb_meta.get("member_best_acc", member_best_acc), float)
        member_best_epoch = np.asarray(
            mb_meta.get("member_best_epoch", member_best_epoch), int
        )
        # a restored tracker inherits its own window; a checkpoint that
        # predates window recording could itself have been started mid-run
        # (legacy resume under the old code), so its window start is
        # UNKNOWN — record -1 rather than claiming full coverage
        member_best_from_epoch = int(mb_meta.get("member_best_from_epoch", -1))

    # Multi-device: replicate the stacked ensemble, shard batches over the
    # data axis (same placement policy as the other trainers).
    from qdml_tpu.parallel.dp import replicate
    from qdml_tpu.parallel.mesh import training_mesh
    from qdml_tpu.parallel.multihost import make_grid_placer

    mesh = training_mesh(cfg)
    if mesh is not None:
        # member_best included: a fresh copy shares params' placement, but a
        # RESTORED one is committed to device 0 by orbax and would clash
        # with the replicated params inside the best-update where()
        params, opt_state, member_best = replicate(
            (params, opt_state, member_best), mesh
        )
    place_train = make_grid_placer(train_loader, mesh)
    place_val = make_grid_placer(val_loader, mesh)

    # Per-epoch noise keys derived from (seed, epoch): a resumed epoch draws
    # exactly the noise an uninterrupted run would have drawn, so resume is
    # bit-reproducible (tests/test_nat_sweep.py::test_train_nat_sweep_resume).
    base_rng = jax.random.PRNGKey(cfg.train.seed + 101)

    # Scan-fused dispatch: same machinery/eligibility as the other trainers.
    from qdml_tpu.train.scan import presplit_keys, scan_eligible

    scan_run = None
    if scan_eligible(cfg, mesh, train_loader, logger):
        scan_run = make_sweep_scan_steps(
            model, tx, sigmas, geom, mesh=mesh, probes=probes_on
        )

    clock = StepClock("nat_sweep_train")
    # Numerics flight recorder over the stacked ensemble: probes/losses are
    # per-member vectors, and ANY nonfinite member trips the watchdog (a
    # spiked-sigma member poisons its slice of every vmapped dispatch).
    rec = FlightRecorder("nat_sweep_train", cfg, workdir=workdir)
    rec.note_good(params)
    cost_done = False
    history = {"train_loss": [], "val_loss": [], "val_acc": []}
    for epoch in range(start_epoch, cfg.train.n_epochs):
        rng = jax.random.fold_in(base_rng, epoch)
        tot = np.zeros(n_members)
        n = 0
        with span("train_epoch", epoch=epoch):
            if scan_run is not None:
                seed = jnp.uint32(cfg.data.seed)
                scen, user = train_loader.grid_coords
                tot_dev = None  # on-device (E,) loss accumulator, one epoch fetch
                for idx, snrs in train_loader.epoch_chunks(epoch, cfg.train.scan_steps):
                    rng, subs = presplit_keys(rng, idx.shape[0])
                    member_keys = jax.vmap(lambda s: jax.random.split(s, n_members))(subs)
                    if not cost_done:
                        maybe_emit_cost(
                            "nat_sweep_train_scan", scan_run, (params, opt_state),
                            seed, scen, user, idx, snrs, member_keys,
                            scan_steps=cfg.train.scan_steps, n_members=n_members,
                        )
                        cost_done = True
                    fetch = rec.should_fetch()
                    losses = None
                    with clock.step() as st:
                        (params, opt_state), ms = scan_run(
                            (params, opt_state), seed, scen, user, idx, snrs, member_keys
                        )
                        if fetch:
                            # sole steady-state sync, on the probe cadence
                            # only (zero with probe_every=0) — see train_hdce
                            st.transfer()
                            losses = np.asarray(jax.device_get(ms["loss"]))
                    chunk = jnp.sum(ms["loss"], axis=0)  # (K, E) -> (E,)
                    tot_dev = chunk if tot_dev is None else tot_dev + chunk
                    rec.on_step(
                        epoch, ms, loss=losses, params=params, rng=member_keys,
                        batch_info={"dispatch": "scan", "idx": idx, "snrs": snrs},
                    )
                    n += idx.shape[0]
                if tot_dev is not None:
                    tot = tot + np.asarray(jax.device_get(tot_dev))
                    # epoch-aggregate watchdog check (per-member vector: ANY
                    # diverged member trips) — see train_hdce
                    rec.on_epoch_loss(epoch, tot)
            else:
                for batch in train_loader.epoch(epoch):
                    rng, sub = jax.random.split(rng)
                    rngs = jax.random.split(sub, n_members)
                    pb = place_train(batch)
                    if not cost_done:
                        maybe_emit_cost(
                            "nat_sweep_train_step", train_step, params, opt_state,
                            rngs, sigmas, pb, n_members=n_members,
                        )
                        cost_done = True
                    with clock.step() as st:
                        params, opt_state, ms = train_step(
                            params, opt_state, rngs, sigmas, pb
                        )
                        st.transfer()
                        losses = np.asarray(jax.device_get(ms["loss"]))
                        tot += losses
                    rec.on_step(
                        epoch, ms, loss=losses, params=params, rng=rngs,
                        batch_info={"dispatch": "step", "step_in_epoch": n},
                    )
                    n += 1
        clock.epoch_end(epoch=epoch)
        train_loss = tot / max(n, 1)

        vloss = np.zeros(n_members)
        vacc = np.zeros(n_members)
        vn = 0
        with span("val_epoch", epoch=epoch):
            for batch in val_loader.epoch(epoch, shuffle=False):
                losses, accs = eval_step(params, place_val(batch))
                vloss += np.asarray(losses)
                vacc += np.asarray(accs)
                vn += 1
        vloss /= max(vn, 1)
        vacc /= max(vn, 1)
        history["train_loss"].append(train_loss)
        history["val_loss"].append(vloss)
        history["val_acc"].append(vacc)
        per_member = {}
        for i, s in enumerate(noise_levels):
            per_member[f"train_loss_sigma{s:g}"] = float(train_loss[i])
            per_member[f"val_loss_sigma{s:g}"] = float(vloss[i])
            per_member[f"val_acc_sigma{s:g}"] = float(vacc[i])
        logger.log(epoch=epoch, **per_member)

        improved = vacc > member_best_acc
        if improved.any():
            mask = jnp.asarray(improved)
            member_best = jax.tree.map(
                lambda b, p: jnp.where(
                    mask.reshape(mask.shape + (1,) * (p.ndim - 1)), p, b
                ),
                member_best,
                params,
            )
            member_best_acc = np.where(improved, vacc, member_best_acc)
            member_best_epoch = np.where(improved, epoch, member_best_epoch)
            if workdir is not None:
                save_checkpoint(
                    workdir,
                    "nat_sweep_member_best",
                    {"params": member_best},
                    {
                        "member_best_acc": [float(a) for a in member_best_acc],
                        "member_best_epoch": [int(e) for e in member_best_epoch],
                        "member_best_from_epoch": member_best_from_epoch,
                        "noise_levels": list(map(float, noise_levels)),
                        "name": cfg.name,
                        "quantum": quantum_meta,
                    },
                )

        if workdir is not None:
            top = int(np.argmax(vacc))
            if float(vacc[top]) > best_acc:
                best_acc = float(vacc[top])
                best_params = jax.tree.map(lambda x: x[top], params)
                save_checkpoint(
                    workdir,
                    "nat_sweep_best",
                    {"params": best_params},
                    {
                        "epoch": epoch,
                        "member": top,
                        "sigma": float(noise_levels[top]),
                        "val_acc": best_acc,
                        "name": cfg.name,
                        "quantum": quantum_meta,
                    },
                )
            save_checkpoint(
                workdir,
                "nat_sweep_resume",
                {"params": params, "opt_state": opt_state},
                {
                    "epoch": epoch,
                    "best_acc": best_acc,
                    "noise_levels": list(map(float, noise_levels)),
                    "name": cfg.name,
                    "quantum": quantum_meta,
                },
            )
    if workdir is not None:
        save_checkpoint(
            workdir,
            "nat_sweep_last",
            {"params": params},
            {
                "noise_levels": list(map(float, noise_levels)),
                "name": cfg.name,
                "quantum": quantum_meta,
            },
        )
    return params, history
