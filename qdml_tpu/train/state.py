"""Train state: params + optimizer + BatchNorm running statistics."""

from __future__ import annotations

from typing import Any

from flax.training import train_state


class TrainState(train_state.TrainState):
    """Flax TrainState extended with BatchNorm ``batch_stats`` (the reference
    trunks use BatchNorm2d, ``Estimators_QuantumNAT_onchipQNN.py:52, 249``)."""

    batch_stats: Any = None
