"""Checkpointing with orbax: best + last policies, resume-capable.

The reference saves write-only ``torch.save`` state dicts with
filename-encoded metadata and two policies — best-metric and final-epoch
(``Runner_P128_QuantumNAT_onchipQNN.py:237-266, 417-426``) — and its loader
must juggle three dict formats plus DataParallel ``module.`` prefixes
(``Test.py:23-62``). Here checkpoints are orbax PyTree directories with a
sidecar ``meta.json`` (epoch, metric, config name); restore is structure-safe
and training can RESUME (the reference cannot — SURVEY.md §5.4).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def _ckptr() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def reconcile_quantum_cfg(cfg, meta: dict):
    """Rebuild the quantum-model config a checkpoint was trained for.

    QSC checkpoints store their architecture facts in ``meta['quantum']``
    (n_qubits/n_layers/n_classes/input_norm). Flags like ``input_norm``
    carry no params of their own, so evaluating with a mismatched config
    would silently change behavior; shape-bearing fields would crash later
    with an opaque error. ``backend`` is different: it is a numerically
    equivalent execution strategy, not an architecture fact, so the eval
    config (and any explicit CLI override) wins — a checkpoint trained with
    ``backend='sharded'`` must remain evaluable on a single host — the
    dispatcher re-resolves for the eval topology. The exception is an
    EXPLICIT eval-config pin (``quantum.impl`` / legacy ``quantum.backend``
    not "auto") that cannot run at the checkpoint's qubit count on this
    topology: that raises a typed
    :class:`~qdml_tpu.quantum.autotune.ImplIneligibleError` naming the
    eligibility reason (e.g. ``sharded_statevector`` pinned and restored on
    one device) instead of a partnerless-collective hang or shape error deep
    in the first forward. Every qsc-checkpoint consumer should pass its
    restored meta through here. No-op when the checkpoint predates the meta
    (or came from a source that has none)."""
    import dataclasses

    stored = (meta or {}).get("quantum")
    if not stored:
        return cfg
    from qdml_tpu.quantum.autotune import ImplIneligibleError, impl_eligible
    from qdml_tpu.quantum.circuits import canonical_impl

    stored = dict(stored)
    trained_backend = stored.pop("backend", None)
    # like backend, the dispatcher override is an execution strategy, not an
    # architecture fact — provenance only, never folded into the eval config.
    # It still goes through the canonical choke point: a checkpoint naming an
    # impl this build does not know (or a deprecated alias) must produce a
    # diagnosable ValueError here, not a KeyError downstream.
    trained_impl = stored.pop("impl", None)
    if trained_impl not in (None, "", "auto"):
        trained_impl = canonical_impl(trained_impl)
    # chi is an mps execution knob (numerics-relevant but param-free) — the
    # eval config's value wins, same rule as backend/impl
    stored.pop("mps_chi", None)
    n_q = stored.get("n_qubits", cfg.quantum.n_qubits)
    # The impl that will actually dispatch at eval is the config's explicit
    # pin (impl > legacy backend; "auto" lets the dispatcher re-resolve for
    # THIS topology and never needs a check). A pin that cannot run here —
    # the checkpoint-and-config pair pinning sharded_statevector restored on
    # a 1-device host, or dense at a 16-qubit checkpoint's n — fails NOW,
    # typed and with the eligibility reason, instead of as a shape error or
    # a partnerless collective deep in the restored model's first forward.
    pinned = (
        cfg.quantum.impl
        if cfg.quantum.impl not in ("", "auto")
        else (cfg.quantum.backend if cfg.quantum.backend != "auto" else None)
    )
    if pinned is not None:
        pinned = canonical_impl(pinned)
        ok, why = impl_eligible(pinned, n_q)
        if not ok:
            raise ImplIneligibleError(
                f"checkpoint (n_qubits={n_q}) pins circuit impl {pinned!r}, "
                f"which cannot run on this topology: {why}"
            )
    elif trained_impl not in (None, "", "auto"):
        ok, why = impl_eligible(trained_impl, n_q)
        if not ok:
            # provenance-only pin that no longer runs here: the dispatcher
            # will re-resolve, but say so — silent was the bug class
            print(
                f"note: checkpoint was trained with circuit impl "
                f"{trained_impl!r}, ineligible on this topology ({why}); "
                "the dispatcher re-resolves for this host"
            )
    if trained_backend is not None:
        # Compare RESOLVED execution paths: with "auto" in play, the stored
        # and configured strings can differ while naming the identical path
        # (auto->dense on CPU vs a 'dense' checkpoint) or match while the
        # path actually changes across platforms — only the resolution is
        # meaningful provenance.
        from qdml_tpu.quantum.circuits import resolve_backend

        trained_res = resolve_backend(trained_backend, n_q)
        eval_res = resolve_backend(cfg.quantum.backend, n_q)
        if trained_res != eval_res:
            print(
                f"note: checkpoint was trained on the {trained_res!r} circuit "
                f"path (backend={trained_backend!r}); evaluating on "
                f"{eval_res!r} (numerically equivalent execution strategies)"
            )
    mismatch = {k: v for k, v in stored.items() if getattr(cfg.quantum, k) != v}
    if mismatch:
        print(f"using checkpoint quantum config {mismatch}")
        cfg = dataclasses.replace(cfg, quantum=dataclasses.replace(cfg.quantum, **mismatch))
    return cfg


def save_checkpoint(workdir: str, tag: str, payload: Any, meta: dict | None = None) -> str:
    """Save a pytree under ``workdir/tag`` (tag in {'best', 'last', ...})."""
    path = os.path.abspath(os.path.join(workdir, tag))
    payload = jax.tree.map(lambda x: x, payload)  # shallow copy
    ckptr = _ckptr()
    # Multi-host: orbax coordinates the array save across processes itself;
    # the plain-JSON sidecar must be written by exactly one.
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    if meta is not None and jax.process_index() == 0:
        with open(path + ".meta.json", "w") as fh:
            json.dump(meta, fh)
    return path


class CheckpointRestoreError(RuntimeError):
    """An EXISTING checkpoint tag failed to restore — corrupt, truncated, or
    partially written (e.g. a crash mid-save, a bad copy). Distinct from
    :class:`CheckpointNotFoundError` (never trained) on purpose: "the file
    is garbage" must never take the never-trained fallback path — the
    serving engine's qsc -> sc downgrade would silently serve the wrong
    model family, and a hot-swap must reply typed ``swap_failed`` while the
    old params keep serving (docs/RESILIENCE.md)."""


def restore_checkpoint(workdir: str, tag: str, target: Any | None = None) -> tuple[Any, dict]:
    """Restore ``workdir/tag``; returns (pytree, meta dict).

    Device-agnostic: without a ``target`` the arrays restore as host numpy
    (a checkpoint written on the TPU stores its device sharding, which would
    otherwise fail to restore in a CPU process — e.g. eval on a host whose
    accelerator tunnel is down). jax ops consume numpy leaves transparently.

    A restore failure on an EXISTING tag raises typed
    :class:`CheckpointRestoreError` (chaining orbax's own error): callers
    with a never-trained fallback must be able to tell "missing" from
    "corrupt" without matching orbax internals.
    """
    path = os.path.abspath(os.path.join(workdir, tag))
    ckptr = _ckptr()
    try:
        if target is not None:
            restored = ckptr.restore(path, target)
        else:
            # orbax >=0.9 wraps the per-array metadata (.item_metadata.tree);
            # 0.7.x returns the metadata tree directly. Both leaves carry
            # shape/dtype, which is all the zeros-target needs.
            md = ckptr.metadata(path)
            meta_tree = md.item_metadata.tree if hasattr(md, "item_metadata") else md
            restored = ckptr.restore(
                path, jax.tree.map(lambda m: np.zeros(m.shape, m.dtype), meta_tree)
            )
    except Exception as e:  # lint: disable=broad-except(orbax raises a zoo of backend-specific errors for a corrupt/truncated tree — FileNotFoundError for missing leaves, ValueError/KeyError for bad metadata, TypeError for garbage structure; ALL of them mean 'existing tag failed to restore' and must surface as the one typed error, re-raised with provenance)
        raise CheckpointRestoreError(
            f"checkpoint {tag!r} under {workdir!r} exists but failed to "
            f"restore (corrupt/truncated/partially written?): "
            f"{type(e).__name__}: {e}"
        ) from e
    meta: dict = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as fh:
            meta = json.load(fh)
    return restored, meta


def has_checkpoint(workdir: str, tag: str) -> bool:
    return os.path.isdir(os.path.join(workdir, tag))


def latest_tag(workdir: str, prefix: str) -> str | None:
    """Best restorable tag for a model family (``prefix`` in {'hdce', 'sc',
    'qsc', 'dce', ...}): ``{prefix}_best`` when present, else ``_last``, else
    ``_resume`` (whose params are a superset of either). One home for the
    tag-discovery order the eval CLI and the serving engine both need —
    ``None`` when the family was never trained in this workdir."""
    for cand in (f"{prefix}_best", f"{prefix}_last", f"{prefix}_resume"):
        if has_checkpoint(workdir, cand):
            return cand
    return None


def restore_params(workdir: str, tag: str) -> tuple[dict, dict]:
    """Eval-only restore: model variables without optimizer state.

    Works on both payload shapes — ``*_best``/``*_last`` checkpoints hold
    ``{params[, batch_stats]}`` already, while ``*_resume`` checkpoints add
    ``opt_state``/``step``, which an inference consumer must not drag onto
    the device (the Adam moments double the restore footprint). Returns
    ``({"params": ..., ["batch_stats": ...]}, meta)``.
    """
    restored, meta = restore_checkpoint(workdir, tag)
    out = {"params": restored["params"]}
    if "batch_stats" in restored:
        out["batch_stats"] = restored["batch_stats"]
    return out, meta


class CheckpointNotFoundError(FileNotFoundError):
    """A model family was never trained in this workdir (no best/last/resume
    tag). Distinct from a *failed restore* of an existing tag — a partially
    written or corrupt checkpoint raises orbax's own error, which callers
    with a fallback (``ServeEngine.from_workdir``'s qsc -> sc downgrade) must
    NOT swallow: silently serving the wrong model family is worse than
    failing loudly."""


def restore_latest_params(workdir: str, prefix: str) -> tuple[dict, dict, str]:
    """Eval-only restore of a family's newest checkpoint: ``(vars, meta,
    tag)`` via :func:`latest_tag` + :func:`restore_params`.

    One home for the restore-the-newest dance the serving engine runs at
    construction AND at every live hot-swap (``ServeEngine.swap_from_workdir``
    re-resolves the tag each call, so a training run promoting a new
    ``*_best`` is picked up without restarting the server). Raises
    :class:`CheckpointNotFoundError` with the train-command hint when the
    family was never trained here; restore failures on an existing tag
    propagate as-is.
    """
    tag = latest_tag(workdir, prefix)
    if tag is None:
        raise CheckpointNotFoundError(
            f"no {prefix} checkpoint (best/last/resume) under {workdir!r} — "
            f"run `qdml-tpu train-{prefix}` first"
        )
    vars_, meta = restore_params(workdir, tag)
    return vars_, meta, tag


def _broadcast_meta(meta: dict) -> dict:
    """Under multi-process, make process 0's sidecar meta authoritative.

    Orbax coordinates the array save across processes, but the plain-JSON
    ``.meta.json`` sidecar is written by process 0 only — on a non-shared
    workdir filesystem, hosts > 0 would read ``{}`` and resume at epoch 0
    with a default best, diverging the control flow (unequal epoch counts /
    save decisions) until a collective save hangs. Broadcasting the JSON
    bytes from process 0 removes the shared-filesystem requirement for the
    *control-flow* fields; the array data itself still needs the usual
    orbax-visible storage (shared fs or object store) — see docs/MULTIHOST.md.
    """
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    payload = np.frombuffer(json.dumps(meta).encode(), dtype=np.uint8)
    # Two-phase: length first (shapes must match across processes; hosts > 0
    # may hold a different/empty meta), then the padded byte buffer.
    n = int(multihost_utils.broadcast_one_to_all(jnp.asarray(len(payload))))
    buf = np.zeros(n, np.uint8)
    buf[: min(n, len(payload))] = payload[:n]
    out = np.asarray(multihost_utils.broadcast_one_to_all(jnp.asarray(buf)))
    return json.loads(out.tobytes().decode())


# ---------------------------------------------------------------------------
# Full-train-state save/resume
# ---------------------------------------------------------------------------


def train_state_payload(state: Any) -> dict:
    """Everything needed to resume: params, optimizer state, step counter,
    and (when present) BatchNorm running statistics."""
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": jax.numpy.asarray(state.step),
    }
    if getattr(state, "batch_stats", None) is not None:
        payload["batch_stats"] = state.batch_stats
    return payload


def save_train_state(workdir: str, tag: str, state: Any, meta: dict | None = None) -> str:
    return save_checkpoint(workdir, tag, train_state_payload(state), meta)


def try_resume(workdir: str | None, tag: str, state: Any) -> tuple[Any, int, dict]:
    """Restore a full TrainState from ``workdir/tag`` if present.

    Returns ``(state, start_epoch, meta)`` — ``start_epoch`` is the epoch
    AFTER the checkpointed one (0 when nothing to resume); ``meta`` carries
    whatever the trainer persisted (e.g. the running best metric, so resumed
    runs do not clobber a better ``*_best`` checkpoint). The reference cannot
    resume at all (write-only checkpoints, SURVEY.md §5.4).
    """
    present = workdir is not None and has_checkpoint(workdir, tag)
    if jax.process_count() > 1:
        # Process 0's view is authoritative: a host whose filesystem view
        # disagrees must fail loudly in the collective restore below, not
        # silently resume from scratch while the others resume from the
        # checkpoint (divergent epoch counts hang the next collective save).
        from jax.experimental import multihost_utils

        present = bool(multihost_utils.broadcast_one_to_all(jax.numpy.asarray(present)))
    if not present:
        return state, 0, {}
    restored, meta = restore_checkpoint(workdir, tag, train_state_payload(state))
    if jax.process_count() > 1:
        meta = _broadcast_meta(meta)
    state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=int(restored["step"]),
    )
    if "batch_stats" in restored:
        state = state.replace(batch_stats=restored["batch_stats"])
    return state, int(meta.get("epoch", -1)) + 1, meta
