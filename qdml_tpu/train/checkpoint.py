"""Checkpointing with orbax: best + last policies, resume-capable.

The reference saves write-only ``torch.save`` state dicts with
filename-encoded metadata and two policies — best-metric and final-epoch
(``Runner_P128_QuantumNAT_onchipQNN.py:237-266, 417-426``) — and its loader
must juggle three dict formats plus DataParallel ``module.`` prefixes
(``Test.py:23-62``). Here checkpoints are orbax PyTree directories with a
sidecar ``meta.json`` (epoch, metric, config name); restore is structure-safe
and training can RESUME (the reference cannot — SURVEY.md §5.4).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp


def _ckptr() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def reconcile_quantum_cfg(cfg, meta: dict):
    """Rebuild the quantum-model config a checkpoint was trained for.

    QSC checkpoints store their architecture facts in ``meta['quantum']``
    (n_qubits/n_layers/n_classes/backend/input_norm). Flags like
    ``input_norm`` carry no params of their own, so evaluating with a
    mismatched config would silently change behavior; shape-bearing fields
    would crash later with an opaque error. Every qsc-checkpoint consumer
    should pass its restored meta through here. No-op when the checkpoint
    predates the meta (or came from a source that has none)."""
    import dataclasses

    stored = (meta or {}).get("quantum")
    if not stored:
        return cfg
    mismatch = {k: v for k, v in stored.items() if getattr(cfg.quantum, k) != v}
    if mismatch:
        print(f"using checkpoint quantum config {mismatch}")
        cfg = dataclasses.replace(cfg, quantum=dataclasses.replace(cfg.quantum, **mismatch))
    return cfg


def save_checkpoint(workdir: str, tag: str, payload: Any, meta: dict | None = None) -> str:
    """Save a pytree under ``workdir/tag`` (tag in {'best', 'last', ...})."""
    path = os.path.abspath(os.path.join(workdir, tag))
    payload = jax.tree.map(lambda x: x, payload)  # shallow copy
    ckptr = _ckptr()
    # Multi-host: orbax coordinates the array save across processes itself;
    # the plain-JSON sidecar must be written by exactly one.
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    if meta is not None and jax.process_index() == 0:
        with open(path + ".meta.json", "w") as fh:
            json.dump(meta, fh)
    return path


def restore_checkpoint(workdir: str, tag: str, target: Any | None = None) -> tuple[Any, dict]:
    """Restore ``workdir/tag``; returns (pytree, meta dict).

    Device-agnostic: without a ``target`` the arrays restore as host numpy
    (a checkpoint written on the TPU stores its device sharding, which would
    otherwise fail to restore in a CPU process — e.g. eval on a host whose
    accelerator tunnel is down). jax ops consume numpy leaves transparently.
    """
    path = os.path.abspath(os.path.join(workdir, tag))
    ckptr = _ckptr()
    if target is not None:
        restored = ckptr.restore(path, target)
    else:
        import numpy as np

        meta_tree = ckptr.metadata(path).item_metadata.tree
        restored = ckptr.restore(
            path, jax.tree.map(lambda m: np.zeros(m.shape, m.dtype), meta_tree)
        )
    meta: dict = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as fh:
            meta = json.load(fh)
    return restored, meta


def has_checkpoint(workdir: str, tag: str) -> bool:
    return os.path.isdir(os.path.join(workdir, tag))


# ---------------------------------------------------------------------------
# Full-train-state save/resume
# ---------------------------------------------------------------------------


def train_state_payload(state: Any) -> dict:
    """Everything needed to resume: params, optimizer state, step counter,
    and (when present) BatchNorm running statistics."""
    payload = {
        "params": state.params,
        "opt_state": state.opt_state,
        "step": jax.numpy.asarray(state.step),
    }
    if getattr(state, "batch_stats", None) is not None:
        payload["batch_stats"] = state.batch_stats
    return payload


def save_train_state(workdir: str, tag: str, state: Any, meta: dict | None = None) -> str:
    return save_checkpoint(workdir, tag, train_state_payload(state), meta)


def try_resume(workdir: str | None, tag: str, state: Any) -> tuple[Any, int, dict]:
    """Restore a full TrainState from ``workdir/tag`` if present.

    Returns ``(state, start_epoch, meta)`` — ``start_epoch`` is the epoch
    AFTER the checkpointed one (0 when nothing to resume); ``meta`` carries
    whatever the trainer persisted (e.g. the running best metric, so resumed
    runs do not clobber a better ``*_best`` checkpoint). The reference cannot
    resume at all (write-only checkpoints, SURVEY.md §5.4).
    """
    if workdir is None or not has_checkpoint(workdir, tag):
        return state, 0, {}
    restored, meta = restore_checkpoint(workdir, tag, train_state_payload(state))
    state = state.replace(
        params=restored["params"],
        opt_state=restored["opt_state"],
        step=int(restored["step"]),
    )
    if "batch_stats" in restored:
        state = state.replace(batch_stats=restored["batch_stats"])
    return state, int(meta.get("epoch", -1)) + 1, meta
