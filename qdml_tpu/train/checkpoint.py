"""Checkpointing with orbax: best + last policies, resume-capable.

The reference saves write-only ``torch.save`` state dicts with
filename-encoded metadata and two policies — best-metric and final-epoch
(``Runner_P128_QuantumNAT_onchipQNN.py:237-266, 417-426``) — and its loader
must juggle three dict formats plus DataParallel ``module.`` prefixes
(``Test.py:23-62``). Here checkpoints are orbax PyTree directories with a
sidecar ``meta.json`` (epoch, metric, config name); restore is structure-safe
and training can RESUME (the reference cannot — SURVEY.md §5.4).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import orbax.checkpoint as ocp


def _ckptr() -> ocp.StandardCheckpointer:
    return ocp.StandardCheckpointer()


def save_checkpoint(workdir: str, tag: str, payload: Any, meta: dict | None = None) -> str:
    """Save a pytree under ``workdir/tag`` (tag in {'best', 'last', ...})."""
    path = os.path.abspath(os.path.join(workdir, tag))
    payload = jax.tree.map(lambda x: x, payload)  # shallow copy
    ckptr = _ckptr()
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    if meta is not None:
        with open(path + ".meta.json", "w") as fh:
            json.dump(meta, fh)
    return path


def restore_checkpoint(workdir: str, tag: str, target: Any | None = None) -> tuple[Any, dict]:
    """Restore ``workdir/tag``; returns (pytree, meta dict)."""
    path = os.path.abspath(os.path.join(workdir, tag))
    restored = _ckptr().restore(path, target)
    meta: dict = {}
    if os.path.exists(path + ".meta.json"):
        with open(path + ".meta.json") as fh:
            meta = json.load(fh)
    return restored, meta


def has_checkpoint(workdir: str, tag: str) -> bool:
    return os.path.isdir(os.path.join(workdir, tag))
