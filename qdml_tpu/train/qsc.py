"""Scenario-classifier training (classical SC and quantum QSC).

Reference loop (``train_QSC_P128``, ``Runner_P128_QuantumNAT_onchipQNN.py:307-426``,
SURVEY.md §3.1): AdamW(1e-3, wd=0.01), 100 epochs over the 3x3 grid with
``F.nll_loss/9`` summed per cell, optional QuantumNAT noise injection and
gradient pruning, best-accuracy + last checkpoints.

TPU-native: the grid flattens to one batch (equal cell sizes make the summed
per-cell mean equal to the flat mean), the QuantumNAT PRNG is threaded through
``apply(rngs={'quantumnat': ...})``, pruning lives in the optax chain, and the
step jits end-to-end — there is no torch->PennyLane->CPU boundary (the
reference's hottest bottleneck, SURVEY.md §3.1).
"""

from __future__ import annotations

from typing import Callable

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.models.cnn import SCP128
from qdml_tpu.models.losses import nll_loss
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.quantum.circuits import resolve_backend
from qdml_tpu.train.checkpoint import save_checkpoint, save_train_state, try_resume
from qdml_tpu.train.optim import get_optimizer
from qdml_tpu.telemetry import FlightRecorder, StepClock, probe_tree, span
from qdml_tpu.telemetry.cost import maybe_emit_cost
from qdml_tpu.train.state import TrainState
from qdml_tpu.utils.metrics import MetricsLogger


def build_classifier(cfg: ExperimentConfig, quantum: bool) -> nn.Module:
    if quantum:
        return QSCP128(
            n_qubits=cfg.quantum.n_qubits,
            n_layers=cfg.quantum.n_layers,
            n_classes=cfg.quantum.n_classes,
            use_quantumnat=cfg.quantum.use_quantumnat,
            noise_level=cfg.quantum.noise_level,
            backend=cfg.quantum.backend,
            impl=cfg.quantum.impl,
            mps_chi=cfg.quantum.mps_chi,
            input_norm=cfg.quantum.input_norm,
        )
    return SCP128(n_classes=cfg.quantum.n_classes)


def _sc_step(
    model: nn.Module,
    needs_rng: bool,
    state: TrainState,
    batch: dict,
    rng: jax.Array,
    probes: bool = True,
) -> tuple[TrainState, dict]:
    """One classifier grid step (traceable; jitted by the makers below).
    ``probes=False`` compiles the numerics probe out (static flag)."""
    x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
    labels = batch["indicator"].reshape(-1)

    def loss_fn(params):
        kwargs = {"rngs": {"quantumnat": rng}} if needs_rng else {}
        log_probs = model.apply({"params": params}, x, train=True, **kwargs)
        return nll_loss(log_probs, labels)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    # optax applied explicitly (flax's apply_gradients verbatim) so the
    # numerics probe sees the actual per-step UPDATES, not a params diff
    updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
    m = {"loss": loss}
    if probes:
        m["probe"] = probe_tree(grads, state.params, updates)
    state = state.replace(
        step=state.step + 1,
        params=optax.apply_updates(state.params, updates),
        opt_state=new_opt_state,
    )
    return state, m


def make_sc_train_step(
    model: nn.Module,
    needs_rng: bool,
    probes: bool = True,
    checkify_errors: bool = False,
) -> Callable:
    from qdml_tpu.utils.platform import donation_argnums

    if checkify_errors:
        # runtime sanitizer (train.checkify): same signature/returns, with
        # the checkify error riding the metrics dict for the flight recorder
        from qdml_tpu.telemetry.sanitizer import checkify_step

        return checkify_step(
            partial(_sc_step, model, needs_rng, probes=probes),
            donate=donation_argnums(0),
        )

    @partial(jax.jit, donate_argnums=donation_argnums(0))
    def step(state: TrainState, batch: dict, rng: jax.Array):
        return _sc_step(model, needs_rng, state, batch, rng, probes=probes)

    return step


def make_sc_scan_steps(
    model: nn.Module,
    geom: ChannelGeometry,
    needs_rng: bool,
    mesh=None,
    probes: bool = True,
) -> Callable:
    """K classifier train steps in ONE device dispatch: the shared scan
    machinery (:func:`qdml_tpu.train.scan.make_scan_steps`) bound to the
    classifier step. ``rngs (K, 2)`` carries one pre-split QuantumNAT key per
    step (:func:`qdml_tpu.train.scan.presplit_keys`) so the noise stream
    matches the per-step dispatch loop exactly."""
    from qdml_tpu.train.scan import make_scan_steps

    return make_scan_steps(
        partial(_sc_step, model, needs_rng, probes=probes),
        geom,
        ("yp_img", "indicator"),
        mesh=mesh,
        with_rng=True,
    )


def make_sc_eval_step(model: nn.Module) -> Callable:
    @jax.jit
    def step(state: TrainState, batch: dict):
        x = batch["yp_img"].reshape(-1, *batch["yp_img"].shape[3:])
        labels = batch["indicator"].reshape(-1)
        log_probs = model.apply({"params": state.params}, x, train=False)
        return {
            "nll_sum": -jnp.sum(
                jnp.take_along_axis(log_probs, labels[:, None], axis=-1)
            ),
            "correct": jnp.sum(jnp.argmax(log_probs, -1) == labels),
            "count": jnp.asarray(labels.size, jnp.float32),
        }

    return step


def init_sc_state(cfg: ExperimentConfig, quantum: bool, steps_per_epoch: int):
    model = build_classifier(cfg, quantum)
    dummy = jnp.zeros((2, *cfg.image_hw, 2), jnp.float32)
    # Compiled init: the quantum backends (sharded above all) are minutes of
    # eager per-op dispatch at large n_qubits but seconds compiled.
    variables = jax.jit(lambda key, x: model.init(key, x, train=False))(
        jax.random.PRNGKey(cfg.train.seed), dummy
    )
    train_cfg = cfg.train
    if quantum:
        # Reference QSC training uses AdamW (Runner...py:320).
        import dataclasses

        train_cfg = dataclasses.replace(train_cfg, optimizer="adamw")
    tx = get_optimizer(train_cfg, steps_per_epoch, cfg.quantum if quantum else None)
    state = TrainState.create(apply_fn=model.apply, params=variables["params"], tx=tx)
    return model, state


def train_classifier(
    cfg: ExperimentConfig,
    quantum: bool,
    logger: MetricsLogger | None = None,
    workdir: str | None = None,
) -> tuple[TrainState, dict]:
    """Train SC_P128 (classical) or QSC_P128 (quantum) over the DML grid."""
    logger = logger or MetricsLogger(echo=False)
    geom = ChannelGeometry.from_config(cfg.data)
    train_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    val_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "val", geom)
    model, state = init_sc_state(cfg, quantum, train_loader.steps_per_epoch)
    if quantum:
        # Autotuned circuit dispatch (docs/QUANTUM.md): time the eligible
        # implementations at THIS run's exact circuit shape before the step
        # compiles, so the trace below bakes in the measured winner instead
        # of a static guess. The grid flattens into one batch inside the
        # step, so the circuit batch is the whole grid. No-op when the
        # dispatcher is overridden or tuning is off for this platform.
        from qdml_tpu.quantum import autotune

        entry = autotune.prewarm(
            cfg, batch=cfg.data.n_scenarios * cfg.data.n_users * cfg.train.batch_size
        )
        if entry is not None:
            logger.log(
                kind="quantum_autotune",
                key=entry["key"],
                impl=entry["best_train"],
                impl_infer=entry["best_fwd"],
                candidates=entry["candidates"],
            )
    needs_rng = quantum and cfg.quantum.use_quantumnat
    probes_on = cfg.train.probe_every > 0  # 0 compiles the probes out
    train_step = make_sc_train_step(
        model, needs_rng, probes=probes_on, checkify_errors=cfg.train.checkify
    )
    eval_step = make_sc_eval_step(model)
    tag = "qsc" if quantum else "sc"

    start_epoch = 0
    best_acc = -1.0
    if cfg.train.resume:
        state, start_epoch, rmeta = try_resume(workdir, f"{tag}_resume", state)
        best_acc = float(rmeta.get("best", best_acc))

    # Multi-device: replicate params, shard batches over the data axis (the
    # statevector itself shards only under the "sharded" backend). Same
    # placement policy as train_hdce (qdml_tpu.parallel.multihost).
    from qdml_tpu.parallel.dp import replicate
    from qdml_tpu.parallel.mesh import training_mesh
    from qdml_tpu.parallel.multihost import make_grid_placer

    mesh = training_mesh(cfg)
    if mesh is not None:
        state = replicate(state, mesh)
    place_train = make_grid_placer(train_loader, mesh)
    place_val = make_grid_placer(val_loader, mesh)

    # Scan-fused dispatch — the DEFAULT, K=1 included (scan_steps=0 opts
    # out): same machinery and eligibility rules as train_hdce
    # (qdml_tpu.train.scan.scan_eligible).
    from qdml_tpu.train.scan import presplit_keys, scan_eligible

    scan_k = cfg.train.scan_steps
    scan_run = None
    if scan_eligible(cfg, mesh, train_loader, logger):
        scan_run = make_sc_scan_steps(model, geom, needs_rng, mesh=mesh, probes=probes_on)

    # Fold the start epoch into the QuantumNAT noise stream so resumed epochs
    # draw FRESH noise instead of replaying epochs 0..start_epoch-1's draws.
    rng = jax.random.fold_in(jax.random.PRNGKey(cfg.train.seed + 1), start_epoch)
    clock = StepClock(f"{tag}_train")
    # Numerics flight recorder: the QuantumNAT noise stream is exactly the
    # knob that can silently destabilize this loop — a NaN here becomes a
    # typed DivergenceError with a post-mortem dump (docs/FLIGHTREC.md).
    rec = FlightRecorder(f"{tag}_train", cfg, workdir=workdir)
    rec.note_good(state.params)
    cost_done = False
    history: dict[str, list] = {"train_loss": [], "val_loss": [], "val_acc": []}
    for epoch in range(start_epoch, cfg.train.n_epochs):
        tot, n = 0.0, 0
        with span("train_epoch", epoch=epoch):
            if scan_run is not None:
                seed = jnp.uint32(cfg.data.seed)
                scen, user = train_loader.grid_coords
                tot_dev = None  # on-device loss accumulator, fetched once per epoch
                for idx, snrs in train_loader.epoch_chunks(epoch, scan_k):
                    rng, subs = presplit_keys(rng, idx.shape[0])
                    if not cost_done:
                        maybe_emit_cost(
                            f"{tag}_train_scan", scan_run, state, seed, scen,
                            user, idx, snrs, subs, scan_steps=scan_k,
                        )
                        cost_done = True
                    fetch = rec.should_fetch()
                    losses = None
                    with clock.step() as st:
                        state, ms = scan_run(state, seed, scen, user, idx, snrs, subs)
                        if fetch:
                            # sole steady-state sync, on the probe cadence
                            # only (zero with probe_every=0) — see train_hdce
                            st.transfer()
                            losses = np.asarray(jax.device_get(ms["loss"]))
                    chunk = jnp.sum(ms["loss"])
                    tot_dev = chunk if tot_dev is None else tot_dev + chunk
                    rec.on_step(
                        epoch, ms, loss=losses, params=state.params, rng=subs,
                        batch_info={"dispatch": "scan", "idx": idx, "snrs": snrs},
                    )
                    n += idx.shape[0]
                if tot_dev is not None:
                    tot = float(jax.device_get(tot_dev))
                    # epoch-aggregate watchdog check — see train_hdce
                    rec.on_epoch_loss(epoch, tot)
            else:
                for batch in train_loader.epoch(epoch):
                    rng, sub = jax.random.split(rng)
                    pb = place_train(batch)
                    if not cost_done:
                        maybe_emit_cost(f"{tag}_train_step", train_step, state, pb, sub)
                        cost_done = True
                    with clock.step() as st:
                        state, m = train_step(state, pb, sub)
                        st.transfer()
                        loss = float(m["loss"])
                        tot = tot + loss
                    rec.on_step(
                        epoch, m, loss=loss, params=state.params, rng=sub,
                        batch_info={"dispatch": "step", "step_in_epoch": n},
                    )
                    n += 1
        clock.epoch_end(epoch=epoch)
        train_loss = tot / max(n, 1)

        sums = {"nll_sum": 0.0, "correct": 0.0, "count": 0.0}
        with span("val_epoch", epoch=epoch):
            for batch in val_loader.epoch(epoch, shuffle=False):
                out = eval_step(state, place_val(batch))
                for k in sums:
                    sums[k] += float(out[k])
        val_loss = sums["nll_sum"] / max(sums["count"], 1)
        val_acc = sums["correct"] / max(sums["count"], 1)
        history["train_loss"].append(train_loss)
        history["val_loss"].append(val_loss)
        history["val_acc"].append(val_acc)
        logger.log(epoch=epoch, train_loss=train_loss, val_loss=val_loss, val_acc=val_acc)

        if workdir is not None:
            meta = {"epoch": epoch, "val_acc": val_acc, "name": cfg.name}
            if quantum:
                # Architecture facts eval needs to rebuild the model the
                # params were trained for (input_norm has no params of its
                # own, so a mismatch would otherwise be silent).
                meta["quantum"] = {
                    "n_qubits": cfg.quantum.n_qubits,
                    "n_layers": cfg.quantum.n_layers,
                    "n_classes": cfg.quantum.n_classes,
                    # store the RESOLVED path: "auto" means different things
                    # on different platforms, so the concrete resolution is
                    # the only meaningful provenance for the reconcile note
                    "backend": resolve_backend(
                        cfg.quantum.backend, cfg.quantum.n_qubits
                    ),
                    # dispatcher provenance (execution strategy, reconcile
                    # pops it like backend): "auto" = autotuned per shape
                    "impl": cfg.quantum.impl,
                    # mps execution knob (numerics-relevant, param-free);
                    # provenance only, the eval config's chi wins
                    "mps_chi": cfg.quantum.mps_chi,
                    "input_norm": cfg.quantum.input_norm,
                }
                # provenance, not architecture (reconcile ignores it): which
                # noise-aware-training recipe produced these params
                meta["training"] = {
                    "use_quantumnat": cfg.quantum.use_quantumnat,
                    "noise_level": cfg.quantum.noise_level,
                }
            if val_acc > best_acc:
                best_acc = val_acc
                save_checkpoint(workdir, f"{tag}_best", {"params": state.params}, meta)
            save_train_state(workdir, f"{tag}_resume", state, {**meta, "best": best_acc})
    if workdir is not None:
        save_checkpoint(
            workdir,
            f"{tag}_last",
            {"params": state.params},
            {"epoch": cfg.train.n_epochs - 1, "name": cfg.name},
        )
    return state, history
