"""HDCE training: hierarchical deep channel estimation, one fused SPMD step.

Reference training loop (``Runner_P128_QuantumNAT_onchipQNN.py:134-283``,
SURVEY.md §3.2): three ``Conv_P128`` trunks + one shared ``FC_P128`` head, four
Adam optimizers, and NINE sequential ``backward()`` calls per step (one per
(scenario, user) grid cell, each loss divided by 9 — gradient accumulation
across the grid; the head accumulates from all 9 cells, each trunk from its 3
user cells).

TPU-native re-design: the 3x3 grid is ONE stacked array batch, the three trunks
are ONE vmapped module (:class:`~qdml_tpu.models.cnn.StackedConvP128`), the
summed per-cell loss is differentiated ONCE, and the four Adam optimizers
collapse into one optax Adam over the combined tree (Adam is elementwise, so
disjoint param slices update identically). The whole step — data included —
is jit-compiled; under a mesh the batch axis shards for data parallelism
(:mod:`qdml_tpu.parallel`).

Equivalence to the reference's nine ``(loss/9).backward()`` calls: gradients
accumulate linearly, so with FROZEN BatchNorm statistics the fused backward is
exactly the nine accumulated backwards
(``tests/test_bn_semantics.py::test_percell_grads_match_fused_with_frozen_bn``).
In train mode the one deviation channel is BN batch statistics — the fused
step normalizes over (U*B) samples per scenario where the reference
normalizes each cell's B alone — measured at bs=32/cell over 50 steps: max
per-step loss gap 2.7e-2 relative, param drift 3.1e-2 relative L2, held-out
NMSE within 0.9% (fused marginally ahead). BN *running* stats use
``momentum ** n_users`` to match the reference's n_users-updates-per-step
warm-up timescale. See ``tests/test_bn_semantics.py`` for the measurement.
"""

from __future__ import annotations

from typing import Any, Callable

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import DMLGridLoader
from qdml_tpu.models.cnn import FCP128, StackedConvP128, activation_dtype
from qdml_tpu.train.checkpoint import save_checkpoint, save_train_state, try_resume
from qdml_tpu.train.optim import get_optimizer
from qdml_tpu.train.scan import make_scan_steps, scan_eligible
from qdml_tpu.telemetry import FlightRecorder, StepClock, probe_tree, span
from qdml_tpu.telemetry.cost import maybe_emit_cost
from qdml_tpu.train.state import TrainState
from qdml_tpu.utils.metrics import MetricsLogger, nmse_db


class HDCE(nn.Module):
    """Stacked per-scenario trunks + shared head.

    Input ``(S, B, 16, 8, 2)`` -> ``(S, B, 2048)``; scenario s flows through
    trunk slice s only, and every scenario shares the single FC head — the
    reference's "shared knowledge" hierarchy (``Runner...py:139-142``).
    """

    n_scenarios: int = 3
    features: int = 32
    out_dim: int = 2048
    dtype: Any = jnp.float32
    conv_impl: str = "auto"  # conv lowering (models.cnn.resolve_conv_impl)
    # torch's per-update BN decay (BatchNorm2d momentum=0.1,
    # Estimators...py:52). init_hdce_state is the single place that
    # compensates the fused step's ONE update per grid-step with
    # 0.9 ** n_users to match the reference's n_users sequential updates
    # (tests/test_bn_semantics.py).
    bn_momentum: float = 0.9

    @nn.compact
    def __call__(self, x, train: bool = False):
        feats = StackedConvP128(
            self.n_scenarios, self.features, self.dtype, self.bn_momentum, self.conv_impl
        )(x, train=train)
        return FCP128(self.out_dim, self.dtype)(feats)


def cell_nmse(pred: jnp.ndarray, label: jnp.ndarray) -> jnp.ndarray:
    """Per-grid-cell whole-batch NMSE: (S, U, B, D) -> (S, U)."""
    err = jnp.sum((pred - label) ** 2, axis=(-1, -2))
    pow_ = jnp.sum(label**2, axis=(-1, -2))
    return err / pow_


def _fused_step(
    model: HDCE, state: TrainState, batch: dict, probes: bool = True
) -> tuple[TrainState, dict]:
    """One fused grid step (traceable; jitted by the makers below).
    ``probes=False`` compiles the numerics probe out entirely (a static
    trace-time flag: the loops pass ``train.probe_every > 0``)."""
    s, u, b = batch["yp_img"].shape[:3]
    x = batch["yp_img"].reshape(s, u * b, *batch["yp_img"].shape[3:])
    label = batch["h_label"]
    perf = batch["h_perf"]

    def loss_fn(params):
        out, upd = model.apply(
            {"params": params, "batch_stats": state.batch_stats},
            x,
            train=True,
            mutable=["batch_stats"],
        )
        pred = out.reshape(s, u, b, -1)
        loss = jnp.mean(cell_nmse(pred, label))  # == reference sum(cell/9)
        loss_perf = jnp.mean(cell_nmse(pred, perf))
        return loss, (upd["batch_stats"], loss_perf)

    (loss, (new_stats, loss_perf)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        state.params
    )
    # optax applied explicitly (flax's apply_gradients verbatim) so the
    # numerics probe sees the actual per-step UPDATES, not a params diff
    updates, new_opt_state = state.tx.update(grads, state.opt_state, state.params)
    m = {"loss": loss, "loss_perf": loss_perf}
    if probes:
        m["probe"] = probe_tree(grads, state.params, updates)
    state = state.replace(
        step=state.step + 1,
        params=optax.apply_updates(state.params, updates),
        opt_state=new_opt_state,
        batch_stats=new_stats,
    )
    return state, m


def make_hdce_train_step(
    model: HDCE, tx, probes: bool = True, checkify_errors: bool = False
) -> Callable:
    from qdml_tpu.utils.platform import donation_argnums

    if checkify_errors:
        # runtime sanitizer (train.checkify): same signature/returns, with
        # the checkify error riding the metrics dict for the flight recorder
        from qdml_tpu.telemetry.sanitizer import checkify_step

        return checkify_step(
            partial(_fused_step, model, probes=probes),
            donate=donation_argnums(0),
        )

    @partial(jax.jit, donate_argnums=donation_argnums(0))
    def step(state: TrainState, batch: dict) -> tuple[TrainState, dict]:
        return _fused_step(model, state, batch, probes=probes)

    return step


def make_hdce_scan_steps(
    model: HDCE,
    geom: ChannelGeometry,
    mesh=None,
    fed: bool = False,
    probes: bool = True,
) -> Callable:
    """K HDCE train steps in ONE device dispatch: the shared scan machinery
    (:func:`qdml_tpu.train.scan.make_scan_steps` — rationale, SPMD
    composition and calling convention documented there) bound to the fused
    HDCE step. Bitwise-identical update sequence to per-step dispatch
    (``tests/test_train.py``)."""
    return make_scan_steps(
        partial(_fused_step, model, probes=probes),
        geom,
        ("yp_img", "h_label", "h_perf"),
        mesh=mesh,
        fed=fed,
    )


def make_hdce_eval_step(model: HDCE) -> Callable:
    @jax.jit
    def step(state: TrainState, batch: dict) -> dict:
        s, u, b = batch["yp_img"].shape[:3]
        x = batch["yp_img"].reshape(s, u * b, *batch["yp_img"].shape[3:])
        out = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats}, x, train=False
        )
        pred = out.reshape(s, u, b, -1)
        # Error/power sums so the caller can form the epoch NMSE over ALL val
        # data (the reference concatenates predictions first, Runner...py:216-235).
        return {
            "err": jnp.sum((pred - batch["h_label"]) ** 2),
            "pow": jnp.sum(batch["h_label"] ** 2),
            "err_perf": jnp.sum((pred - batch["h_perf"]) ** 2),
            "pow_perf": jnp.sum(batch["h_perf"] ** 2),
        }

    return step


def init_hdce_state(cfg: ExperimentConfig, steps_per_epoch: int) -> tuple[HDCE, TrainState]:
    model = HDCE(
        n_scenarios=cfg.data.n_scenarios,
        features=cfg.model.features,
        out_dim=cfg.h_out_dim,
        dtype=activation_dtype(cfg.model.dtype),
        bn_momentum=0.9**cfg.data.n_users,
        conv_impl=cfg.model.conv_impl,
    )
    dummy = jnp.zeros(
        (cfg.data.n_scenarios, 2, *cfg.image_hw, 2), jnp.float32
    )
    variables = model.init(jax.random.PRNGKey(cfg.train.seed), dummy, train=False)
    tx = get_optimizer(cfg.train, steps_per_epoch)
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables["batch_stats"],
    )
    return model, state


def train_hdce(
    cfg: ExperimentConfig,
    logger: MetricsLogger | None = None,
    workdir: str | None = None,
) -> tuple[TrainState, dict]:
    """Full HDCE training run (reference ``train_Conv_Linear_of_HDCE``).

    Returns the final state and a history dict with per-epoch train/val NMSE.
    """
    logger = logger or MetricsLogger(echo=False)
    geom = ChannelGeometry.from_config(cfg.data)
    train_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "train", geom)
    val_loader = DMLGridLoader(cfg.data, cfg.train.batch_size, "val", geom)
    model, state = init_hdce_state(cfg, train_loader.steps_per_epoch)
    # probe_every=0 compiles the numerics probes OUT of the step program
    # (static flag); the watchdog's loss checks don't need them
    probes_on = cfg.train.probe_every > 0
    train_step = make_hdce_train_step(
        model, state.tx, probes=probes_on, checkify_errors=cfg.train.checkify
    )
    eval_step = make_hdce_eval_step(model)

    start_epoch = 0
    best = float("inf")
    if cfg.train.resume:
        state, start_epoch, rmeta = try_resume(workdir, "hdce_resume", state)
        best = float(rmeta.get("best", best))  # don't clobber a better *_best

    # Multi-device: place state and batches over the (fed, data, model) mesh;
    # the jitted step then runs SPMD (computation follows the shardings, XLA
    # inserts the collectives). Under multiple processes the placer switches
    # the loaders to per-host slice generation. Single device: no-op.
    from qdml_tpu.parallel.federated import shard_hdce_state
    from qdml_tpu.parallel.mesh import training_mesh
    from qdml_tpu.parallel.multihost import make_grid_placer

    mesh = training_mesh(cfg)
    if mesh is not None:
        state = shard_hdce_state(
            state,
            mesh,
            n_scenarios=cfg.data.n_scenarios,
            tensor_parallel=mesh.shape[cfg.mesh.model_axis_name] > 1,
        )
    fed = mesh is not None and mesh.shape[cfg.mesh.fed_axis_name] > 1
    place_train = make_grid_placer(train_loader, mesh, fed=fed)
    place_val = make_grid_placer(val_loader, mesh, fed=fed)

    # Scan-fused dispatch — the DEFAULT, K=1 included (scan_steps=0 opts
    # out): K steps per device dispatch with on-device batch synthesis
    # inside the scan, composing with a single-process mesh via a sharding
    # constraint on the generated batch (eligibility rules + the structured
    # scan_dispatch reason record in scan_eligible).
    scan_k = cfg.train.scan_steps
    scan_run = None
    if scan_eligible(cfg, mesh, train_loader, logger):
        scan_run = make_hdce_scan_steps(model, geom, mesh=mesh, fed=fed, probes=probes_on)

    # Telemetry (events reach the CLI-installed global sink, or the logger's
    # own stream when bound): per-epoch train/val spans plus a StepClock
    # separating compile vs steady-state vs host-transfer time per dispatch.
    clock = StepClock("hdce_train")
    # Numerics flight recorder: probes ride the step's metrics (computed on
    # device inside the jitted step), fetched/logged on the probe_every
    # cadence; the watchdog turns NaN/Inf into a typed DivergenceError with
    # a post-mortem dump (docs/FLIGHTREC.md).
    rec = FlightRecorder("hdce_train", cfg, workdir=workdir)
    rec.note_good(state.params)
    cost_done = False
    history: dict[str, list] = {"train_loss": [], "val_nmse": [], "val_nmse_perf": []}
    for epoch in range(start_epoch, cfg.train.n_epochs):
        tot, n = 0.0, 0
        with span("train_epoch", epoch=epoch):
            if scan_run is not None:
                seed = jnp.uint32(cfg.data.seed)
                scen, user = train_loader.grid_coords
                tot_dev = None  # on-device loss accumulator, fetched once per epoch
                for idx, snrs in train_loader.epoch_chunks(epoch, scan_k):
                    if not cost_done:
                        # one cost record per run: lowering only (traces, no
                        # extra compile — the first dispatch below still does
                        # the one and only compile)
                        maybe_emit_cost(
                            "hdce_train_scan", scan_run, state, seed, scen,
                            user, idx, snrs, scan_steps=scan_k,
                        )
                        cost_done = True
                    fetch = rec.should_fetch()
                    losses = None
                    with clock.step() as st:
                        state, ms = scan_run(state, seed, scen, user, idx, snrs)
                        if fetch:
                            # the ONLY steady-state device->host sync, and only
                            # on the flight recorder's probe cadence: one bulk
                            # transfer for the whole (K,) loss vector.
                            # Off-cadence dispatches enqueue back-to-back with
                            # zero transfers — probe_every=0 pins the epoch's
                            # host-transfer counter at exactly zero
                            # (tests/test_train.py)
                            st.transfer()
                            losses = np.asarray(jax.device_get(ms["loss"]))
                    # epoch aggregation stays ON DEVICE (a float() here would
                    # reintroduce the per-dispatch sync the cadence just paid
                    # off); fetched once after the epoch's last dispatch
                    chunk = jnp.sum(ms["loss"])
                    tot_dev = chunk if tot_dev is None else tot_dev + chunk
                    n += idx.shape[0]
                    rec.on_step(
                        epoch, ms, loss=losses, params=state.params,
                        batch_info={"dispatch": "scan", "idx": idx, "snrs": snrs},
                    )
                    if losses is not None and (n // scan_k) % max(
                        cfg.train.print_freq // scan_k, 1
                    ) == 0:
                        logger.log(step=int(state.step), epoch=epoch, loss=float(losses[-1]))
                if tot_dev is not None:
                    tot = float(jax.device_get(tot_dev))
                    # epoch-aggregate watchdog check: NaN propagates through
                    # the on-device sum, so divergence still trips (at epoch
                    # granularity) even when the cadence fetched no losses —
                    # probe_every=0's only armed loss check
                    rec.on_epoch_loss(epoch, tot)
            else:
                for batch in train_loader.epoch(epoch):
                    pb = place_train(batch)
                    if not cost_done:
                        maybe_emit_cost("hdce_train_step", train_step, state, pb)
                        cost_done = True
                    with clock.step() as st:
                        state, m = train_step(state, pb)
                        st.transfer()
                        loss = float(m["loss"])
                    rec.on_step(
                        epoch, m, loss=loss, params=state.params,
                        batch_info={"dispatch": "step", "step_in_epoch": n},
                    )
                    tot, n = tot + loss, n + 1
                    if n % cfg.train.print_freq == 0:
                        logger.log(step=int(state.step), epoch=epoch, loss=loss)
        clock.epoch_end(epoch=epoch)
        train_loss = tot / max(n, 1)

        sums = {"err": 0.0, "pow": 0.0, "err_perf": 0.0, "pow_perf": 0.0}
        with span("val_epoch", epoch=epoch):
            for batch in val_loader.epoch(epoch, shuffle=False):
                out = eval_step(state, place_val(batch))
                for k in sums:
                    sums[k] += float(out[k])
        val_nmse = sums["err"] / max(sums["pow"], 1e-30)
        val_perf = sums["err_perf"] / max(sums["pow_perf"], 1e-30)
        history["train_loss"].append(train_loss)
        history["val_nmse"].append(val_nmse)
        history["val_nmse_perf"].append(val_perf)
        logger.log(
            epoch=epoch,
            train_loss=train_loss,
            val_nmse=val_nmse,
            val_nmse_db=nmse_db(val_nmse),
            val_nmse_perf=val_perf,
        )

        if workdir is not None:
            meta = {"epoch": epoch, "val_nmse": val_nmse, "name": cfg.name}
            if val_nmse < best:
                best = val_nmse
                payload = {"params": state.params, "batch_stats": state.batch_stats}
                save_checkpoint(workdir, "hdce_best", payload, meta)
            # full state (params + optimizer + step) for resume — this IS the
            # "last" checkpoint (its params are a superset), so `hdce_last`
            # is only materialised once at the end, halving per-epoch IO.
            save_train_state(workdir, "hdce_resume", state, {**meta, "best": best})
    if workdir is not None:
        save_checkpoint(
            workdir,
            "hdce_last",
            {"params": state.params, "batch_stats": state.batch_stats},
            {"epoch": cfg.train.n_epochs - 1, "name": cfg.name},
        )
    return state, history
