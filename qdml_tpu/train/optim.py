"""Optimizers and LR schedule (reference ``get_optimizer`` + manual decay).

Reference facts reproduced:
- 'adam' -> Adam, 'sgd' -> SGD(momentum=0.9), anything else ->
  NotImplementedError (``Runner_P128_QuantumNAT_onchipQNN.py:40-46``);
- the QSC trainer uses AdamW(lr=1e-3, weight_decay=0.01) (``Runner...py:320``);
- LR is halved every ``lr_decay_epochs`` (30) epochs with floor 1e-6
  (``Runner...py:272-283``) — here an optax schedule instead of a manual
  param-group mutation;
- on-chip-QNN gradient pruning slots in FRONT of the optimizer
  (``Runner...py:364-369``) as an optax transform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from qdml_tpu.config import QuantumConfig, TrainConfig
from qdml_tpu.ops.grad_prune import gradient_prune


def scale_by_adam_lowp(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    moments_dtype=jnp.bfloat16,
    nu_dtype=jnp.float32,
) -> optax.GradientTransformation:
    """Adam moment estimation with the first-moment tree STORED in a low dtype.

    The Adam update of a large weight is HBM-bandwidth-bound, and two of the
    four trees it streams are the moments (measured on v5e: the fused
    head-weight grad+update runs at ~730 GB/s ~ HBM peak,
    results/perf_r5/scan_rbg.trace.json.gz). Storing mu in bfloat16 cuts a
    quarter of that traffic. All arithmetic — decay, square, bias correction,
    rsqrt — runs in f32; only the carried state is rounded.

    The second moment nu stays in ``nu_dtype`` (f32 by default, ADVICE r5
    medium): nu's per-step relative change is (1-b2) = 1e-3, below the bf16
    half-ulp (~4e-3), so a bf16-stored nu EMA cannot decay — ``b2*v +
    (1-b2)*g^2`` rounds back to ``v`` whenever ``g^2`` is within ~5x of
    ``v``, and nu only ratchets up on spikes, suppressing the effective step
    size long after gradients shrink. mu's (1-b1) = 0.1 per-step change is
    well above bf16 ulp, so its EMA tracks fine. Long-horizon observation:
    ``tests/test_train.py::test_adam_lowp_nu_tracks_decaying_gradients``;
    per-step agreement: ``test_adam_lowp_matches_f32``.
    """

    def init(params):
        return optax.ScaleByAdamState(
            count=jnp.zeros([], jnp.int32),
            mu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=moments_dtype), params
            ),
            nu=jax.tree_util.tree_map(
                lambda p: jnp.zeros_like(p, dtype=nu_dtype), params
            ),
        )

    def update(grads, state, params=None):
        del params
        f32 = lambda t: t.astype(jnp.float32)
        mu = jax.tree_util.tree_map(
            lambda m, g: (b1 * f32(m) + (1.0 - b1) * g).astype(moments_dtype),
            state.mu,
            grads,
        )
        nu = jax.tree_util.tree_map(
            lambda v, g: (b2 * f32(v) + (1.0 - b2) * g * g).astype(nu_dtype),
            state.nu,
            grads,
        )
        count = optax.safe_int32_increment(state.count)
        bc1 = 1.0 - b1**count.astype(jnp.float32)
        bc2 = 1.0 - b2**count.astype(jnp.float32)
        updates = jax.tree_util.tree_map(
            lambda m, v: (f32(m) / bc1) / (jnp.sqrt(f32(v) / bc2) + eps), mu, nu
        )
        return updates, optax.ScaleByAdamState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def lr_schedule(cfg: TrainConfig, steps_per_epoch: int) -> optax.Schedule:
    """Step-indexed schedule: halve every ``lr_decay_epochs`` epochs, floored."""

    def sched(step):
        epoch = step // max(steps_per_epoch, 1)
        lr = cfg.lr * 0.5 ** (epoch // cfg.lr_decay_epochs)
        return jnp.maximum(lr, cfg.lr_floor)

    return sched


_MOMENTS_DTYPES = ("float32", "bfloat16")


def get_optimizer(
    cfg: TrainConfig,
    steps_per_epoch: int,
    quantum: QuantumConfig | None = None,
) -> optax.GradientTransformation:
    sched = lr_schedule(cfg, steps_per_epoch)
    moments = getattr(cfg, "moments_dtype", "float32")
    # Same rejection contract as data.rng_impl (ADVICE r5 low): a typo like
    # 'bf16' must not silently select the f32 path.
    if moments not in _MOMENTS_DTYPES:
        raise ValueError(
            f"moments_dtype must be one of {_MOMENTS_DTYPES}, got {moments!r}"
        )
    lowp = moments == "bfloat16"
    if lowp and cfg.optimizer != "adam":
        import warnings

        warnings.warn(
            f"moments_dtype='bfloat16' applies only to optimizer='adam'; "
            f"optimizer {cfg.optimizer!r} keeps float32 moments",
            stacklevel=2,
        )
    if cfg.optimizer == "adam":
        base = (
            optax.chain(scale_by_adam_lowp(), optax.scale_by_learning_rate(sched))
            if lowp
            else optax.adam(sched)
        )
    elif cfg.optimizer == "adamw":
        base = optax.adamw(sched, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        base = optax.sgd(sched, momentum=cfg.momentum)
    else:
        raise NotImplementedError(f"optimizer {cfg.optimizer!r}")  # Runner...py:46
    if quantum is not None and quantum.use_gradient_pruning:
        return optax.chain(
            gradient_prune(quantum.gradient_threshold, quantum.gradient_prune_mode),
            base,
        )
    return base
