"""Optimizers and LR schedule (reference ``get_optimizer`` + manual decay).

Reference facts reproduced:
- 'adam' -> Adam, 'sgd' -> SGD(momentum=0.9), anything else ->
  NotImplementedError (``Runner_P128_QuantumNAT_onchipQNN.py:40-46``);
- the QSC trainer uses AdamW(lr=1e-3, weight_decay=0.01) (``Runner...py:320``);
- LR is halved every ``lr_decay_epochs`` (30) epochs with floor 1e-6
  (``Runner...py:272-283``) — here an optax schedule instead of a manual
  param-group mutation;
- on-chip-QNN gradient pruning slots in FRONT of the optimizer
  (``Runner...py:364-369``) as an optax transform.
"""

from __future__ import annotations

import jax.numpy as jnp
import optax

from qdml_tpu.config import QuantumConfig, TrainConfig
from qdml_tpu.ops.grad_prune import gradient_prune


def lr_schedule(cfg: TrainConfig, steps_per_epoch: int) -> optax.Schedule:
    """Step-indexed schedule: halve every ``lr_decay_epochs`` epochs, floored."""

    def sched(step):
        epoch = step // max(steps_per_epoch, 1)
        lr = cfg.lr * 0.5 ** (epoch // cfg.lr_decay_epochs)
        return jnp.maximum(lr, cfg.lr_floor)

    return sched


def get_optimizer(
    cfg: TrainConfig,
    steps_per_epoch: int,
    quantum: QuantumConfig | None = None,
) -> optax.GradientTransformation:
    sched = lr_schedule(cfg, steps_per_epoch)
    if cfg.optimizer == "adam":
        base = optax.adam(sched)
    elif cfg.optimizer == "adamw":
        base = optax.adamw(sched, weight_decay=cfg.weight_decay)
    elif cfg.optimizer == "sgd":
        base = optax.sgd(sched, momentum=cfg.momentum)
    else:
        raise NotImplementedError(f"optimizer {cfg.optimizer!r}")  # Runner...py:46
    if quantum is not None and quantum.use_gradient_pruning:
        return optax.chain(
            gradient_prune(quantum.gradient_threshold, quantum.gradient_prune_mode),
            base,
        )
    return base
