"""Unified telemetry layer: run manifests, spans, device counters, reports.

Every entrypoint (CLI commands, ``bench.py``, the train loops, the eval
sweeps) routes its observability through this package so no benchmark or
metrics artifact is ever orphaned from its provenance again:

- :func:`run_manifest` — one self-describing header record per run (config +
  hash, git SHA, JAX/device topology, effective perf knobs, seeds), written
  as the first line of every telemetry/metrics JSONL;
- :func:`span` — nested wall-clock timing spans (``with span("compile"):``),
  multihost-aware (only the primary process writes; events carry the process
  index) with an automatic bridge into an active ``jax.profiler`` trace;
- :class:`StepClock` / :class:`Histogram` / :func:`device_memory_snapshot` —
  per-interval device counters: step-time percentiles (p50/p95/max, not just
  means), host-transfer time, live-buffer/memory stats where the backend
  exposes them, and the persistent-compile-cache hit/miss counters
  (``qdml_tpu.utils.compile_cache``);
- :mod:`qdml_tpu.telemetry.report` — the ``qdml-tpu report`` regression gate
  over one or more telemetry artifacts vs a committed baseline;
- :mod:`qdml_tpu.telemetry.timeseries` / :mod:`~qdml_tpu.telemetry.burnrate`
  — the ``qdml-tpu monitor`` flight deck: metrics-verb-only scraping of a
  running serve/route address, counter differencing into fixed windows, and
  multi-window SLO error-budget burn-rate alerting with an event-correlated
  timeline;
- :mod:`qdml_tpu.telemetry.capacity` — the ``qdml-tpu plan`` trace-replay
  capacity planner, validated against committed dryrun windows;
- :mod:`qdml_tpu.telemetry.events` — the event spine: every subsystem's
  structured events on one process-global :class:`EventBus` (common
  envelope, bounded ring, explicit drop counter), tailed live over the
  wire via the ``{"op": "events"}`` verb / ``qdml-tpu events``.

The long-standing ``MetricsLogger`` (``qdml_tpu.utils.metrics``), ``StepTimer``
and ``trace()`` (``qdml_tpu.utils.profiling``) are thin facades over this
layer — their call sites and test pins are unchanged. File formats and span
conventions: ``docs/TELEMETRY.md``.
"""

from qdml_tpu.telemetry.core import Telemetry, is_primary  # noqa: F401
from qdml_tpu.telemetry import cost  # noqa: F401
from qdml_tpu.telemetry.numerics import (  # noqa: F401
    DivergenceError,
    FlightRecorder,
    Watchdog,
    probe_tree,
)
from qdml_tpu.telemetry.counters import (  # noqa: F401
    Histogram,
    StepClock,
    device_memory_snapshot,
)
from qdml_tpu.telemetry.manifest import (  # noqa: F401
    config_hash,
    effective_knobs,
    run_manifest,
)
from qdml_tpu.telemetry.events import (  # noqa: F401
    EventBus,
    ensure_bus,
    get_bus,
    install_bus,
    publish,
)
from qdml_tpu.telemetry.spans import (  # noqa: F401
    get_sink,
    profiler_trace,
    set_sink,
    span,
)
from qdml_tpu.telemetry.tracing import (  # noqa: F401
    PHASES,
    TraceContext,
    trace_sampled,
)
from qdml_tpu.telemetry.timeseries import (  # noqa: F401
    MonitorScraper,
    SnapshotDiff,
    counter_delta,
)
from qdml_tpu.telemetry.burnrate import (  # noqa: F401
    BurnAlerter,
    BurnRateRule,
    burn_rate,
    render_timeline,
)
