"""``qdml-tpu report``: regression-aware markdown summary over telemetry files.

Loads one or more *current* artifacts (telemetry/metrics JSONL with a manifest
header, a bench one-line record, a committed ``results/bench_tpu_*.json``, or
a driver ``BENCH_rNN.json`` wrapper) plus one *baseline* artifact, extracts
every throughput metric both sides share, and emits a markdown delta table.
Exits nonzero (:data:`EXIT_REGRESSION`) when any shared metric regressed by
more than the threshold — the CI gate future TPU sessions run before
promoting a headline.

Platform honesty: a cpu_fallback artifact is not comparable to a tpu-* one
(the r4 "206-vs-451 sps regression" was host contention, not code); when the
two sides ran on different platforms the deltas are still reported but the
gate is disarmed, with a note saying so.

Usage (via the CLI):

    python -m qdml_tpu.cli report --current=PATH[,PATH...] --baseline=PATH \
        [--threshold=10] [--out=report.md]
"""

from __future__ import annotations

import json
import os
import re
from typing import Any

# Per-implementation QSC sub-benches (qsc_dense, qsc_pallas, ... — NOT the
# scan-fused variants, which measure a different program). These are
# implementation-race entrants, not independent workloads: the gate compares
# best-of-impls on each side, so a fixed impl losing ground (or being
# retired) cannot fail CI while a faster dispatch is available — the exact
# "gating on a losing fixed impl" failure the autotuned dispatcher removes.
# qsc_auto is deliberately NOT demoted: the auto-dispatched path IS the
# train/serve hot path, so a qsc_auto regression (e.g. a stale table
# dispatching a loser while a fixed impl still measures fast) must fail the
# gate like any other hot-path metric — it still feeds best-of-impls too.
_QSC_IMPL_RE = re.compile(r"^qsc_(?!auto\.)(?!.*scan)[^.]+\.samples_per_sec$")
_QSC_BEST_RE = re.compile(r"^qsc_(?!.*scan)[^.]+\.samples_per_sec$")
QSC_BEST_KEY = "qsc.best_of_impls"

EXIT_OK = 0
EXIT_USAGE = 2
EXIT_REGRESSION = 3

DEFAULT_THRESHOLD_PCT = 10.0


def _iter_objs(path: str) -> list[Any]:
    """Parse a file as one JSON value or as JSONL; skip unparseable lines."""
    with open(path) as fh:
        text = fh.read().strip()
    if not text:
        return []
    try:
        return [json.loads(text)]
    except json.JSONDecodeError:
        pass
    objs = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            objs.append(json.loads(line))
        except json.JSONDecodeError:
            continue
    return objs


def _record_from(obj: dict) -> dict | None:
    """A bench-style record from a raw object, unwrapping driver wrappers."""
    if "metric" in obj and "value" in obj:
        return obj
    parsed = obj.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = obj.get("tail")
    if isinstance(tail, str):
        for line in reversed(tail.strip().splitlines()):
            try:
                cand = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(cand, dict) and "metric" in cand:
                return cand
    return None


def _serving_from(obj: dict) -> dict | None:
    """Latency/throughput/SLO/fleet numbers from a ``serve_summary``
    telemetry record (the loadgen harness writes one per run). Latency
    percentiles live in a separate namespace from throughput because their
    regression sign is inverted: serving got WORSE when latency went UP.
    SLO attainment inverts the other way (a DROP is the regression), and the
    fleet block (replicas × devices) makes rps deltas attributable to
    scale-out vs speed-up."""
    if obj.get("kind") != "serve_summary":
        return None
    out: dict = {
        "latency": {},
        "rps": None,
        "platform": obj.get("platform"),
        "phases": None,
        "trace": None,
        "slo_attainment": None,
        "fleet": None,
        "n_scenarios": None,
        "dispatch": None,
        "overflow_rate": None,
        "goodput_rps": None,
        "padding_waste": None,
        "batching": None,
        "stranded_futures": None,
        "breaker_open_fraction": None,
        "router": None,
    }
    lat = obj.get("latency_ms") or {}
    for key in ("p50_ms", "p95_ms", "p99_ms"):
        if isinstance(lat.get(key), (int, float)):
            out["latency"][key] = float(lat[key])
    if isinstance(obj.get("rps"), (int, float)):
        out["rps"] = float(obj["rps"])
    # goodput-first serving metrics (ragged-batching PR): useful-rows/s gates
    # like rps (lower = regression); padding waste — the dispatched-row
    # fraction XLA computed for nothing — gates absolutely like the sparse
    # overflow rate (near-zero baselines make ratios meaningless)
    if isinstance(obj.get("goodput_rps"), (int, float)):
        out["goodput_rps"] = float(obj["goodput_rps"])
    if isinstance(obj.get("padding_waste"), (int, float)):
        out["padding_waste"] = float(obj["padding_waste"])
    batching = obj.get("batching")
    if isinstance(batching, dict):
        out["batching"] = {
            "mode": batching.get("mode"),
            "continuous_admission": batching.get("continuous_admission"),
        }
    # resilience metrics (fault-tolerance PR): stranded futures gate
    # always-armed at 0 (a client hung forever is a protocol violation on
    # any hardware); the breaker open fraction gates absolutely like the
    # overflow rate (healthy runs sit at 0.0 — ratios are meaningless)
    if isinstance(obj.get("stranded_futures"), int):
        out["stranded_futures"] = obj["stranded_futures"]
    brk = obj.get("breaker")
    if isinstance(brk, dict) and isinstance(
        brk.get("open_fraction"), (int, float)
    ):
        out["breaker_open_fraction"] = float(brk["open_fraction"])
    # per-phase latency decomposition (request tracing, docs/TELEMETRY.md):
    # the sampled traced fraction's batch_wait/queue_wait/compute/fetch/wire
    # histograms plus the coverage fact — the report's attribution input (a
    # p99 move gates per phase, so it is blamed on the phase that moved)
    phases = obj.get("phases")
    if isinstance(phases, dict):
        ph = {k: v for k, v in phases.items() if isinstance(v, dict)}
        out["phases"] = ph or None
    tr = obj.get("trace")
    if isinstance(tr, dict):
        out["trace"] = tr
    slo = obj.get("slo")
    if isinstance(slo, dict) and isinstance(slo.get("attainment"), (int, float)):
        out["slo_attainment"] = float(slo["attainment"])
    fleet = {}
    if isinstance(obj.get("replicas"), int):
        fleet["replicas"] = obj["replicas"]
    if isinstance(obj.get("workers"), int):
        fleet["workers"] = obj["workers"]
    mesh = obj.get("mesh")
    if isinstance(mesh, dict) and isinstance(mesh.get("devices"), int):
        fleet["devices"] = mesh["devices"]
    if isinstance(obj.get("rps_per_replica"), (int, float)):
        fleet["rps_per_replica"] = float(obj["rps_per_replica"])
    out["fleet"] = fleet or None
    # scenario scale-out facts (sparse-dispatch PR): expert-family count,
    # the routing mode the warmup race baked in, and the sparse
    # overflow-fallback rate — a rising rate is an O(S) compute leak the
    # gate must catch even while rps still looks healthy
    if isinstance(obj.get("n_scenarios"), int):
        out["n_scenarios"] = obj["n_scenarios"]
    disp = obj.get("dispatch")
    if isinstance(disp, dict):
        out["dispatch"] = {
            "mode": disp.get("mode"),
            "capacity_factor": disp.get("capacity_factor"),
        }
        if isinstance(disp.get("overflow_rate"), (int, float)):
            out["overflow_rate"] = float(disp["overflow_rate"])
    # fleet-router facts (docs/FLEET.md): a loadgen window measured THROUGH
    # the router tier carries the router's own ledger — backend count,
    # balancing policy, failovers/ejections — so the fleet line names the
    # topology the latency/goodput deltas were measured across
    rt = obj.get("router")
    if isinstance(rt, dict):
        out["router"] = {
            "backends": rt.get("backends"),
            "backends_live": rt.get("backends_live"),
            "balance": rt.get("balance"),
            "failovers": rt.get("failovers"),
            "ejections": rt.get("ejections"),
            "dedup_hits": rt.get("dedup_hits"),
        }
    return out


def extract(path: str) -> dict:
    """Pull ``{manifest, record, throughput, serving, cost, platform}`` out
    of one artifact. ``cost`` maps a program key (a bench sub-bench name, a
    train-loop ``cost`` record name, or ``serve_bucket[N]``) to its XLA cost
    block (:func:`qdml_tpu.telemetry.cost.analyze` shape)."""
    src: dict = {
        "path": path,
        "manifest": None,
        "record": None,
        "throughput": {},
        "serving": None,
        "cost": {},
        "roofline": {},
        "host_transfers": {},
        "platform": None,
        "qsc_scaling": None,
        "scenario_scaling": None,
        "monitor": None,
    }
    for obj in _iter_objs(path):
        if not isinstance(obj, dict):
            continue
        if obj.get("kind") == "manifest":
            # last wins: an appended/resumed stream carries one manifest per
            # invocation, and the last record belongs to the last invocation
            src["manifest"] = obj
            continue
        if obj.get("kind") == "monitor_summary":
            # the flight deck's end-of-attachment rollup (qdml-tpu monitor):
            # burn-rate peaks, alert counts by mark/signal, planner
            # validation — last wins like every other summary record
            src["monitor"] = obj
            continue
        if obj.get("kind") == "cost" and obj.get("name"):
            key = str(obj["name"])
            if obj.get("bucket") is not None:
                key = f"{key}[{obj['bucket']}]"
            src["cost"][key] = obj  # last record per program wins
            continue
        serving = _serving_from(obj)
        if serving is not None:
            src["serving"] = serving  # last serve_summary wins
            if serving["rps"] is not None:
                # completed-request throughput rides the existing gate
                # (lower = regression, same as samples/sec)
                src["throughput"]["serve.rps"] = serving["rps"]
            if serving["goodput_rps"] is not None:
                # goodput (useful-rows/s) rides the same gate: padded rows
                # never count, so a mode that pads more cannot inflate it
                src["throughput"]["serve.goodput_rps"] = serving["goodput_rps"]
            if serving["platform"] and not src["platform"]:
                # serving-only artifacts carry their backend too, so the
                # platform-mismatch disarm covers latency gates (a bench
                # record in the same stream keeps precedence)
                src["platform"] = serving["platform"]
            continue
        rec = _record_from(obj)
        if rec is not None:
            src["record"] = rec  # last record in the stream wins
    rec = src["record"]
    if rec is not None:
        src["platform"] = rec.get("platform") or src["platform"]
        if isinstance(rec.get("value"), (int, float)):
            src["throughput"][rec.get("metric") or "value"] = float(rec["value"])
        for key, d in (rec.get("details") or {}).items():
            if not isinstance(d, dict):
                continue
            if key == "qsc_scaling" and isinstance(d.get("points"), list):
                # The qubit-scaling axis: each point's measured number is
                # already best-of-impls AT THAT n (the dispatcher raced the
                # candidates and the winner was timed), so every n-bucket
                # gates as its own throughput metric — n=16 regressing
                # cannot hide behind n=6 improving. The zero-padded key
                # keeps the table sorted by qubit count.
                src["qsc_scaling"] = d
                for p in d["points"]:
                    if isinstance(p, dict) and isinstance(
                        p.get("samples_per_sec"), (int, float)
                    ):
                        nk = f"qsc_scaling.n{int(p['n_qubits']):02d}"
                        src["throughput"][f"{nk}.best_of_impls"] = float(
                            p["samples_per_sec"]
                        )
                continue
            if key == "scenario_scaling" and isinstance(d.get("points"), list):
                # The scenario-scaling axis, gated exactly like the qubit
                # one: each point's measured number is already
                # best-of-dispatch AT THAT S (the routing race timed the
                # loser too), so every S-bucket gates as its own metric —
                # S=64 regressing cannot hide behind S=3 improving.
                src["scenario_scaling"] = d
                for p in d["points"]:
                    if isinstance(p, dict) and isinstance(
                        p.get("samples_per_sec"), (int, float)
                    ):
                        sk = f"scenario_scaling.s{int(p['n_scenarios']):02d}"
                        src["throughput"][f"{sk}.best_of_dispatch"] = float(
                            p["samples_per_sec"]
                        )
                continue
            if isinstance(d.get("samples_per_sec"), (int, float)):
                src["throughput"][f"{key}.samples_per_sec"] = float(d["samples_per_sec"])
            if isinstance(d.get("cost"), dict):
                src["cost"][key] = d["cost"]
            # achieved-vs-roofline fraction (bench train records since the
            # latency-floor PR): gated with an inverted-improvement sign —
            # the fraction DROPPING is the regression
            roof = d.get("roofline")
            if isinstance(roof, dict) and isinstance(roof.get("fraction"), (int, float)):
                src["roofline"][key] = float(roof["fraction"])
            # steady-state host transfers inside the timed loop: 0 by
            # construction; any reappearance is a program-property failure
            if isinstance(d.get("host_transfers"), (int, float)):
                src["host_transfers"][key] = int(d["host_transfers"])
    # Synthesized best-of-impls QSC metric: the regression gate for the
    # quantum classifier compares the fastest implementation measured on each
    # side (the per-impl rows stay in the table, informational).
    impl_vals = [v for k, v in src["throughput"].items() if _QSC_BEST_RE.match(k)]
    if impl_vals:
        src["throughput"][QSC_BEST_KEY] = max(impl_vals)
    return src


def _manifest_line(src: dict) -> str | None:
    man = src.get("manifest")
    if not man:
        return None
    jx = man.get("jax") or {}
    bits = []
    if man.get("config_hash"):
        bits.append(f"config `{man['config_hash']}`")
    if man.get("git"):
        sha = man["git"].get("sha", "")[:12]
        bits.append(f"git `{sha}`" + ("*" if man["git"].get("dirty") else ""))
    if jx.get("backend"):
        bits.append(
            f"{jx.get('device_count', '?')}x {jx.get('backend')} "
            f"({jx.get('process_count', 1)} proc)"
        )
    knobs = man.get("knobs")
    if knobs:
        bits.append(
            "knobs rng={rng_impl}/trig={trig_impl}/moments={moments_dtype}".format(**knobs)
        )
    if not bits:
        return None
    return f"  - manifest `{os.path.basename(src['path'])}`: " + ", ".join(bits)


def _pct(cur: float, base: float) -> float | None:
    """Relative delta, or None for a zero baseline — a ratio against zero is
    undefined, and the alternative (float inf) leaks bare ``Infinity`` into
    the strict-JSON ``--json`` gate output."""
    return (cur - base) / base * 100.0 if base else None


def _cost_deltas(base_cost: dict, cur_cost: dict) -> dict | None:
    """FLOPs/bytes deltas between two available cost blocks; None when either
    side has no comparable numbers."""
    out = {}
    for field in ("flops", "bytes_accessed"):
        b, c = base_cost.get(field), cur_cost.get(field)
        if isinstance(b, (int, float)) and isinstance(c, (int, float)) and b:
            out[field] = {"baseline": b, "current": c, "delta_pct": round(_pct(c, b), 2)}
    return out or None


# A regressed benchmark whose program also changed by more than this is
# flagged "program change" — the regression may be MORE work, not slower
# execution of the same work.
PROGRAM_CHANGE_PCT = 1.0

# Absolute slack on the sparse-dispatch overflow-fallback rate (fraction of
# routed rows): healthy runs sit at/near 0.0, so the gate compares absolute
# rates, not ratios — 2 points of new overflow is a capacity-factor misfit
# worth failing on, whatever the baseline was.
OVERFLOW_RATE_SLACK = 0.02

# Absolute slack on the serving padding-waste fraction (padded rows /
# dispatched rows), gated like the overflow rate and for the same reason: a
# well-tiered deployment sits near 0 where ratios explode. 5 points of new
# padding is a tier ladder (or admission policy) that no longer fits the
# traffic's fill distribution — FLOPs burned on rows nobody asked for.
PADDING_WASTE_SLACK = 0.05

# Absolute slack on the circuit-breaker open fraction (fast-failed submits /
# offered submits), same absolute-comparison rationale: a healthy window
# sits at 0.0. 5 points of new brownout means the breaker spent a
# meaningful share of the window open — either the watermarks misfit the
# traffic or capacity regressed under it.
BREAKER_OPEN_SLACK = 0.05


def _lint_gate(lint_path: str | None) -> dict | None:
    """Row data from a ``qdml-tpu lint --json`` artifact. The lint gate is
    host-side static analysis: platform disarm rules never apply to it."""
    if lint_path is None:
        return None
    try:
        with open(lint_path) as fh:
            lint = json.load(fh)
    except (OSError, json.JSONDecodeError) as e:
        return {"path": lint_path, "ok": False, "new_findings": None,
                "error": f"{type(e).__name__}: {e}"}
    return {
        "path": lint_path,
        "ok": bool(lint.get("ok")) and int(lint.get("new_findings") or 0) == 0,
        "new_findings": int(lint.get("new_findings") or 0),
        "suppressed": lint.get("suppressed"),
        "baselined": lint.get("baselined"),
        "per_rule": lint.get("per_rule") or {},
        "error": None,
    }


def build_report_data(
    current_paths: list[str],
    baseline_path: str,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
    lint_path: str | None = None,
) -> dict:
    """Full machine-readable report: markdown + per-gate rows + cost deltas.

    Returns ``{"markdown", "gates", "regressions", "gate_armed",
    "disarm_reason", "cost", "threshold_pct", ...}`` — the ``--json`` output
    is this dict minus the markdown, so CI consumes the same resolution the
    human-facing table shows (no markdown parsing)."""
    base = extract(baseline_path)
    curs = [extract(p) for p in current_paths]
    cur_tp: dict[str, float] = {}
    for c in curs:
        cur_tp.update(c["throughput"])
    cur_cost: dict[str, dict] = {}
    for c in curs:
        cur_cost.update(c["cost"])
    gates: list[dict] = []
    cost_rows: list[dict] = []
    disarm_reason: str | None = None
    # Platform resolution must match the value resolution (later files win a
    # shared metric, so the later file's platform labels the merged set);
    # heterogeneous current platforms disarm the gate below.
    cur_platforms = [c["platform"] for c in curs if c["platform"]]
    cur_platform = cur_platforms[-1] if cur_platforms else None

    lines = [
        "# qdml-tpu telemetry report",
        "",
        f"- baseline: `{baseline_path}`"
        + (f" (platform {base['platform']})" if base["platform"] else ""),
        "- current: " + ", ".join(f"`{p}`" for p in current_paths)
        + (f" (platform {cur_platform})" if cur_platform else ""),
        f"- regression threshold: {threshold_pct:g}%",
    ]
    for src in [base] + curs:
        man_line = _manifest_line(src)
        if man_line:
            lines.append(man_line)
    lines.append("")

    regressions: list[dict] = []
    gate_armed = True
    transfer_failed = False
    stranded_failed = False
    monitor_failed = False

    # Lint gate (qdml-tpu lint --json artifact): folded in alongside the perf
    # gates so CI reads ONE exit code. Static analysis is host-side — the
    # platform-mismatch disarm below never applies to this row, and a lint
    # failure alone forces the regression exit code (report_main).
    lint = _lint_gate(lint_path)
    if lint is not None:
        if lint["error"]:
            status, detail = "regression", f"unreadable lint artifact: {lint['error']}"
        elif lint["ok"]:
            status = "ok"
            detail = (
                f"0 new findings ({lint['suppressed']} suppressed, "
                f"{lint['baselined']} baselined)"
            )
        else:
            status = "regression"
            per_rule = ", ".join(f"{k}: {v}" for k, v in lint["per_rule"].items())
            detail = f"{lint['new_findings']} new finding(s) — {per_rule or 'see artifact'}"
        gates.append(
            {"metric": "lint.new_findings", "kind": "lint",
             "baseline": 0, "current": lint["new_findings"],
             "delta_pct": None, "status": status}
        )
        lines.append(f"- lint gate (`{lint['path']}`): **{status}** — {detail}")
        lines.append("")
        if status == "regression":
            regressions.append(
                {"metric": "lint.new_findings", "baseline": 0,
                 "current": lint["new_findings"], "delta_pct": None}
            )

    if len(set(cur_platforms)) > 1:
        gate_armed = False
        disarm_reason = (
            f"current artifacts span platforms {sorted(set(cur_platforms))}"
        )
        lines.append(
            f"> **note**: current artifacts span platforms {sorted(set(cur_platforms))} "
            "— merged deltas are not attributable to one platform, regression "
            "gate disarmed."
        )
        lines.append("")
    elif base["platform"] and cur_platform and base["platform"] != cur_platform:
        gate_armed = False
        disarm_reason = (
            f"platform mismatch: baseline {base['platform']} vs current {cur_platform}"
        )
        lines.append(
            f"> **note**: platform mismatch (baseline {base['platform']} vs "
            f"current {cur_platform}) — deltas shown, regression gate disarmed "
            "(cross-platform throughput ratios compare hardware/contention, "
            "not code)."
        )
        lines.append("")

    def _data(note: str | None = None) -> dict:
        return {
            "schema": 1,
            "baseline": baseline_path,
            "current": list(current_paths),
            "threshold_pct": threshold_pct,
            "baseline_platform": base["platform"],
            "current_platform": cur_platform,
            "gate_armed": gate_armed,
            "disarm_reason": disarm_reason,
            "gates": gates,
            "regressions": regressions,
            "cost": cost_rows,
            "lint": lint,
            # lint failures force the regression exit even when the perf gate
            # is platform-disarmed: static analysis ran on THIS host's source
            "lint_failed": bool(lint is not None and not lint["ok"]),
            # a reappearing steady-state host transfer is a PROGRAM property
            # (the bench loop is transfer-free by construction), so like lint
            # it forces the regression exit even under platform disarm
            "transfer_failed": transfer_failed,
            # a stranded future (a client hung forever) violates the serving
            # protocol's resolution invariant on ANY hardware — always-armed
            # like lint, forces the regression exit under platform disarm
            "stranded_failed": stranded_failed,
            # monitor invariants (alert expectations + planner validation)
            # are correctness properties of the observability stack itself —
            # always-armed like lint/stranded, forces the regression exit
            "monitor_failed": monitor_failed,
            "note": note,
            "markdown": "\n".join(lines),
        }

    if not base["throughput"]:
        lines.append(
            "_baseline carries no throughput metrics (nothing to gate; "
            "e.g. a targets-only BASELINE.json)._"
        )
        return _data("baseline carries no throughput metrics")
    if not cur_tp:
        # A baseline with numbers and a current run that measured NOTHING is
        # a gate failure, not a pass: the fully-errored bench path still
        # writes a record (value null, error-only details), and CI must not
        # promote it. Armed regardless of platform tags — "nothing measured"
        # is a failure on any hardware.
        lines.append(
            "_current artifacts carry no throughput metrics — **gate fails**: "
            "an all-errored run cannot demonstrate the absence of a "
            "regression._"
        )
        regressions.append(
            {"metric": "(no throughput measured)", "baseline": None,
             "current": None, "delta_pct": None}
        )
        # the sentinel is a real gate row too: --json consumers iterating
        # `gates` must see WHAT failed, not just exit_code 3
        gates.append(
            {"metric": "(no throughput measured)", "kind": "throughput",
             "baseline": None, "current": None, "delta_pct": None,
             "status": "regression"}
        )
        gate_armed, disarm_reason = True, None
        return _data("current artifacts carry no throughput metrics")

    lines += [
        "| metric | baseline | current | delta | status |",
        "|---|---|---|---|---|",
    ]
    for key in sorted(set(base["throughput"]) | set(cur_tp)):
        b = base["throughput"].get(key)
        c = cur_tp.get(key)
        if b is None or c is None:
            only = "current-only" if b is None else "baseline-only"
            gates.append(
                {"metric": key, "kind": "throughput", "baseline": b,
                 "current": c, "delta_pct": None, "status": only}
            )
            lines.append(
                f"| {key} | {'—' if b is None else f'{b:g}'} | "
                f"{'—' if c is None else f'{c:g}'} | — | {only} |"
            )
            continue
        delta_pct = _pct(c, b)
        program_change = None
        if delta_pct is None:
            gates.append(
                {"metric": key, "kind": "throughput", "baseline": b, "current": c,
                 "delta_pct": None, "status": "zero-baseline"}
            )
            lines.append(f"| {key} | {b:g} | {c:g} | — | zero-baseline |")
            continue
        if delta_pct < -threshold_pct:
            if _QSC_IMPL_RE.match(key):
                # one entrant of the QSC implementation race slowed down;
                # the gate judges the race's winner (qsc.best_of_impls), so
                # a losing fixed impl can no longer fail CI by itself
                gates.append(
                    {"metric": key, "kind": "throughput", "baseline": b,
                     "current": c, "delta_pct": round(delta_pct, 2),
                     "status": "informational"}
                )
                lines.append(
                    f"| {key} | {b:g} | {c:g} | {delta_pct:+.1f}% | "
                    "informational (best-of-impls gates QSC) |"
                )
                continue
            status_key, status_md = "regression", "**REGRESSION**"
            # Perf regression vs program change: when the regressed
            # sub-bench's own XLA cost moved too, the slowdown is (at least
            # partly) MORE WORK, not slower execution of the same program.
            prog = key.rsplit(".", 1)[0]
            deltas = None
            if prog in base["cost"] and prog in cur_cost:
                deltas = _cost_deltas(base["cost"][prog], cur_cost[prog])
            if deltas and any(
                abs(d["delta_pct"]) > PROGRAM_CHANGE_PCT for d in deltas.values()
            ):
                program_change = deltas
                status_key = "regression+program-change"
                status_md += " (program changed)"
            reg = {"metric": key, "baseline": b, "current": c,
                   "delta_pct": round(delta_pct, 2)}
            if program_change:
                reg["program_change"] = program_change
            regressions.append(reg)
        elif delta_pct > threshold_pct:
            status_key = status_md = "improved"
        else:
            status_key = status_md = "ok"
        row = {"metric": key, "kind": "throughput", "baseline": b, "current": c,
               "delta_pct": round(delta_pct, 2), "status": status_key}
        if program_change:
            row["program_change"] = program_change
        gates.append(row)
        lines.append(f"| {key} | {b:g} | {c:g} | {delta_pct:+.1f}% | {status_md} |")

    # Serving-latency section: tail percentiles from serve_summary records.
    # The delta sign is INVERTED relative to throughput — latency going UP
    # beyond the threshold is the regression; the same platform rules arm
    # the gate (cross-platform latencies compare hardware, not code).
    base_lat = (base.get("serving") or {}).get("latency") or {}
    cur_lat: dict[str, float] = {}
    for c_src in curs:
        cur_lat.update((c_src.get("serving") or {}).get("latency") or {})
    if base_lat or cur_lat:
        lines += [
            "",
            "## serving latency",
            "",
        ]
        # fleet topology line: a serve.rps delta between 1 replica on 1
        # device and 4 replicas on 8 is scale-out, not speed-up — name the
        # topologies so the aggregate-rps gate reads attributably
        def _fleet_str(src):
            serving = src.get("serving") or {}
            f = serving.get("fleet")
            if not f and not serving.get("router"):
                return None
            # a socket window measured THROUGH the router tier has no
            # in-process fleet block — the router facts alone still make a
            # fleet line (the topology the numbers were measured across)
            if not f:
                s = "router front"
            else:
                topo = [f"{f.get('replicas', '?')} replica(s)"]
                if f.get("devices"):
                    topo.append(f"{f['devices']} device(s)")
                s = " x ".join(topo)
                if f.get("rps_per_replica") is not None:
                    s += f" ({f['rps_per_replica']:g} rps/replica)"
            # scenario scale-out facts ride the fleet line: expert-family
            # count, which routing dispatch the race baked in, and the
            # sparse overflow-fallback rate when one was measured
            if serving.get("n_scenarios") is not None:
                s += f", S={serving['n_scenarios']}"
            disp = serving.get("dispatch")
            if disp and disp.get("mode"):
                s += f" {disp['mode']}-dispatch"
                if serving.get("overflow_rate") is not None:
                    s += f" (overflow {serving['overflow_rate']:.2%})"
            # batching mode rides the fleet line too: a p99/goodput delta
            # between a bucket fleet and a ragged one is a MODE change, and
            # the reader must see it named (the bucket-vs-ragged dryrun's
            # whole comparison hangs on this label)
            bat = serving.get("batching")
            if bat and bat.get("mode"):
                s += f" {bat['mode']}-batching"
                if serving.get("padding_waste") is not None:
                    s += f" (pad waste {serving['padding_waste']:.2%})"
            # the fleet-router line: a window measured through the router
            # tier names how many hosts it spanned and the balancing policy
            # — a p99 delta across different fan-outs is topology, not code
            rt = serving.get("router")
            if rt and rt.get("backends"):
                s += (
                    f", via router over {rt['backends']} backend(s)"
                    f" [{rt.get('balance', '?')}]"
                )
                if rt.get("backends_live") is not None and (
                    rt["backends_live"] != rt["backends"]
                ):
                    s += f" ({rt['backends_live']} live)"
                if rt.get("failovers"):
                    s += f", {rt['failovers']} failover(s)"
            return s

        base_fleet = _fleet_str(base)
        cur_fleet = next(
            (s for s in (_fleet_str(c) for c in reversed(curs)) if s), None
        )
        if base_fleet or cur_fleet:
            lines.append(
                f"- fleet: baseline {base_fleet or 'n/a'} -> current "
                f"{cur_fleet or 'n/a'}"
            )
            lines.append("")
        lines += [
            "| percentile | baseline | current | delta | status |",
            "|---|---|---|---|---|",
        ]
        for key in ("p50_ms", "p95_ms", "p99_ms"):
            b = base_lat.get(key)
            c = cur_lat.get(key)
            if b is None and c is None:
                continue
            if b is None or c is None:
                only = "current-only" if b is None else "baseline-only"
                gates.append(
                    {"metric": f"serving.{key}", "kind": "latency", "baseline": b,
                     "current": c, "delta_pct": None, "status": only}
                )
                lines.append(
                    f"| {key} | {'—' if b is None else f'{b:g}'} | "
                    f"{'—' if c is None else f'{c:g}'} | — | {only} |"
                )
                continue
            delta_pct = _pct(c, b)
            if delta_pct is None:
                gates.append(
                    {"metric": f"serving.{key}", "kind": "latency", "baseline": b,
                     "current": c, "delta_pct": None, "status": "zero-baseline"}
                )
                lines.append(f"| {key} | {b:g} | {c:g} | — | zero-baseline |")
                continue
            if delta_pct > threshold_pct:
                status_key, status_md = "regression", "**REGRESSION**"
                regressions.append(
                    {"metric": f"serving.{key}", "baseline": b, "current": c,
                     "delta_pct": round(delta_pct, 2)}
                )
            elif delta_pct < -threshold_pct:
                status_key = status_md = "improved"
            else:
                status_key = status_md = "ok"
            gates.append(
                {"metric": f"serving.{key}", "kind": "latency", "baseline": b,
                 "current": c, "delta_pct": round(delta_pct, 2), "status": status_key}
            )
            lines.append(f"| {key} | {b:g} | {c:g} | {delta_pct:+.1f}% | {status_md} |")

    # Phase-decomposition section (request tracing, docs/TELEMETRY.md): the
    # per-phase p99s from the traced sample, each gated EXACTLY like the
    # end-to-end latency percentiles (up beyond threshold = regression, same
    # platform arming rules) — so an end-to-end p99 move is ATTRIBUTED to
    # the phase that moved instead of staying one opaque number. Router-
    # aggregated blocks that carry only exact (n, sum, mean) — quantiles
    # cannot cross a process boundary — contribute no p99 row and are shown
    # as coverage only.
    base_ph = (base.get("serving") or {}).get("phases") or {}
    cur_ph: dict[str, dict] = {}
    cur_trace: dict | None = None
    for c_src in curs:
        s_serving = c_src.get("serving") or {}
        if s_serving.get("phases"):
            cur_ph.update(s_serving["phases"])
        if s_serving.get("trace"):
            cur_trace = s_serving["trace"]
    if base_ph or cur_ph:
        from qdml_tpu.telemetry.tracing import PHASES as _PHASE_ORDER

        lines += ["", "## serving phase decomposition (where the time goes)", ""]
        if cur_trace is not None:
            cov = (
                f"sampled {cur_trace.get('sampled', '?')} of "
                f"{cur_trace.get('completed', '?')} completed requests"
            )
            if isinstance(cur_trace.get("fraction"), (int, float)):
                cov += f" ({cur_trace['fraction']:.1%})"
            rec = cur_trace.get("reconciliation")
            if isinstance(rec, dict) and rec.get("attributed_fraction") is not None:
                cov += (
                    f"; phases attribute {rec['attributed_fraction']:.1%} of "
                    "end-to-end latency"
                )
            lines.append(f"- trace coverage: {cov}")
        lines.append(
            "- clock-skew rule: every phase is a single-clock duration — wire "
            "time is router-measured around its own exchange; two hosts' "
            "clocks are never differenced"
        )
        lines += [
            "",
            "| phase | baseline p99 (ms) | current p99 (ms) | delta | status |",
            "|---|---|---|---|---|",
        ]
        phase_moved: list[str] = []
        names = [p for p in _PHASE_ORDER if p in base_ph or p in cur_ph]
        names += sorted((set(base_ph) | set(cur_ph)) - set(names))
        for name in names:
            b = (base_ph.get(name) or {}).get("p99_ms")
            c = (cur_ph.get(name) or {}).get("p99_ms")
            metric = f"serve.phase.{name}.p99_ms"
            if b is None and c is None:
                continue  # exact-sum-only blocks: no quantile to gate
            if b is None or c is None:
                only = "current-only" if b is None else "baseline-only"
                gates.append(
                    {"metric": metric, "kind": "phase", "baseline": b,
                     "current": c, "delta_pct": None, "status": only}
                )
                lines.append(
                    f"| {name} | {'—' if b is None else f'{b:g}'} | "
                    f"{'—' if c is None else f'{c:g}'} | — | {only} |"
                )
                continue
            delta_pct = _pct(c, b)
            if delta_pct is None:
                gates.append(
                    {"metric": metric, "kind": "phase", "baseline": b,
                     "current": c, "delta_pct": None, "status": "zero-baseline"}
                )
                lines.append(f"| {name} | {b:g} | {c:g} | — | zero-baseline |")
                continue
            if delta_pct > threshold_pct:
                status_key, status_md = "regression", "**REGRESSION**"
                phase_moved.append(f"{name} ({delta_pct:+.1f}%)")
                regressions.append(
                    {"metric": metric, "baseline": b, "current": c,
                     "delta_pct": round(delta_pct, 2)}
                )
            elif delta_pct < -threshold_pct:
                status_key = status_md = "improved"
            else:
                status_key = status_md = "ok"
            gates.append(
                {"metric": metric, "kind": "phase", "baseline": b, "current": c,
                 "delta_pct": round(delta_pct, 2), "status": status_key}
            )
            lines.append(
                f"| {name} | {b:g} | {c:g} | {delta_pct:+.1f}% | {status_md} |"
            )
        if phase_moved:
            e2e = next(
                (r for r in regressions if r["metric"] == "serving.p99_ms"), None
            )
            lines.append("")
            lines.append(
                "- p99 attribution: the "
                + (
                    f"end-to-end p99 move ({e2e['delta_pct']:+.1f}%) "
                    if e2e
                    else "tail move "
                )
                + "is carried by: "
                + ", ".join(phase_moved)
            )

    # Serving-SLO gate: attainment = fraction of deadline-carrying requests
    # answered within their deadline (serve_summary.slo.attainment). The
    # sign works like roofline-fraction: a DROP beyond the threshold is the
    # regression; the same platform rules arm it (attainment under load is a
    # hardware-throughput-shaped number).
    b_slo = (base.get("serving") or {}).get("slo_attainment")
    c_slo = None
    for c_src in curs:
        v = (c_src.get("serving") or {}).get("slo_attainment")
        if v is not None:
            c_slo = v
    if b_slo is not None or c_slo is not None:
        if not (base_lat or cur_lat):
            # an all-shed run can carry an SLO figure with NO latency
            # samples — give the bullet its own section instead of
            # orphaning it under the throughput table
            lines += ["", "## serving"]
        if b_slo is None or c_slo is None:
            only = "current-only" if b_slo is None else "baseline-only"
            gates.append(
                {"metric": "serve.slo_attainment", "kind": "slo", "baseline": b_slo,
                 "current": c_slo, "delta_pct": None, "status": only}
            )
            lines.append(
                f"- serving SLO attainment: "
                f"{'—' if b_slo is None else f'{b_slo:g}'} -> "
                f"{'—' if c_slo is None else f'{c_slo:g}'} ({only})"
            )
        else:
            delta_pct = _pct(c_slo, b_slo)
            if delta_pct is None:
                status_key = status_md = "zero-baseline"
            elif delta_pct < -threshold_pct:
                status_key, status_md = "regression", "**REGRESSION**"
                regressions.append(
                    {"metric": "serve.slo_attainment", "baseline": b_slo,
                     "current": c_slo, "delta_pct": round(delta_pct, 2)}
                )
            elif delta_pct > threshold_pct:
                status_key = status_md = "improved"
            else:
                status_key = status_md = "ok"
            gates.append(
                {"metric": "serve.slo_attainment", "kind": "slo",
                 "baseline": b_slo, "current": c_slo,
                 "delta_pct": None if delta_pct is None else round(delta_pct, 2),
                 "status": status_key}
            )
            lines.append(
                f"- serving SLO attainment: {b_slo:g} -> {c_slo:g} "
                + (f"({delta_pct:+.1f}%) " if delta_pct is not None else "")
                + f"{status_md}"
            )

    # Absolute-slack serving gates (one shared shape, two metrics): both
    # compare ABSOLUTELY, not as ratios — healthy baselines sit at/near 0.0
    # where a relative delta is undefined or explosive. Regression when the
    # current fraction exceeds the baseline by more than the metric's slack.
    def _absolute_gate(field: str, metric: str, kind: str, slack: float,
                       label: str) -> None:
        b_val = (base.get("serving") or {}).get(field)
        c_val = None
        for c_src in curs:
            v = (c_src.get("serving") or {}).get(field)
            if v is not None:
                c_val = v
        if b_val is None and c_val is None:
            return
        if b_val is None or c_val is None:
            only = "current-only" if b_val is None else "baseline-only"
            gates.append(
                {"metric": metric, "kind": kind, "baseline": b_val,
                 "current": c_val, "delta_pct": None, "status": only}
            )
            lines.append(
                f"- {label}: {'—' if b_val is None else f'{b_val:g}'} -> "
                f"{'—' if c_val is None else f'{c_val:g}'} ({only})"
            )
            return
        if c_val > b_val + slack:
            status_key, status_md = "regression", "**REGRESSION**"
            regressions.append(
                {"metric": metric, "baseline": b_val, "current": c_val,
                 "delta_pct": None}
            )
        elif c_val < b_val - slack:
            status_key = status_md = "improved"
        else:
            status_key = status_md = "ok"
        gates.append(
            {"metric": metric, "kind": kind, "baseline": b_val,
             "current": c_val, "delta_pct": None, "status": status_key}
        )
        lines.append(f"- {label}: {b_val:g} -> {c_val:g} {status_md}")

    # Sparse-dispatch overflow: the fraction of routed rows the capacity
    # buckets could NOT hold (served by the dense fallback — never dropped,
    # but each one is O(S) compute for O(1) work); rising = the capacity
    # factor no longer fits the traffic skew.
    _absolute_gate("overflow_rate", "serve.overflow_rate", "dispatch",
                   OVERFLOW_RATE_SLACK, "sparse-dispatch overflow rate")
    # Serving padding waste: the fraction of dispatched rows that were
    # padding (serve_summary.padding_waste — goodput's complement); rising =
    # the tier ladder (or admission policy) no longer fits the traffic's
    # fill levels — compute the goodput gate cannot see while rps still
    # looks healthy.
    _absolute_gate("padding_waste", "serve.padding_waste", "batching",
                   PADDING_WASTE_SLACK, "serving padding waste")
    # Circuit-breaker open fraction: fast-failed submits / offered submits
    # (serve_summary.breaker.open_fraction); rising = the breaker spent a
    # meaningful share of the window browning out — capacity regressed under
    # the traffic, or the watermarks no longer fit it.
    _absolute_gate("breaker_open_fraction", "serve.breaker_open_fraction",
                   "breaker", BREAKER_OPEN_SLACK, "breaker open fraction")

    # Stranded-futures gate: ALWAYS-ARMED, baseline pinned at the invariant
    # (0), like the lint and host-transfer gates — a future that never
    # resolved is a client hung forever, a protocol violation no platform
    # mismatch can excuse. Reported only when the current window measured it
    # (serve_summary.stranded_futures; old baselines without the field never
    # disarm the check).
    c_stranded = None
    for c_src in curs:
        v = (c_src.get("serving") or {}).get("stranded_futures")
        if v is not None:
            c_stranded = v
    if c_stranded is not None:
        st_status = "ok" if c_stranded == 0 else "regression"
        gates.append(
            {"metric": "serve.stranded_futures", "kind": "resilience",
             "baseline": 0, "current": c_stranded, "delta_pct": None,
             "status": st_status}
        )
        lines.append(
            f"- stranded futures (always-armed, invariant 0): {c_stranded} "
            + ("ok" if st_status == "ok" else "**REGRESSION**")
        )
        if st_status == "regression":
            stranded_failed = True
            regressions.append(
                {"metric": "serve.stranded_futures", "baseline": 0,
                 "current": c_stranded, "delta_pct": None}
            )

    # Monitoring section (qdml-tpu monitor, docs/TELEMETRY.md "flight
    # deck"): the burn-rate alerting and the capacity planner are part of
    # the observability stack itself, so their invariants gate ALWAYS-ARMED
    # like lint/stranded — a monitor that fails to page during an injected
    # fault (or pages on a healthy baseline) is broken on any hardware.
    cur_mon = None
    for c_src in curs:
        if c_src.get("monitor") is not None:
            cur_mon = c_src["monitor"]  # last monitor_summary wins
    if cur_mon is not None:
        lines += ["", "## monitoring (flight deck)", ""]
        alerts = cur_mon.get("alerts") or {}
        lines.append(
            f"- monitor: {cur_mon.get('windows', 0)} windows at "
            f"{cur_mon.get('interval_s', 0)}s, "
            f"{cur_mon.get('scrape_errors', 0)} scrape errors, "
            f"{cur_mon.get('counter_resets', 0)} counter resets, "
            f"{alerts.get('fired', 0)} alert(s) fired / "
            f"{alerts.get('resolved', 0)} resolved"
        )
        # peak burn per signal: informational — the alert-expectation gate
        # below is the pass/fail judgment, the peaks say how close it came
        peaks = cur_mon.get("peak_burn") or {}
        hot = {
            s: p for s, p in peaks.items()
            if isinstance(p, dict) and (p.get("fast") or 0) > 0
        }
        if hot:
            lines.append(
                "- peak burn (fast/slow x budget): " + ", ".join(
                    f"{s} {p.get('fast', 0):g}/{p.get('slow', 0):g}"
                    for s, p in sorted(hot.items())
                )
            )
        by_mark = alerts.get("by_mark") or {}
        expect = cur_mon.get("expect") or {}
        for mark in sorted(expect.get("fired") or []):
            fired = int(by_mark.get(mark, 0))
            ok = fired > 0
            gates.append(
                {"metric": f"monitor.alerts[{mark}]", "kind": "monitor",
                 "baseline": 1, "current": fired, "delta_pct": None,
                 "status": "ok" if ok else "regression"}
            )
            lines.append(
                f"- alert expectation `{mark}` (fault injected, >=1 must "
                f"fire): {fired} " + ("ok" if ok else "**REGRESSION**")
            )
            if not ok:
                monitor_failed = True
                regressions.append(
                    {"metric": f"monitor.alerts[{mark}]", "baseline": 1,
                     "current": fired, "delta_pct": None}
                )
        for mark in sorted(expect.get("quiet") or []):
            fired = int(by_mark.get(mark, 0))
            ok = fired == 0
            gates.append(
                {"metric": f"monitor.alerts[{mark}]", "kind": "monitor",
                 "baseline": 0, "current": fired, "delta_pct": None,
                 "status": "ok" if ok else "regression"}
            )
            lines.append(
                f"- alert expectation `{mark}` (healthy window, none may "
                f"fire): {fired} " + ("ok" if ok else "**REGRESSION**")
            )
            if not ok:
                monitor_failed = True
                regressions.append(
                    {"metric": f"monitor.alerts[{mark}]", "baseline": 0,
                     "current": fired, "delta_pct": None}
                )
        planner = cur_mon.get("planner")
        if isinstance(planner, dict):
            p_ok = bool(planner.get("ok"))
            gates.append(
                {"metric": "monitor.planner_validation", "kind": "monitor",
                 "baseline": None, "current": planner.get("max_p99_ratio"),
                 "delta_pct": None,
                 "status": "ok" if p_ok else "regression"}
            )
            band = planner.get("band") or {}
            lines.append(
                f"- planner validation ({planner.get('n_windows', 0)} "
                f"windows, p99 within x{band.get('p99_factor', '?')} "
                f"(wire-mode x{band.get('wire_p99_factor', '?')}), "
                f"rps within {band.get('rps_frac', '?')}): max p99 ratio "
                f"{planner.get('max_p99_ratio')}, max rps err "
                f"{planner.get('max_rps_err')} "
                + ("ok" if p_ok else "**REGRESSION**")
            )
            if not p_ok:
                monitor_failed = True
                regressions.append(
                    {"metric": "monitor.planner_validation", "baseline": None,
                     "current": planner.get("max_p99_ratio"),
                     "delta_pct": None}
                )
        # Event-spine loss ledger (telemetry/events.py): a monitor that
        # tailed the spine commits event_drops = ring evictions + cursor
        # lost. Zero means the committed stream saw EVERY envelope the
        # fleet published — any loss voids the correlation evidence below,
        # so this arms whenever the summary carries the counter.
        drops = cur_mon.get("event_drops")
        if drops is not None:
            d_ok = int(drops) == 0
            gates.append(
                {"metric": "monitor.event_drops", "kind": "monitor",
                 "baseline": 0, "current": int(drops), "delta_pct": None,
                 "status": "ok" if d_ok else "regression"}
            )
            spine = cur_mon.get("spine") or {}
            lines.append(
                f"- event spine: {spine.get('events', 0)} envelope(s) "
                f"tailed, loss ledger {int(drops)} "
                f"(ring {spine.get('ring_dropped', 0)} / cursor "
                f"{spine.get('cursor_lost', 0)}) "
                + ("ok" if d_ok else "**REGRESSION**")
            )
            if not d_ok:
                monitor_failed = True
                regressions.append(
                    {"metric": "monitor.event_drops", "baseline": 0,
                     "current": int(drops), "delta_pct": None}
                )
        # Hands-off loop (telemetry/attach.py): the attachment must never
        # have given up, every decision made under a burn alert must carry
        # the alert-episode id (the by-id join between monitor_alert and
        # fleet_scale_event), and when the dryrun EXPECTS an alert-driven
        # scale-up (expect.scale_up_correlated) at least one up-decision
        # must actually be stamped with an episode.
        hands = cur_mon.get("handsoff")
        if isinstance(hands, dict):
            scale_events = hands.get("scale_events") or []
            uncorrelated = [
                e for e in scale_events
                if e.get("burn_alert") and not e.get("alert_episode")
            ]
            corr_ups = [
                e for e in scale_events
                if e.get("direction") == "up" and e.get("alert_episode")
            ]
            h_ok = hands.get("give_up") is None and not uncorrelated
            if expect.get("scale_up_correlated") and not corr_ups:
                h_ok = False
            gates.append(
                {"metric": "monitor.handsoff", "kind": "monitor",
                 "baseline": None, "current": len(scale_events),
                 "delta_pct": None,
                 "status": "ok" if h_ok else "regression"}
            )
            lines.append(
                f"- hands-off loop: {hands.get('ticks', 0)} tick(s), "
                f"{len(scale_events)} scale decision(s) "
                f"({len(corr_ups)} alert-correlated up), "
                f"{hands.get('reattaches', 0)} reattach(es), give-up "
                f"{'none' if hands.get('give_up') is None else hands['give_up'].get('reason')} "
                + ("ok" if h_ok else "**REGRESSION**")
            )
            if not h_ok:
                monitor_failed = True
                regressions.append(
                    {"metric": "monitor.handsoff", "baseline": None,
                     "current": len(scale_events), "delta_pct": None}
                )

    # Roofline section: achieved-vs-roofline fraction per train sub-bench
    # (bench.py details.*.roofline.fraction — telemetry/cost.py). The sign is
    # inverted like latency in spirit but the metric is a fraction of the
    # hardware ceiling: the fraction DROPPING beyond the threshold is the
    # regression (the fused path slid back toward dispatch-/transfer-bound).
    # Platform rules arm it like throughput — a fraction is measured against
    # THIS platform's ridge, so cross-platform deltas compare hardware.
    base_roof = base.get("roofline") or {}
    cur_roof: dict[str, float] = {}
    for c_src in curs:
        cur_roof.update(c_src.get("roofline") or {})
    if base_roof or cur_roof:
        lines += [
            "",
            "## roofline fraction (achieved / ceiling at program intensity)",
            "",
            "| program | baseline | current | delta | status |",
            "|---|---|---|---|---|",
        ]
        for key in sorted(set(base_roof) | set(cur_roof)):
            b = base_roof.get(key)
            c = cur_roof.get(key)
            metric = f"{key}.roofline_fraction"
            if b is None or c is None:
                only = "current-only" if b is None else "baseline-only"
                gates.append(
                    {"metric": metric, "kind": "roofline", "baseline": b,
                     "current": c, "delta_pct": None, "status": only}
                )
                lines.append(
                    f"| {key} | {'—' if b is None else f'{b:g}'} | "
                    f"{'—' if c is None else f'{c:g}'} | — | {only} |"
                )
                continue
            delta_pct = _pct(c, b)
            if delta_pct is None:
                gates.append(
                    {"metric": metric, "kind": "roofline", "baseline": b,
                     "current": c, "delta_pct": None, "status": "zero-baseline"}
                )
                lines.append(f"| {key} | {b:g} | {c:g} | — | zero-baseline |")
                continue
            if delta_pct < -threshold_pct:
                status_key, status_md = "regression", "**REGRESSION**"
                regressions.append(
                    {"metric": metric, "baseline": b, "current": c,
                     "delta_pct": round(delta_pct, 2)}
                )
            elif delta_pct > threshold_pct:
                status_key = status_md = "improved"
            else:
                status_key = status_md = "ok"
            gates.append(
                {"metric": metric, "kind": "roofline", "baseline": b,
                 "current": c, "delta_pct": round(delta_pct, 2), "status": status_key}
            )
            lines.append(f"| {key} | {b:g} | {c:g} | {delta_pct:+.1f}% | {status_md} |")

    # Qubit-scaling section: the n=4..24 axis (bench.py --scaling /
    # scripts/qubit_scaling_sweep.py). The per-n GATES already sit in the
    # throughput table above (qsc_scaling.nNN.best_of_impls — each point is
    # the dispatcher's measured winner at that n, i.e. best-of-impls by
    # construction); this section is the human-facing crossover view: which
    # impl won each n, at what chi, and what it beat.
    cur_scaling = next(
        (c.get("qsc_scaling") for c in reversed(curs) if c.get("qsc_scaling")),
        None,
    )
    if cur_scaling is not None:
        pts = [p for p in cur_scaling.get("points", []) if isinstance(p, dict)]
        lines += [
            "",
            "## qubit scaling (best-of-impls per n)",
            "",
            f"- topology: {cur_scaling.get('devices_on_model', '?')} device(s) "
            f"on the model axis, platform {cur_scaling.get('platform', '?')}",
            "",
            "| n | impl | chi | batch | samples/s | vs next | agreement |",
            "|---|---|---|---|---|---|---|",
        ]
        for p in sorted(pts, key=lambda p: p.get("n_qubits", 0)):
            n = p.get("n_qubits", "?")
            if "error" in p and "samples_per_sec" not in p:
                lines.append(f"| {n} | — | — | — | — | — | error: {p['error']} |")
                continue
            impl = p.get("quantum_impl", "?")
            chi = p.get("mps_chi", "—")
            # margin over the best losing candidate's train time, straight
            # off the recorded race
            cands = p.get("candidates") or {}
            timed = {
                k: v["train_ms"]
                for k, v in cands.items()
                if isinstance(v, dict)
                and isinstance(v.get("train_ms"), (int, float))
                and k != impl
            }
            if timed and isinstance(
                (cands.get(impl) or {}).get("train_ms"), (int, float)
            ):
                k2 = min(timed, key=timed.get)
                ratio = timed[k2] / cands[impl]["train_ms"]
                vs_next = f"{ratio:.2f}x vs {k2}"
            else:
                vs_next = "only candidate" if impl != "?" else "—"
            agr = p.get("agreement") or {}
            if agr.get("max_abs_delta") is not None:
                agree = f"{agr['max_abs_delta']:.2e} vs {agr.get('reference')}"
            else:
                agree = "—"
            sps = p.get("samples_per_sec")
            lines.append(
                f"| {n} | {impl} | {chi} | {p.get('batch', '—')} | "
                f"{sps if sps is not None else '—'} | {vs_next} | {agree} |"
            )

    # Scenario-scaling section: the S=3..64 axis (bench.py --scenario-scaling
    # / scripts/scenario_scaling_sweep.py). The per-S GATES already sit in
    # the throughput table (scenario_scaling.sNN.best_of_dispatch — each
    # point is the routing race's measured winner at that S); this section is
    # the human-facing crossover view: which dispatch won each S, at what
    # capacity, and what it beat.
    cur_sscaling = next(
        (
            c.get("scenario_scaling")
            for c in reversed(curs)
            if c.get("scenario_scaling")
        ),
        None,
    )
    if cur_sscaling is not None:
        pts = [p for p in cur_sscaling.get("points", []) if isinstance(p, dict)]
        lines += [
            "",
            "## scenario scaling (best-of-dispatch per S)",
            "",
            f"- platform {cur_sscaling.get('platform', '?')}, capacity factor "
            f"{cur_sscaling.get('capacity_factor', '?')}",
            "",
            "| S | dispatch | capacity | batch | rows/s | vs other | agreement |",
            "|---|---|---|---|---|---|---|",
        ]
        for p in sorted(pts, key=lambda p: p.get("n_scenarios", 0)):
            s_n = p.get("n_scenarios", "?")
            if "error" in p and "samples_per_sec" not in p:
                lines.append(f"| {s_n} | — | — | — | — | — | error: {p['error']} |")
                continue
            mode = p.get("dispatch", "?")
            cands = p.get("candidates") or {}
            timed = {
                k: v["infer_ms"]
                for k, v in cands.items()
                if isinstance(v, dict)
                and isinstance(v.get("infer_ms"), (int, float))
                and k != mode
            }
            if timed and isinstance(
                (cands.get(mode) or {}).get("infer_ms"), (int, float)
            ):
                k2 = min(timed, key=timed.get)
                vs = f"{timed[k2] / cands[mode]['infer_ms']:.2f}x vs {k2}"
            else:
                vs = "only candidate" if mode != "?" else "—"
            agr = p.get("agreement") or {}
            agree = (
                f"{agr['max_abs_delta']:.2e}"
                if isinstance(agr.get("max_abs_delta"), (int, float))
                else "—"
            )
            sps = p.get("samples_per_sec")
            lines.append(
                f"| {s_n} | {mode} | {p.get('capacity', '—')} | "
                f"{p.get('batch', '—')} | {sps if sps is not None else '—'} | "
                f"{vs} | {agree} |"
            )

    # Steady-state host-transfer gate: the bench's timed loops are
    # transfer-free by construction (0 committed in every record) and run
    # under the strict device->host transfer guard on accelerator backends;
    # a reintroduced sync trips the guard and bench.py records the failed
    # sub-bench with host_transfers=1 — so "current > baseline" is the
    # reachable failure signal, not a hypothetical. A program property,
    # armed regardless of platform (like the lint gate).
    base_ht = base.get("host_transfers") or {}
    cur_ht: dict[str, int] = {}
    for c_src in curs:
        cur_ht.update(c_src.get("host_transfers") or {})
    ht_rows = []
    for key in sorted(set(base_ht) & set(cur_ht)):
        b, c = base_ht[key], cur_ht[key]
        if c > b:
            transfer_failed = True
            gates.append(
                {"metric": f"{key}.host_transfers", "kind": "host-transfers",
                 "baseline": b, "current": c, "delta_pct": None,
                 "status": "regression"}
            )
            regressions.append(
                {"metric": f"{key}.host_transfers", "baseline": b, "current": c,
                 "delta_pct": None}
            )
            ht_rows.append(f"- **{key}**: {b} -> {c} steady-state host transfer(s)")
        else:
            gates.append(
                {"metric": f"{key}.host_transfers", "kind": "host-transfers",
                 "baseline": b, "current": c, "delta_pct": None, "status": "ok"}
            )
    if ht_rows:
        lines += ["", "## steady-state host transfers — **REGRESSION**", ""] + ht_rows

    # Cost section: the XLA accounting for every program both sides measured.
    # A FLOPs/bytes delta is a PROGRAM change (config, lowering, fusion), a
    # regression with flat cost is an execution change — the table separates
    # the two failure stories.
    shared_cost = sorted(
        k
        for k in set(base["cost"]) & set(cur_cost)
        if base["cost"][k].get("available") and cur_cost[k].get("available")
    )
    if shared_cost:
        lines += [
            "",
            "## cost (XLA program accounting)",
            "",
            "| program | GFLOPs | Δ flops | MB accessed | Δ bytes | roofline |",
            "|---|---|---|---|---|---|",
        ]
        for k in shared_cost:
            bc, cc = base["cost"][k], cur_cost[k]
            deltas = _cost_deltas(bc, cc) or {}
            f_d = deltas.get("flops", {}).get("delta_pct")
            b_d = deltas.get("bytes_accessed", {}).get("delta_pct")
            changed = any(
                abs(d["delta_pct"]) > PROGRAM_CHANGE_PCT for d in deltas.values()
            )
            cost_rows.append(
                {"program": k, "baseline": {f: bc.get(f) for f in
                                            ("flops", "bytes_accessed", "peak_temp_bytes", "roofline")},
                 "current": {f: cc.get(f) for f in
                             ("flops", "bytes_accessed", "peak_temp_bytes", "roofline")},
                 "deltas": deltas, "program_changed": changed}
            )
            gflops = (
                f"{cc['flops'] / 1e9:.3f}" if isinstance(cc.get("flops"), (int, float)) else "—"
            )
            mb = (
                f"{cc['bytes_accessed'] / 1e6:.2f}"
                if isinstance(cc.get("bytes_accessed"), (int, float))
                else "—"
            )
            roof = cc.get("roofline", "unknown")
            if cc.get("roofline") != bc.get("roofline"):
                roof = f"{bc.get('roofline')} → {roof}"
            if changed:  # inside the last cell: a 7th cell would be dropped
                roof += " — **program changed**"
            lines.append(
                f"| {k} | {gflops} | "
                f"{'—' if f_d is None else f'{f_d:+.1f}%'} | {mb} | "
                f"{'—' if b_d is None else f'{b_d:+.1f}%'} | {roof} |"
            )

    lines.append("")
    flagged = [r for r in regressions if r.get("program_change")]
    if regressions:
        lines.append(
            f"**{len(regressions)} metric(s) regressed beyond {threshold_pct:g}%**"
            + ("" if gate_armed else " (gate disarmed: platform mismatch)")
        )
        if flagged:
            lines.append(
                f"- {len(flagged)} regression(s) coincide with a changed "
                "program (FLOPs/bytes moved): likely a config/lowering "
                "change, not a pure slowdown — "
                + ", ".join(r["metric"] for r in flagged)
            )
    else:
        lines.append("No regressions beyond threshold.")
    return _data()


def build_report(
    current_paths: list[str],
    baseline_path: str,
    threshold_pct: float = DEFAULT_THRESHOLD_PCT,
) -> tuple[str, list[dict], bool]:
    """Back-compat view of :func:`build_report_data`: ``(markdown,
    regressions, gate_armed)``. ``regressions`` lists every shared metric
    whose current value regressed beyond ``threshold_pct``; ``gate_armed``
    is False when the two sides ran on different platforms."""
    data = build_report_data(current_paths, baseline_path, threshold_pct)
    return data["markdown"], data["regressions"], data["gate_armed"]


def report_main(argv: list[str]) -> int:
    """CLI entry: parse ``--current/--baseline/--threshold/--out/--json``,
    print the markdown, return the gate's exit code. ``--json=PATH`` also
    writes the machine-readable gate output (per-gate status + deltas,
    disarm reason, cost deltas, the exit code itself) so CI consumes the
    gate without parsing markdown."""
    currents: list[str] = []
    baseline: str | None = None
    threshold = DEFAULT_THRESHOLD_PCT
    out: str | None = None
    json_out: str | None = None
    lint_path: str | None = None
    for arg in argv:
        if arg.startswith("--current="):
            currents += [p for p in arg.split("=", 1)[1].split(",") if p]
        elif arg.startswith("--baseline="):
            baseline = arg.split("=", 1)[1]
        elif arg.startswith("--lint="):
            lint_path = arg.split("=", 1)[1]
        elif arg.startswith("--threshold="):
            raw = arg.split("=", 1)[1]
            try:
                threshold = float(raw)
            except ValueError:
                print(f"report: --threshold must be a number, got {raw!r}")
                return EXIT_USAGE
        elif arg.startswith("--out="):
            out = arg.split("=", 1)[1]
        elif arg.startswith("--json="):
            json_out = arg.split("=", 1)[1]
        else:
            print(f"report: unrecognised argument {arg!r}")
            return EXIT_USAGE
    if not currents or baseline is None:
        print(
            "usage: qdml-tpu report --current=PATH[,PATH...] --baseline=PATH "
            "[--threshold=PCT] [--out=FILE.md] [--json=FILE.json] "
            "[--lint=LINT.json]"
        )
        return EXIT_USAGE
    for p in currents + [baseline]:
        if not os.path.exists(p):
            print(f"report: no such file {p!r}")
            return EXIT_USAGE
    data = build_report_data(currents, baseline, threshold, lint_path=lint_path)
    md = data["markdown"]
    print(md)
    rc = (
        EXIT_REGRESSION
        if (
            (data["regressions"] and data["gate_armed"])
            or data["lint_failed"]
            or data.get("transfer_failed")
            or data.get("stranded_failed")
            or data.get("monitor_failed")
        )
        else EXIT_OK
    )
    if out:
        os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
        with open(out, "w") as fh:
            fh.write(md + "\n")
    if json_out:
        payload = {k: v for k, v in data.items() if k != "markdown"}
        payload["exit_code"] = rc
        os.makedirs(os.path.dirname(json_out) or ".", exist_ok=True)
        with open(json_out, "w") as fh:
            json.dump(payload, fh, indent=2)
    return rc
