"""Runtime numerics sanitizer: ``jax.experimental.checkify`` wiring.

The static side of PR 4 (graftlint, :mod:`qdml_tpu.analysis`) catches hazard
*shapes*; this module catches hazard *values*: division by zero, NaN/Inf
production, and out-of-bounds indexing INSIDE the compiled programs, at the
op where they happen — where the flight recorder's probes only see the
aggregate damage a step later.

Opt-in by config flag, mirroring the ``probe_every=0`` static-flag pattern:

- ``--train.checkify=true`` threads checkify through the four train-step
  makers (``train/hdce.py``, ``train/dce.py``, ``train/qsc.py``,
  ``train/nat_sweep.py``). The checkified step returns its error value in
  the metrics dict (``checkify_err``); the :class:`~qdml_tpu.telemetry.
  numerics.FlightRecorder` promotes a tripped check into the existing
  dump-and-raise path — same post-mortem bundle, same typed
  :class:`~qdml_tpu.telemetry.numerics.DivergenceError`, same CLI exit 4.
- ``--serve.checkify=true`` wraps the serve engine's fused forward; a
  tripped check raises ``DivergenceError`` from ``infer``, which the serve
  loop forwards into every affected request future (typed failure, no hang).

OFF (the default) is free by construction: the flag never wraps, so the
traced program is byte-identical to the unflagged build — pinned by
``tests/test_analysis.py`` against the ``utils/compile_cache`` counters.
ON costs one functionalized error value through the program plus one
device->host error fetch per host-visible step (train) / batch (serve);
checkify's added checks also inhibit some fusions, so it is a debugging
mode, never the production default.

Scan-fused dispatch (``train.scan_steps >= 1`` — the default, K=1
included) falls back to per-step dispatch under checkify
(``train/scan.py::scan_eligible``, which records the reason in the run
JSONL): the per-step error fetch is the point of the mode, and a K-step
fused program would aggregate K steps' checks into one opaque trip.
"""

from __future__ import annotations

from typing import Any, Callable

_COMPAT_DONE = False


def _ensure_checkify_compat() -> None:
    """Backfill the checkify scatter-OOB rule for batched scatters.

    This container's jax (0.4.37) lowers ``take_along_axis`` (the NLL loss's
    log-prob pick, ``models/losses.py``) to a gather with
    ``operand_batching_dims``; its gradient is the matching batched
    scatter-add. ``checkify``'s ``scatter_oob`` predates batching dims:
    operand dims that are batching dims are neither inserted-window nor
    update-window dims, so ``update_window_dims[pos]`` indexes past the end
    — ``IndexError: tuple index out of range`` at trace time the moment
    index checks are enabled on any classifier train step (caught by driving
    ``train-sc --train.checkify=true`` on the real backend). The fix is the
    upstream one: batching dims take slice size 1, exactly like inserted
    window dims. Structurally gated (source probe), idempotent, and a no-op
    on jax versions that already handle batching dims — the same
    backfill-and-degrade contract as ``utils.platform.ensure_jax_compat``.
    """
    global _COMPAT_DONE
    if _COMPAT_DONE:
        return
    _COMPAT_DONE = True
    try:
        import inspect

        import numpy as np
        from jax import lax
        import jax.numpy as jnp
        from jax._src import checkify as _ck

        if "batching" in inspect.getsource(_ck.scatter_oob):
            return  # this jax already handles batched scatters

        def scatter_oob(operand, indices, updates, dnums):
            batching = getattr(dnums, "operand_batching_dims", ())
            slice_sizes = []
            pos = 0
            for i in range(len(operand.shape)):
                if i in dnums.inserted_window_dims or i in batching:
                    slice_sizes.append(1)
                else:
                    slice_sizes.append(updates.shape[dnums.update_window_dims[pos]])
                    pos += 1

            upper_bound = np.array(  # lint: disable=host-sync-hot-path(static-shape bounds built host-side at trace time — the upstream rule's own implementation)
                [operand.shape[i] - slice_sizes[i]
                 for i in dnums.scatter_dims_to_operand_dims],
                np.int64,
            )
            upper_bound = np.minimum(upper_bound, np.iinfo(indices.dtype).max)
            upper_bound = lax.broadcast_in_dim(
                upper_bound, indices.shape, (len(indices.shape) - 1,)
            )
            lower_oob = jnp.less(indices, 0)
            upper_oob = jnp.greater(indices, upper_bound.astype(indices.dtype))
            oob_mask = jnp.logical_or(lower_oob, upper_oob)
            payload = _ck.oob_payload(
                oob_mask, indices, dnums.scatter_dims_to_operand_dims, operand.shape
            )
            return jnp.any(oob_mask), payload

        _ck.scatter_oob = scatter_oob
    except Exception:  # lint: disable=broad-except(compat shim — a moved private API leaves checkify exactly as shipped)
        pass


def checks():
    """The error set: float (NaN/Inf), index OOB, and div-by-zero checks —
    the three silent-garbage classes QuantumNAT noise injection and
    statevector normalization can produce."""
    from jax.experimental import checkify

    _ensure_checkify_compat()
    return checkify.float_checks | checkify.index_checks | checkify.div_checks


def error_message(err: Any) -> str | None:
    """First tripped check's message, or None when the step was clean.
    HOST SYNC: fetches the error flag — callers pay this once per
    host-visible step, which is the cost of turning the sanitizer on."""
    msg = err.get()
    return msg if msg else None


def checkify_step(step_fn: Callable, donate: tuple[int, ...] = ()) -> Callable:
    """Wrap a traceable train step so its checkify error rides the metrics.

    ``step_fn(*args) -> (*state_parts, metrics_dict)`` (the convention all
    four trainers follow: the metrics dict is the LAST element). The wrapped
    callable has the identical signature and return shape, with
    ``metrics["checkify_err"]`` added — so the train loops and the flight
    recorder need no per-trainer plumbing. ``donate`` follows the same
    argument indices as the unwrapped jit (checkify preserves the
    signature)."""
    import jax
    from jax.experimental import checkify

    checked = checkify.checkify(step_fn, errors=checks())
    jitted = jax.jit(checked, donate_argnums=donate)

    def step(*args):
        err, out = jitted(*args)
        return (*out[:-1], {**out[-1], "checkify_err": err})

    return step
