"""Per-request phase tracing: where a serving request's latency actually went.

The serving story's tail-latency claims (ragged p99, recovery SLO, router
failover) all rest on ONE opaque number — the enqueue->result latency
histogram — so a p99 regression cannot be attributed to queue wait vs batch
coalescing vs device compute vs result fetch vs router wire. This module is
the attribution layer: a sampled :class:`TraceContext` rides each request
(trace id = the existing idempotent request id) and collects named phase
DURATIONS stamped at the five host-side boundaries that already exist —
client send, batcher enqueue, dequeue/dispatch, device-result fetch, and
future resolution — plus the router tier's per-attempt wire spans.

Non-negotiable contracts (docs/TELEMETRY.md "request tracing"):

- **Host-side only.** Tracing never touches jitted code: no phase stamp is
  reachable from a jit-compiled or pallas program (the ``trace-in-jit-path``
  graftlint rule enforces it — a wall-clock stamp inside a traced program
  would freeze at trace time, exactly the ``wall-clock-in-jit`` hazard). The
  serve executables are HLO-identical with tracing on or off, pinned.
- **Overhead-free when off.** ``serve.trace_sample=0`` (the default) builds
  no TraceContext, stamps no clock, adds no compiles and no host transfers
  — pinned in tests/test_tracing.py.
- **Single-clock durations only.** Every phase is a duration measured on ONE
  host's clock. Cross-process spans (router wire time) are measured by the
  process that owns both endpoints of the interval (the router times its own
  send->reply exchange); two hosts' clocks are NEVER differenced — clock
  skew would fabricate negative or inflated phases. The client-side
  reconciliation (loadgen) therefore reports an *unattributed* residual
  (client wall minus the sum of reported durations) rather than labeling it
  wire time.

Phase vocabulary (the per-phase ServeMetrics histograms and report gates):

- ``batch_wait`` — enqueue -> the batch's NEWEST member's enqueue: time this
  request spent waiting for later arrivals to coalesce with (continuous
  admission drives it toward 0; bucket coalescing pays up to ``max_wait_ms``);
- ``queue_wait`` — newest member's enqueue -> dequeue: the formed batch's
  wait for a free engine (shared by every request in the batch);
- ``compute`` — dispatch -> device results ready (the executable call plus
  the device fence, host-measured around the pre-compiled call);
- ``fetch`` — device->host copy of the reply arrays;
- ``wire`` — one router->backend exchange (router-measured; a failover
  retry adds a SEPARATE wire span per attempt, so a failed-over request's
  trace shows exactly where the retries went).

Routers may prepend auxiliary spans (``pick``, ``dedup_wait``); unknown
phase names histogram fine but only the five above carry report gates.
"""

from __future__ import annotations

import hashlib

# The gated phase vocabulary, in pipeline order. ServeMetrics accepts any
# phase name (routers add pick/dedup_wait), but these five are the report's
# decomposition gates.
PHASES: tuple[str, ...] = ("batch_wait", "queue_wait", "compute", "fetch", "wire")

_SAMPLE_BUCKETS = 1 << 16


def trace_sampled(rid, rate: float) -> bool:
    """Deterministic id-hash sampling: the same request id makes the same
    decision on the client, the router and every backend WITHOUT any
    coordination bit on the wire — a retried/failed-over id stays traced
    end to end. ``rate`` <= 0 never samples (the overhead-free default);
    >= 1 always; in between, a stable md5 bucket of ``str(rid)``."""
    if rate <= 0.0:
        return False
    if rate >= 1.0:
        return True
    h = int.from_bytes(hashlib.md5(str(rid).encode()).digest()[:4], "big")
    return (h % _SAMPLE_BUCKETS) < rate * _SAMPLE_BUCKETS


class TraceContext:
    """One request's ordered (phase, duration) spans + the end-to-end total.

    Durations are seconds internally (the Histogram convention) and
    milliseconds on the wire (the reply-latency convention). Phases may
    repeat — a failover retry appends one ``wire`` span per attempt.
    ``detail`` carries structured non-duration facts (the router's attempt
    table, dedup re-attachment) that ride the wire for humans and the dryrun
    checks but never enter a histogram.
    """

    __slots__ = ("rid", "phases", "total_s", "detail")

    def __init__(self, rid, phases=None, total_s: float | None = None,
                 detail: dict | None = None):
        self.rid = rid
        self.phases: list[tuple[str, float]] = list(phases or [])
        self.total_s = total_s
        self.detail = detail

    def add_phase(self, name: str, dur_s: float) -> None:
        """Append one measured span. Clamped at zero: a fake-clock test (or
        a coarse clock) must never histogram a negative duration."""
        self.phases.append((str(name), max(0.0, float(dur_s))))

    def phase_sum_s(self) -> float:
        return sum(d for _, d in self.phases)

    def prepend(self, phases: list[tuple[str, float]]) -> None:
        """Insert upstream-tier spans ahead of this trace's own (the router
        prepends pick/wire before the backend's queue/compute/fetch)."""
        self.phases[:0] = list(phases)

    def to_wire(self) -> dict:
        """The optional ``trace`` field of a newline-JSON reply."""
        out: dict = {
            "id": self.rid,
            "phases": [[n, round(d * 1e3, 3)] for n, d in self.phases],
        }
        if self.total_s is not None:
            out["total_ms"] = round(self.total_s * 1e3, 3)
        if self.detail:
            out["detail"] = self.detail
        return out

    @classmethod
    def from_wire(cls, obj) -> "TraceContext | None":
        """Parse a reply's ``trace`` field; tolerant — a malformed block from
        an older/newer peer degrades to None, never an exception on the
        client's reply path."""
        if not isinstance(obj, dict):
            return None
        phases: list[tuple[str, float]] = []
        for item in obj.get("phases") or []:
            if (
                isinstance(item, (list, tuple))
                and len(item) == 2
                and isinstance(item[0], str)
                and isinstance(item[1], (int, float))
            ):
                phases.append((item[0], max(0.0, float(item[1]) / 1e3)))
            else:
                return None
        total = obj.get("total_ms")
        detail = obj.get("detail")
        return cls(
            obj.get("id"),
            phases=phases,
            total_s=float(total) / 1e3 if isinstance(total, (int, float)) else None,
            detail=detail if isinstance(detail, dict) else None,
        )
