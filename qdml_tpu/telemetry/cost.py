"""XLA cost accounting: FLOPs, bytes, peak memory, roofline classification.

A throughput regression has two very different causes — the program got
slower, or the program *changed* (more FLOPs, more bytes) — and telemetry
that records only samples/sec cannot tell them apart. This module records
what the compiled HLO actually costs, straight from XLA's own analyses:

- :func:`analyze` accepts either a ``jax.stages.Compiled`` (full:
  ``cost_analysis()`` + ``memory_analysis()``) or a ``jax.stages.Lowered``
  (``cost_analysis()`` only — no compile paid just for accounting: the train
  loops and bench analyze the *lowering* of the step they are about to run,
  which traces but never compiles, so the step-path compile count is
  untouched);
- the record carries FLOPs, bytes accessed, peak temp memory (compiled
  source only), the derived arithmetic intensity, and a roofline
  classification against the platform's ridge point
  (``docs/ROOFLINE.md``);
- **degradation is structural**: ``cost_analysis()`` is backend-dependent
  and may return ``None`` or raise on some platforms/versions — every
  failure path degrades to ``{"available": false, "reason": ...}`` instead
  of crashing the train/serve/bench run that asked
  (``tests/test_numerics.py`` pins this with a monkeypatched backend).

Consumers: the four train loops and ``bench.py`` emit one ``cost`` record
per compiled program into their manifest-headed JSONL (via
:func:`maybe_emit_cost` — inert without an active sink), the serving engine
attaches one per AOT warmup bucket, and ``qdml-tpu report`` grows a cost
section that flags regressed benchmarks whose FLOPs/bytes also moved
(program change vs. plain slowdown).
"""

from __future__ import annotations

import os
import sys
from typing import Any

from qdml_tpu.telemetry import spans as _spans

# (peak bf16 FLOP/s, HBM bytes/s) by platform — ridge intensity is their
# ratio (FLOP/byte). TPU numbers match bench.py's _PEAK_BF16 generation
# table + published HBM bandwidths; "cpu" is a nominal desktop-class ridge
# (the classification is a coarse label there, the raw intensity is the
# portable number).
_PLATFORM_PEAKS: dict[str, tuple[float, float]] = {
    "tpu-v4": (275e12, 1.23e12),
    "tpu-v5e": (197e12, 8.19e11),
    "tpu-v5p": (459e12, 2.77e12),
    "tpu-v6e": (918e12, 1.64e12),
    "cpu": (1e11, 1.2e10),
}
_DEFAULT_RIDGE_PLATFORM = "tpu-v5e"


def detect_platform() -> str:
    """Cost-table platform label: ``cpu``/``gpu`` from the live backend, any
    accelerator plugin (the tunnelled TPU registers under its own name)
    labelled ``tpu-<gen>`` from ``PALLAS_AXON_TPU_GEN``. Never imports jax
    (host-side callers) and never raises."""
    jax = sys.modules.get("jax")
    backend = None
    if jax is not None:
        try:
            backend = jax.default_backend()
        except Exception:  # lint: disable=broad-except(backend probe is provenance only; no backend reads as unknown)
            backend = None
    if backend in ("cpu", "gpu") or backend is None:
        return backend or "unknown"
    return f"tpu-{os.environ.get('PALLAS_AXON_TPU_GEN', 'v5e')}"


def ridge_intensity(platform: str) -> float:
    peak, bw = _PLATFORM_PEAKS.get(
        platform, _PLATFORM_PEAKS[_DEFAULT_RIDGE_PLATFORM]
    )
    return peak / bw


def _first_dict(ca: Any) -> dict | None:
    """Normalize ``cost_analysis()`` output: Compiled returns a one-element
    list of dicts, Lowered a plain dict, broken backends None/[]."""
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    return ca if isinstance(ca, dict) else None


def analyze(executable: Any, platform: str | None = None) -> dict:
    """Cost record for one lowered/compiled XLA program. Never raises."""
    platform = platform or detect_platform()
    flops = bytes_accessed = None
    reason = None
    try:
        ca = _first_dict(executable.cost_analysis())
        if ca is not None:
            f = ca.get("flops")
            b = ca.get("bytes accessed")
            flops = float(f) if isinstance(f, (int, float)) else None
            bytes_accessed = float(b) if isinstance(b, (int, float)) else None
        else:
            reason = "cost_analysis() returned no properties"
    except Exception as e:  # lint: disable=broad-except(backend-dependent API — degrades to available:false by design (monkeypatch-tested))
        reason = f"cost_analysis failed: {type(e).__name__}: {e}"
    mem: dict[str, int] = {}
    memory_analysis = getattr(executable, "memory_analysis", None)
    if callable(memory_analysis):
        try:
            m = memory_analysis()
            if m is not None:
                for field, key in (
                    ("temp_size_in_bytes", "peak_temp_bytes"),
                    ("argument_size_in_bytes", "argument_bytes"),
                    ("output_size_in_bytes", "output_bytes"),
                    ("generated_code_size_in_bytes", "generated_code_bytes"),
                ):
                    v = getattr(m, field, None)
                    if isinstance(v, int):
                        mem[key] = v
        except Exception:  # lint: disable=broad-except(memory stats are a bonus on backends that expose them)
            pass
    if flops is None and bytes_accessed is None and not mem:
        return {
            "available": False,
            "reason": reason or "backend exposes no cost/memory analysis",
            "platform": platform,
        }
    out: dict[str, Any] = {
        "available": True,
        "platform": platform,
        # provenance from the API shape (only Compiled has memory_analysis),
        # NOT from whether the stats materialized — a Compiled whose memory
        # stats fail must not masquerade as a cheap lowered analysis.
        # "lowered" records carry no memory stats by design: the analysis ran
        # on the pre-compile HLO precisely to avoid paying a compile.
        "source": "compiled" if callable(memory_analysis) else "lowered",
        "flops": flops,
        "bytes_accessed": bytes_accessed,
        "peak_temp_bytes": mem.get("peak_temp_bytes"),
        **{k: v for k, v in mem.items() if k != "peak_temp_bytes"},
    }
    if flops and bytes_accessed:
        ai = flops / bytes_accessed
        ridge = ridge_intensity(platform)
        out["arithmetic_intensity"] = round(ai, 4)
        out["ridge_intensity"] = round(ridge, 2)
        out["roofline"] = "compute-bound" if ai >= ridge else "memory-bound"
    else:
        out["roofline"] = "unknown"
    return out


def achieved_roofline(
    cost: dict | None, programs_per_sec: float, platform: str | None = None
) -> dict | None:
    """Achieved-vs-roofline fraction for a MEASURED program rate.

    The cost record says what the compiled program does (FLOPs, bytes,
    arithmetic intensity); a measurement says how often it ran. Together they
    place the program ON the roofline: the ceiling at its intensity is
    ``min(peak_flops, bw * intensity)``, the achieved rate is ``flops *
    programs_per_sec``, and their ratio is the fraction of the hardware
    floor actually reached — THE number the dispatch-gap work moves (device
    time can be at peak while wall throughput rots in host gaps).

    Returns ``{"achieved_tflops_per_s", "ceiling_tflops_per_s", "fraction",
    "bound", "arithmetic_intensity", "platform"}`` or ``None`` when the cost
    block is unavailable / carries no flops+bytes (degradation mirrors
    :func:`analyze`: accounting must never kill the measurement it annotates).
    ``bound`` names the ceiling's limiting resource at this intensity —
    "compute" past the ridge, "memory" below it.
    """
    if not isinstance(cost, dict) or not cost.get("available"):
        return None
    flops, bytes_accessed = cost.get("flops"), cost.get("bytes_accessed")
    if not (
        isinstance(flops, (int, float))
        and isinstance(bytes_accessed, (int, float))
        and flops > 0
        and bytes_accessed > 0
        and programs_per_sec > 0
    ):
        return None
    platform = platform or cost.get("platform") or detect_platform()
    peak, bw = _PLATFORM_PEAKS.get(platform, _PLATFORM_PEAKS[_DEFAULT_RIDGE_PLATFORM])
    intensity = flops / bytes_accessed
    ceiling = min(peak, bw * intensity)
    achieved = flops * programs_per_sec
    return {
        "platform": platform,
        "arithmetic_intensity": round(intensity, 4),
        "achieved_tflops_per_s": round(achieved / 1e12, 6),
        "ceiling_tflops_per_s": round(ceiling / 1e12, 6),
        "fraction": round(achieved / ceiling, 6),
        "bound": "compute" if peak <= bw * intensity else "memory",
    }


def analyze_jit(jitted: Any, *args, platform: str | None = None, **kwargs) -> dict:
    """Cost record for a jitted callable at concrete/abstract args: traces
    (``.lower``, cheap) but never compiles — the caller's own first dispatch
    still performs the one and only compile. Never raises."""
    try:
        lowered = jitted.lower(*args, **kwargs)
    except Exception as e:  # lint: disable=broad-except(cost accounting must never kill the run it measures)
        return {
            "available": False,
            "reason": f"lowering failed: {type(e).__name__}: {e}",
            "platform": platform or detect_platform(),
        }
    return analyze(lowered, platform=platform)


def maybe_emit_cost(name: str, jitted: Any, *args, sink=None, **tags) -> dict | None:
    """Emit one ``cost`` record for ``jitted`` at ``args`` into the explicit
    or process-global telemetry sink; a no-op (returning None, not even
    tracing) when no sink is active — unit tests driving the trainers
    directly see zero behavior change."""
    target = sink if sink is not None else _spans.get_sink()
    if target is None or not getattr(target, "active", False):
        return None
    rec = analyze_jit(jitted, *args)
    target.emit("cost", name=name, **rec, **tags)
    return rec
