"""Telemetry sink: one append-only JSONL stream per run, primary-writer aware.

A :class:`Telemetry` owns the run's JSONL file. All record kinds share the one
stream — a ``manifest`` header line first, then interleaved ``metrics`` (the
legacy bare-record shape, for reader compatibility), ``span`` and ``counters``
lines — so a single artifact carries both the numbers and their provenance.

Multi-host: every process measures, only the primary (process 0) writes.
Concurrent appends from N hosts to one shared file would interleave, and the
metrics are replicated/psum-aggregated anyway; non-primary sinks are inert
(``active`` False, all writes no-ops).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any, IO


def is_primary() -> bool:
    """True on the single process that should write shared files.

    Probes ``jax.process_index()`` only when jax is already imported: a
    host-side tool that never touched jax (the bench parent, ``report``) is
    single-process by construction and must not pay — or trigger — a backend
    import just to log.
    """
    jax = sys.modules.get("jax")
    if jax is None:
        return True
    try:
        return jax.process_index() == 0
    except Exception:  # lint: disable=broad-except(process_index before distributed init — single process acts as primary)
        return True


class Telemetry:
    """Append-only JSONL telemetry stream.

    ``manifest`` (a :func:`qdml_tpu.telemetry.manifest.run_manifest` dict) is
    written as the stream's first record at open — every run appends its own
    manifest, so even a resumed/appended file carries one header per process
    invocation and no record in it is ever orphaned from its provenance.
    """

    def __init__(
        self,
        path: str | None = None,
        manifest: dict | None = None,
        echo: bool = False,
    ):
        self.path = path
        self.echo = echo
        self._fh: IO[str] | None = None
        if path is not None and is_primary():
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            self._fh = open(path, "a", buffering=1)
            if manifest is not None:
                self.write_raw(dict(manifest))

    @property
    def active(self) -> bool:
        """Whether writes reach a file (primary process with a path)."""
        return self._fh is not None

    def write_raw(self, rec: dict) -> None:
        """Append one record exactly as given (no kind/ts decoration)."""
        if self._fh is not None:
            self._fh.write(json.dumps(rec) + "\n")
        if self.echo:
            print(json.dumps(rec), flush=True)

    def emit(self, kind: str, **payload: Any) -> dict:
        """Append one typed record: ``{"kind": kind, "ts": ..., **payload}``."""
        rec = {"kind": kind, "ts": round(time.time(), 3), **payload}
        self.write_raw(rec)
        return rec

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
