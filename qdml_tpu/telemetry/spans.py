"""Span tracer: nested wall-clock timing with a jax.profiler bridge.

``with span("compile"): ...`` times the enclosed block and appends one
``span`` record at exit (children close before parents, so a reader can
reconstruct the tree from ``path``/``depth``). Records go to the explicit
``sink`` if given, else to the process-global sink (:func:`set_sink`, wired
by the CLI to the run's telemetry file); with neither, spans cost two
``perf_counter`` calls and write nothing — library callers stay clean.

Multihost: every process measures, only the primary's sink writes
(``core.Telemetry``); records carry the writing process's index.

Bridge: when jax is already imported, each span also opens a
``jax.profiler.TraceAnnotation``, so spans show up as named regions inside
any active profiler trace (``profiler_trace`` below / ``cli profile``).
"""

from __future__ import annotations

import contextlib
import sys
import threading
import time
from typing import Iterator

_local = threading.local()
_sink = None


def set_sink(sink) -> None:
    """Install the process-global span/counter sink (a ``Telemetry``), or
    ``None`` to detach."""
    global _sink
    _sink = sink


def get_sink():
    return _sink


def _stack() -> list[str]:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


def _process_index() -> int | None:
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.process_index()
    except Exception:  # lint: disable=broad-except(process_index before distributed init — spans then carry no index)
        return None


@contextlib.contextmanager
def span(name: str, sink=None, **tags) -> Iterator[None]:
    """Time a block; emit one nested ``span`` record at exit."""
    st = _stack()
    st.append(name)
    path = "/".join(st)
    bridge = contextlib.nullcontext()
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            bridge = jax.profiler.TraceAnnotation(name)
        except Exception:  # lint: disable=broad-except(the profiler bridge is optional; spans must work without an active trace)
            pass
    t_wall = time.time()
    t0 = time.perf_counter()
    try:
        with bridge:
            yield
    finally:
        dur = time.perf_counter() - t0
        st.pop()
        target = sink if sink is not None else _sink
        if target is not None and getattr(target, "active", False):
            rec = {
                "kind": "span",
                "ts": round(t_wall, 3),
                "name": name,
                "path": path,
                "depth": len(st),
                "dur_s": round(dur, 6),
                **tags,
            }
            proc = _process_index()
            if proc is not None:
                rec["process"] = proc
            target.write_raw(rec)


@contextlib.contextmanager
def profiler_trace(logdir: str, sink=None) -> Iterator[None]:
    """``jax.profiler`` trace of the enclosed device work, wrapped in a span
    (so the telemetry stream records that — and how long — a trace ran, and
    inner spans annotate the trace's timeline)."""
    import jax

    with span("jax_profiler_trace", sink=sink, logdir=logdir):
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
