"""Unified event spine: one envelope, one ring, explicit loss.

Before this module the stack's structured events — ``drift_event`` /
``control_event`` / ``fleet_scale_event`` (control), ``replica_restarted`` /
``replica_quarantined`` / ``supervisor_error`` (serve supervision),
``backend_ejected`` / ``fleet_lifecycle`` / ``router_swap`` (router tier),
``monitor_alert`` / ``counter_reset`` (flight deck) — were scattered across
per-subsystem JSONL sinks with no shared envelope and no way to tail them
from a RUNNING process; the PR-16 timeline had to reconstruct causality
after the fact. The :class:`EventBus` gives every emitter one envelope:

- ``seq`` — monotone per-process sequence number (the cursor key);
- ``ts`` — wall-clock emission time;
- ``tier`` — which subsystem published (serve / router / control / monitor);
- ``kind`` — the event name (``replica_restarted``, ``fleet_scale_event``…);
- ``severity`` — ``debug`` / ``info`` / ``warning`` / ``critical``, inferred
  from the kind (``classify``) unless the publisher overrides it;
- correlation keys, hoisted from the payload when present: ``rid`` (request),
  ``swap_epoch`` (deploy), ``episode`` (burn-alert episode id), ``decision``
  (scale decision id), ``planner_sha`` (capacity-plan assumptions);
- ``data`` — the full original payload, untouched.

The ring is bounded and loss is EXPLICIT: when a publish evicts the oldest
envelope, ``dropped`` increments, and every :meth:`tail` reply carries the
cumulative counter plus the cursor-relative ``lost`` count — a reader can
always tell "I saw everything" from "the buffer lapped me"; there is no
silent path. Tails survive restarts through the same ``start_seq`` epoch
contract the monitor's counter differencing uses (docs/TELEMETRY.md): a
cursor stamped with a dead process's epoch mismatches the new bus's and the
tail restarts from the buffer head instead of silently skipping the new
process's first ``seq`` events.

The bus is process-global (``ensure_bus``/``publish``, mirroring
``spans.set_sink``) so library emitters need no wiring: the serve server and
fleet router answer ``{"op": "events"}`` from whatever the process
accumulated, sink or no sink. Publishing is a deque append under a lock —
cheap enough to leave always-on.
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time
from collections import deque

DEFAULT_CAPACITY = 4096
DEFAULT_TAIL_LIMIT = 512

# strictly-increasing epoch allocator: two buses born within the same
# wall-clock millisecond (a fast in-process restart, or tests) must still
# get DISTINCT start_seq epochs, or a stale cursor would silently "match"
# the replacement ring and skip its first events
_epoch_lock = lockdep.Lock("events:_epoch_lock")
_last_epoch = 0


def _new_epoch() -> int:
    global _last_epoch
    with _epoch_lock:
        e = int(time.time() * 1000)
        if e <= _last_epoch:
            e = _last_epoch + 1
        _last_epoch = e
        return e

SEVERITIES = ("debug", "info", "warning", "critical")

# kind -> severity vocabulary (docs/TELEMETRY.md "event spine"). Anything
# unlisted is "info"; monitor_alert is state-dependent (firing pages).
_CRITICAL = frozenset({
    "replica_quarantined",
    "supervisor_error",
    "backend_ejected",
    "spawn_failed",
    "monitor_attach_giveup",
})
_WARNING = frozenset({
    "replica_restarted",
    "router_poll_error",
    "drift_event",
    "counter_reset",
    "late_scrape",
    "monitor_reattach",
    "worker_crash",
})
_DEBUG = frozenset({"monitor_timeseries"})

# envelope correlation keys <- payload field aliases, first present wins.
# The payload stays intact under "data"; hoisting just makes the keys
# greppable/joinable without knowing each record's shape.
_CORRELATION = (
    ("rid", ("rid", "request_id")),
    ("swap_epoch", ("swap_epoch",)),
    ("episode", ("episode", "alert_episode")),
    ("decision", ("decision", "decision_id")),
    ("planner_sha", ("planner_sha", "assumptions_sha")),
)


def classify(kind: str, fields: dict | None = None) -> str:
    """Default severity for ``kind`` (publisher override always wins)."""
    if kind == "monitor_alert":
        return "critical" if (fields or {}).get("state") == "firing" else "info"
    if kind in _CRITICAL:
        return "critical"
    if kind in _WARNING:
        return "warning"
    if kind in _DEBUG:
        return "debug"
    return "info"


class EventBus:
    """Bounded in-process event ring with cursor tails and explicit drops.

    ``capacity`` bounds memory on a long-lived server; ``clock`` injects a
    fake wall clock for tests. All ring/cursor state (``_ring``, ``_seq``,
    ``_dropped``) is touched only under ``_lock`` (graftlint LOCK_MAP,
    analysis/project.py): publishers are request workers, supervisors and
    poll threads, tails come from the asyncio verb handlers.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=time.time):
        self.capacity = max(1, int(capacity))
        self._clock = clock
        # restart-visibility epoch, same contract as ServeLoop/FleetRouter
        # start_seq: a cursor from before a process restart mismatches and
        # the tail restarts from the head instead of skipping new events
        self.start_seq = _new_epoch()
        self._lock = lockdep.Lock("EventBus._lock")
        self._ring: deque = deque()
        self._seq = 0
        self._dropped = 0

    # -- publishing ----------------------------------------------------------

    def publish(
        self, kind: str, tier: str = "host", severity: str | None = None,
        **fields,
    ) -> dict:
        """Append one envelope; returns it. Eviction on a full ring counts
        in ``dropped`` — loss is observable, never silent."""
        sev = severity if severity is not None else classify(kind, fields)
        env = {
            "ts": round(float(self._clock()), 6),
            "tier": tier,
            "kind": kind,
            "severity": sev,
        }
        for key, aliases in _CORRELATION:
            for a in aliases:
                if fields.get(a) is not None:
                    env[key] = fields[a]
                    break
        env["data"] = fields
        with self._lock:
            self._seq += 1
            env["seq"] = self._seq
            if len(self._ring) >= self.capacity:
                self._ring.popleft()
                self._dropped += 1
            self._ring.append(env)
        return env

    # -- tailing -------------------------------------------------------------

    def tail(self, cursor: dict | None = None, limit: int = DEFAULT_TAIL_LIMIT) -> dict:
        """Events after ``cursor`` (``{"start_seq": ..., "seq": ...}``; None
        or an epoch-mismatched cursor reads from the buffer head). The reply
        is the next cursor plus the loss ledger::

            {"start_seq": epoch, "next_seq": resume-from seq,
             "dropped": cumulative evictions, "lost": evicted past THIS
             cursor (0 = the reader saw every event), "events": [...]}

        Resume by passing ``{"start_seq": reply["start_seq"], "seq":
        reply["next_seq"]}`` back — same cursor, no gaps, no duplicates.
        """
        limit = max(1, int(limit))
        since = 0
        if isinstance(cursor, dict):
            try:
                if int(cursor.get("start_seq") or 0) == self.start_seq:
                    since = max(0, int(cursor.get("seq") or 0))
            except (TypeError, ValueError):
                since = 0
        with self._lock:
            oldest = self._ring[0]["seq"] if self._ring else self._seq + 1
            events = []
            for e in self._ring:
                if e["seq"] > since:
                    events.append(e)
                    if len(events) >= limit:
                        break
            dropped = self._dropped
        return {
            "start_seq": self.start_seq,
            "next_seq": events[-1]["seq"] if events else max(since, oldest - 1),
            "dropped": dropped,
            "lost": max(0, oldest - 1 - since),
            "events": events,
        }

    def snapshot(self) -> dict:
        """Ledger facts without the events (health/summary blocks)."""
        with self._lock:
            return {
                "start_seq": self.start_seq,
                "seq": self._seq,
                "dropped": self._dropped,
                "size": len(self._ring),
                "capacity": self.capacity,
            }


# -- process-global bus (mirrors spans.set_sink / get_sink) ------------------

_bus: EventBus | None = None
_bus_guard = lockdep.Lock("events:_bus_guard")


def install_bus(bus: EventBus | None) -> None:
    """Install (or with None, detach) the process-global bus. Tests install
    a fresh bus to isolate their cursors; servers just use ``ensure_bus``."""
    global _bus
    _bus = bus


def get_bus() -> EventBus | None:
    return _bus


def ensure_bus(capacity: int = DEFAULT_CAPACITY) -> EventBus:
    """The process-global bus, created on first use (double-checked: two
    racing first publishers must not each install a bus and split the
    stream)."""
    global _bus
    if _bus is None:
        with _bus_guard:
            if _bus is None:
                _bus = EventBus(capacity)
    return _bus


def publish(kind: str, tier: str = "host", severity: str | None = None, **fields) -> dict:
    """Publish onto the process-global bus (creating it on first use).
    The one-liner every emitter choke point calls alongside its JSONL
    write — the sink is the durable record, the bus is the live tail."""
    return ensure_bus().publish(kind, tier=tier, severity=severity, **fields)


def normalize_tail(reply: dict) -> tuple[list[dict], dict, int, int]:
    """``(events, next_cursor, dropped, lost)`` from either tail shape:
    a single bus (``{"start_seq", "next_seq", ...}``) or a router
    aggregation (``{"cursor": {source: ...}, ...}``). The next cursor is
    whatever the endpoint wants passed back verbatim."""
    events = reply.get("events") or []
    if "cursor" in reply:
        cursor = reply["cursor"]
    else:
        cursor = {"start_seq": reply.get("start_seq"),
                  "seq": reply.get("next_seq")}
    return (events, cursor,
            int(reply.get("dropped") or 0), int(reply.get("lost") or 0))


# ---------------------------------------------------------------------------
# CLI: qdml-tpu events
# ---------------------------------------------------------------------------


def events_main(argv: list[str]) -> int:
    """``qdml-tpu events --addr=HOST:PORT [--follow] [--interval=1.0]
    [--limit=512] [--min-severity=debug] [--kinds=a,b] [--tiers=x,y]`` —
    tail a running serve/route endpoint's event spine as JSONL on stdout.
    One tail and exit by default; ``--follow`` keeps polling the cursor
    (Ctrl-C to stop). A nonzero loss ledger prints a ``spine_loss`` line —
    drops are never silent, not even on a human's terminal. Host-side
    only: no jax, no config."""
    import json as _json
    import sys as _sys

    def _arg(name: str, default):
        return next(
            (a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")),
            default,
        )

    addr = _arg("addr", None)
    if not addr or ":" not in addr:
        print("events needs --addr=HOST:PORT (a serve or route endpoint)")
        return 2
    host, port = addr.rsplit(":", 1)
    follow = any(a == "--follow" for a in argv)
    interval = float(_arg("interval", "1.0"))
    limit = int(_arg("limit", str(DEFAULT_TAIL_LIMIT)))
    min_sev = SEVERITIES.index(str(_arg("min-severity", "debug")))
    kinds = {k for k in str(_arg("kinds", "")).split(",") if k}
    tiers = {t for t in str(_arg("tiers", "")).split(",") if t}

    from qdml_tpu.serve.client import ServeClient, ServeClientError

    client = ServeClient(host, int(port), timeout_s=max(5.0, interval * 4))
    cursor = None
    last_dropped = last_lost = 0
    try:
        while True:
            try:
                rep = client.events(cursor, limit=limit)
            except ServeClientError as e:
                print(_json.dumps({"spine_error": str(e)}), file=_sys.stderr)
                return 3
            if not rep.get("ok"):
                print(_json.dumps({"spine_error": rep.get("reason")}),
                      file=_sys.stderr)
                return 3
            events, cursor, dropped, lost = normalize_tail(
                rep.get("events") or {}
            )
            if dropped > last_dropped or lost > last_lost:
                print(_json.dumps({"spine_loss": {"dropped": dropped,
                                                  "lost": lost}}))
                last_dropped, last_lost = dropped, lost
            for e in events:
                if SEVERITIES.index(e.get("severity", "info")) < min_sev:
                    continue
                if kinds and e.get("kind") not in kinds:
                    continue
                if tiers and e.get("tier") not in tiers:
                    continue
                print(_json.dumps(e), flush=follow)
            if not follow:
                break
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    finally:
        client.close_connection()
    return 0
