"""SLO error-budget burn-rate alerting + the timeline dashboard
(docs/TELEMETRY.md "burn-rate alerting").

An SLO target (say 99% of deadline-carrying requests met) implies an
error BUDGET (1%). The burn rate is how fast a window is spending it:

    burn = (windowed error rate) / (budget rate)

burn 1x spends exactly the budget over the SLO period; burn 14x pages.
One window cannot do this job: a short window alone pages on every blip,
a long window alone pages an hour late. The standard discipline (SRE
workbook's multi-window multi-burn alerts) evaluates a FAST and a SLOW
window and fires only when BOTH exceed the threshold — the fast window
proves it is happening now, the slow window proves it is not a blip.
:class:`BurnRateRule` implements one such pair with debounce (N
consecutive over-threshold evaluations before firing) and a latch (stays
firing until both windows recover, so one good scrape cannot flap the
alert); :class:`BurnAlerter` runs a battery of rules over the monitor's
windowed signals (SLO attainment, admission sheds, breaker fast-fails,
quarantine/restart events, router failovers, externally-fed stranded
futures).

Zero-traffic discipline: a window with no eligible traffic has NO burn
rate (``burn_rate`` returns None, never 0/0 = NaN) and never advances the
debounce — an idle fleet is not a healthy fleet evidence-wise, and it is
not a paging fleet either.

Window scaling: production burn alerting uses 5m/1h pairs against a
30-day budget; a dryrun lives for half a minute. :meth:`BurnAlerter.for_run`
scales the pair to the run length (fast ~ run/15, slow ~ run/4, floored
at two scrape intervals) so the SAME rule shapes are testable end-to-end
in seconds.

``render_timeline`` turns a committed monitor JSONL stream (plus optional
sibling event streams: control_event / drift_event / the serve stack's
``counters``-kind fleet events) into the markdown timeline dashboard —
metric windows and structured events on one clock, alerts annotated with
the events they correlate with.
"""

from __future__ import annotations

from collections import deque

# the serve/fleet/control stack's structured event names worth a timeline
# row (all emitted as kind="counters" records with a "name" field)
STACK_EVENT_NAMES = (
    "replica_restarted",
    "replica_quarantined",
    "supervisor_error",
    "backend_ejected",
    "backend_readmitted",
    "router_swap",
    "router_poll_error",
    "drift_event",
    "control_event",
    "counter_reset",
)


def burn_rate(errors: float, total: float, budget: float) -> float | None:
    """Error-budget burn multiple for one window; None when the window has
    no eligible traffic (0/0 is 'no evidence', not 'no burn')."""
    if total is None or total <= 0:
        return None
    bad = max(0.0, float(errors)) / float(total)
    if budget <= 0:
        return float("inf") if bad > 0 else 0.0
    return bad / budget


class BurnRateRule:
    """One signal's fast/slow window pair with debounce + latch."""

    def __init__(
        self,
        signal: str,
        budget: float,
        fast_s: float,
        slow_s: float,
        threshold: float,
        debounce: int = 2,
    ):
        if slow_s < fast_s:
            raise ValueError(f"slow window {slow_s} < fast window {fast_s}")
        self.signal = signal
        self.budget = float(budget)
        self.fast_s = float(fast_s)
        self.slow_s = float(slow_s)
        self.threshold = float(threshold)
        self.debounce = max(1, int(debounce))
        self._samples: deque = deque()  # (t, errors, total)
        self._pending = 0
        self.firing = False
        self.peak_fast = 0.0
        self.peak_slow = 0.0
        self.fired_count = 0
        self.resolved_count = 0

    def feed(self, t: float, errors: float, total: float) -> None:
        self._samples.append((float(t), float(errors), float(total)))
        horizon = t - self.slow_s
        while self._samples and self._samples[0][0] <= horizon:
            self._samples.popleft()

    def _window_burn(self, t: float, width: float) -> float | None:
        lo = t - width
        err = tot = 0.0
        for ts, e, n in self._samples:
            if ts > lo:
                err += e
                tot += n
        return burn_rate(err, tot, self.budget)

    def burns(self, t: float) -> dict:
        return {"fast": self._window_burn(t, self.fast_s),
                "slow": self._window_burn(t, self.slow_s)}

    def evaluate(self, t: float) -> dict | None:
        """One evaluation at time ``t``; returns the alert-transition
        payload (state firing/resolved) or None. Multi-window: fires iff
        BOTH windows exceed the threshold for ``debounce`` consecutive
        evaluations; latched: resolves only when BOTH recover."""
        fast = self._window_burn(t, self.fast_s)
        slow = self._window_burn(t, self.slow_s)
        if fast is not None:
            self.peak_fast = max(self.peak_fast, fast)
        if slow is not None:
            self.peak_slow = max(self.peak_slow, slow)
        if fast is None or slow is None:
            return None  # zero-traffic window: no evidence, no transition
        over = fast >= self.threshold and slow >= self.threshold
        if not self.firing:
            self._pending = self._pending + 1 if over else 0
            if self._pending >= self.debounce:
                self.firing = True
                self._pending = 0
                self.fired_count += 1
                return self._alert("firing", t, fast, slow)
            return None
        if not over and fast < self.threshold and slow < self.threshold:
            self.firing = False
            self.resolved_count += 1
            return self._alert("resolved", t, fast, slow)
        return None

    def _alert(self, state: str, t: float, fast: float, slow: float) -> dict:
        return {
            "signal": self.signal,
            "state": state,
            # alert-episode id: one per fire, shared by the resolve that
            # closes it. The event spine hoists it as the ``episode``
            # correlation key, and the hands-off autoscaler stamps it onto
            # the scale decision it triggers — "which alert caused this
            # scale-up" is a join on this id, not a timestamp guess.
            "episode": f"{self.signal}#{self.fired_count}",
            "t_s": round(t, 4),
            "fast_burn": round(fast, 3),
            "slow_burn": round(slow, 3),
            "threshold": self.threshold,
            "budget": self.budget,
            "fast_s": self.fast_s,
            "slow_s": self.slow_s,
        }


class BurnAlerter:
    """A battery of :class:`BurnRateRule` — one per monitored signal."""

    #: default per-signal error budgets (fraction of eligible traffic that
    #: may go bad before burn 1x): slo comes from the target; the rest are
    #: operational budgets for events that should essentially never happen
    DEFAULT_BUDGETS = {
        "shed": 0.02,
        "breaker": 0.02,
        "quarantine": 0.05,
        "router": 0.02,
        "stranded": 0.001,
    }

    def __init__(self, rules: dict[str, BurnRateRule]):
        self.rules = dict(rules)

    @classmethod
    def for_run(
        cls,
        duration_s: float,
        interval_s: float,
        slo_target: float = 0.99,
        threshold: float = 8.0,
        fast_s: float | None = None,
        slow_s: float | None = None,
        debounce: int = 2,
        budgets: dict[str, float] | None = None,
    ) -> "BurnAlerter":
        """Window pair scaled to the run length (see module docstring);
        explicit ``fast_s``/``slow_s`` override the scaling."""
        fast = fast_s if fast_s else min(max(2 * interval_s, duration_s / 15.0), 300.0)
        slow = slow_s if slow_s else min(max(3 * fast, duration_s / 4.0), 3600.0)
        slow = max(slow, fast)
        b = dict(cls.DEFAULT_BUDGETS)
        b["slo"] = max(1e-6, 1.0 - float(slo_target))
        if budgets:
            b.update(budgets)
        return cls({
            sig: BurnRateRule(sig, budget, fast, slow, threshold, debounce)
            for sig, budget in b.items()
        })

    def feed(self, t: float, signal: str, errors: float, total: float) -> None:
        rule = self.rules.get(signal)
        if rule is not None:
            rule.feed(t, errors, total)

    def evaluate(self, t: float, mark: str = "") -> list[dict]:
        out = []
        for rule in self.rules.values():
            a = rule.evaluate(t)
            if a is not None:
                a["mark"] = mark
                out.append(a)
        return out

    def burns(self, t: float) -> dict:
        """Current fast/slow burns per signal (only signals with evidence)."""
        out = {}
        for sig, rule in self.rules.items():
            b = rule.burns(t)
            if b["fast"] is not None or b["slow"] is not None:
                out[sig] = {
                    k: (None if v is None else round(v, 3))
                    for k, v in b.items()
                }
        return out

    def peaks(self) -> dict:
        return {
            sig: {"fast": round(r.peak_fast, 3), "slow": round(r.peak_slow, 3)}
            for sig, r in self.rules.items()
            if r.peak_fast > 0 or r.peak_slow > 0
        }

    def firing(self) -> list[dict]:
        """Currently-latched alerts as ``[{"signal", "episode"}]`` — the
        open episode ids the hands-off attachment stamps onto any scale
        decision made while they burn (telemetry/attach.py)."""
        return [
            {"signal": sig, "episode": f"{sig}#{r.fired_count}"}
            for sig, r in self.rules.items()
            if r.firing
        ]


# ---------------------------------------------------------------------------
# timeline rendering
# ---------------------------------------------------------------------------


def _event_label(rec: dict) -> str:
    if rec.get("kind") == "monitor_event" or "event" in rec:
        name = rec.get("event", "?")
        who = rec.get("backend") or rec.get("replica") or ""
        return f"{name}({who})" if who else str(name)
    if rec.get("kind") == "counter_reset":
        return f"counter_reset({rec.get('counter')})"
    name = rec.get("name", rec.get("kind", "?"))
    if name == "control_event":
        return f"control:{rec.get('action', '?')}"
    if name == "drift_event":
        return f"drift(s{rec.get('scenario', '?')})"
    who = rec.get("backend") or rec.get("replica") or ""
    return f"{name}({who})" if who else str(name)


def render_timeline(records: list[dict], extra_events: list[dict] | None = None,
                    max_rows: int = 200) -> str:
    """The markdown timeline dashboard: one table row per monitor window,
    structured events correlated onto the same clock, alerts annotated
    with the events inside their fast window (the 'what was happening when
    it paged' view). ``extra_events`` merges sibling JSONL streams (a
    control loop's control_event/drift_event records, a serve run's fleet
    events) by wall-clock ``ts``."""
    manifest = next((r for r in records if r.get("kind") == "manifest"), None)
    windows = [r for r in records if r.get("kind") == "monitor_timeseries"]
    events = [r for r in records
              if r.get("kind") in ("monitor_event", "counter_reset")]
    alerts = [r for r in records if r.get("kind") == "monitor_alert"]
    summary = next(
        (r for r in records if r.get("kind") == "monitor_summary"), None
    )

    # wall-clock -> monitor-relative mapping for sibling streams
    offset = None
    for w in windows:
        if w.get("ts") is not None and w.get("t_s") is not None:
            offset = float(w["ts"]) - float(w["t_s"])
            break
    merged = list(events)
    for rec in extra_events or []:
        name = rec.get("name")
        if rec.get("kind") == "counters" and name in STACK_EVENT_NAMES:
            if offset is not None and rec.get("ts") is not None:
                rec = dict(rec)
                rec["t_s"] = round(float(rec["ts"]) - offset, 4)
            merged.append(rec)
    merged = [e for e in merged if e.get("t_s") is not None]
    merged.sort(key=lambda e: e["t_s"])

    lines: list[str] = ["# fleet flight deck — monitor timeline", ""]
    if manifest is not None:
        run = manifest.get("run") or {}
        lines.append(
            f"- source: `{run.get('argv') or manifest.get('argv') or '?'}`"
        )
    if summary is not None:
        lines.append(
            f"- {summary.get('windows')} windows over "
            f"{summary.get('duration_s')}s at {summary.get('interval_s')}s; "
            f"{(summary.get('alerts') or {}).get('fired', 0)} alert(s) fired, "
            f"{summary.get('counter_resets')} counter reset(s), "
            f"{summary.get('scrape_errors')} scrape error(s)"
        )
    lines.append("")

    lines.append("## windows")
    lines.append("")
    lines.append("| t (s) | mark | rps | slo | burn slo f/s | burn router f/s "
                 "| queue | live | events |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    shown = windows[:max_rows]
    prev_t = None
    for w in shown:
        t = w.get("t_s")
        slo = w.get("slo")
        slo_s = "—" if not slo else f"{slo['met']:.0f}/{slo['n']:.0f}"
        burn = w.get("burn") or {}

        def _fmt(v):
            return f"{v:.1f}" if isinstance(v, (int, float)) else "—"

        def _b(sig):
            b = burn.get(sig)
            if not b:
                return "—"
            return f"{_fmt(b.get('fast'))}/{_fmt(b.get('slow'))}"

        evs = [
            _event_label(e) for e in merged
            if (prev_t is None or e["t_s"] > prev_t) and e["t_s"] <= (t or 0)
            and e.get("event") != "mark"
        ]
        mark_s = w.get("mark") or ""
        alert_here = [a for a in alerts
                      if a.get("t_s") == t and a.get("state") == "firing"]
        if alert_here:
            evs = [f"**ALERT {a['signal']}**" for a in alert_here] + evs
        lines.append(
            f"| {t} | {mark_s} | {w.get('rps') if w.get('rps') is not None else '—'} "
            f"| {slo_s} | {_b('slo')} | {_b('router')} "
            f"| {w.get('queue_depth')} | {w.get('backends_live') if w.get('backends_live') is not None else w.get('replicas')} "
            f"| {', '.join(evs) if evs else ''} |"
        )
        prev_t = t
    if len(windows) > max_rows:
        lines.append("")
        lines.append(f"_... {len(windows) - max_rows} more windows truncated_")
    lines.append("")

    lines.append("## alerts")
    lines.append("")
    if not alerts:
        lines.append("none fired.")
    for a in alerts:
        t = a.get("t_s") or 0.0
        mark_s = f" [{a['mark']}]" if a.get("mark") else ""
        lines.append(
            f"- t={t}s{mark_s} **{a.get('signal')} {a.get('state', '?').upper()}** "
            f"fast={a.get('fast_burn')}x slow={a.get('slow_burn')}x "
            f"(threshold {a.get('threshold')}x over {a.get('fast_s')}s/"
            f"{a.get('slow_s')}s, budget {a.get('budget')})"
        )
        if a.get("state") == "firing":
            lo = t - float(a.get("fast_s") or 0.0) - 1.0
            corr = [
                f"{_event_label(e)}@{e['t_s']}s" for e in merged
                if lo <= e["t_s"] <= t + 0.5 and e.get("event") != "mark"
            ]
            if corr:
                lines.append(f"  - correlated events: {', '.join(corr)}")
    lines.append("")

    if summary is not None:
        lines.append("## summary")
        lines.append("")
        peaks = summary.get("peak_burn") or {}
        if peaks:
            lines.append("| signal | peak fast burn | peak slow burn |")
            lines.append("|---|---|---|")
            for sig, p in sorted(peaks.items()):
                lines.append(f"| {sig} | {p.get('fast')}x | {p.get('slow')}x |")
            lines.append("")
        al = summary.get("alerts") or {}
        if al.get("by_mark"):
            lines.append(
                "- alerts by segment: "
                + ", ".join(f"{k or '(untagged)'}={v}"
                            for k, v in al["by_mark"].items())
            )
        if summary.get("planner") is not None:
            pl = summary["planner"]
            lines.append(
                f"- capacity-planner validation: "
                f"{'PASS' if pl.get('ok') else 'FAIL'} "
                f"({pl.get('n_windows')} window(s), max |p99 log-ratio| "
                f"{pl.get('max_p99_ratio')}, max rps err "
                f"{pl.get('max_rps_err')})"
            )
        lines.append("")
    return "\n".join(lines)
