"""The live observability loop: a monitor attachment that DRIVES the fleet.

``qdml-tpu monitor`` (telemetry/timeseries.py) observes; the elastic fleet
(fleet/lifecycle.py + control/fleet_scale.py) provisions; until this module
the two met only through committed artifacts — the PR-17 proof fed
:meth:`FleetAutoscaler.observe` from windowed summaries after the fact.
:class:`MonitorAttachment` closes the loop hands-off: one long-running
scraper, and every finished window becomes a live policy tick.

Per window the attachment:

1. scrapes health + metrics + the event-spine tail (the three sanctioned
   read verbs — the attachment never sends inference; acting happens
   through the injected autoscaler's ``scale_fn``, a separate actuator);
2. reads the burn-alerter's latched state (:meth:`BurnAlerter.firing`) —
   the open alert-episode ids;
3. ticks ``autoscaler.observe(queue_depth, backends, slo_attainment,
   burn_alert, alert_episode, backends_live)`` — a decision made while an
   alert burns carries the episode id, so the emitted
   ``fleet_scale_event`` is joined to the ``monitor_alert`` that drove it
   BY ID in the event stream; burn + a live count below membership is the
   grow signal (the fleet is provably short-handed AND paging).

Reconnect discipline (the front door restarting mid-attachment must not
end a hands-off loop): a failed scrape backs off exponentially instead of
holding the grid; on recovery the attachment emits ``monitor_reattach``
and the event tail resumes from the last seen per-source ``(start_seq,
seq)`` cursor — the restart-epoch contract means no gaps and no duplicates
across the outage. Exhausting ``max_reconnects`` consecutive attempts ends
the run with a TYPED give-up (``monitor_attach_giveup`` + a ``give_up``
summary block), never a traceback.
"""

from __future__ import annotations

import threading

from qdml_tpu.telemetry.timeseries import MonitorScraper


class MonitorAttachment:
    """Drive a :class:`FleetAutoscaler` (or any object with an
    ``observe(queue_depth, backends, slo_attainment=, burn_alert=,
    alert_episode=)`` method) from a live :class:`MonitorScraper`.

    The scraper should be constructed with ``tail_events=True`` so each
    window also drains the event spine (the attachment works without it,
    but then the committed stream carries no correlation evidence).
    """

    def __init__(
        self,
        scraper: MonitorScraper,
        autoscaler,
        reconnect_backoff_s: float = 0.5,
        reconnect_max_s: float = 8.0,
        max_reconnects: int = 8,
    ):
        self.scraper = scraper
        self.autoscaler = autoscaler
        self.reconnect_backoff_s = float(reconnect_backoff_s)
        self.reconnect_max_s = float(reconnect_max_s)
        self.max_reconnects = max(1, int(max_reconnects))
        self.ticks = 0
        self.decisions: list[dict] = []
        self.reattaches = 0
        self.give_up: dict | None = None

    # -- one policy tick -----------------------------------------------------

    def tick(self, rec: dict) -> dict | None:
        """One finished window into one ``observe`` tick. Returns the
        ``fleet_scale_event`` payload when the policy decided, else None."""
        self.ticks += 1
        firing = (
            self.scraper.alerter.firing()
            if self.scraper.alerter is not None else []
        )
        slo = rec.get("slo") or {}
        # anchor the policy to MEMBERSHIP (rec["backends"]), not the live
        # count: an ejected-but-provisioned backend is the router's
        # short-horizon remedy in flight, and the policy acts on provisioned
        # capacity through lifecycle.scale_to — anchoring to backends_live
        # would make every ejection look like a retirement. The live count
        # rides along separately: burn + (live < membership) is the
        # short-handed grow signal.
        live = rec.get("backends_live")
        decision = self.autoscaler.observe(
            float(rec.get("queue_depth") or 0),
            int(rec.get("backends") or rec.get("backends_live")
                or rec.get("replicas") or 1),
            slo_attainment=slo.get("attainment"),
            burn_alert=bool(firing),
            alert_episode=firing[0]["episode"] if firing else None,
            backends_live=None if live is None else int(live),
        )
        if decision is not None:
            self.decisions.append(decision)
        return decision

    # -- the attachment loop -------------------------------------------------

    def run(self, duration_s: float, stop: threading.Event | None = None) -> int:
        """Attached scrape-and-tick loop for ``duration_s`` (or until
        ``stop``); returns the number of policy ticks taken.

        Healthy scrapes anchor to the absolute monotonic grid exactly like
        :meth:`MonitorScraper.run` (late scrapes emit ``late_scrape``). A
        FAILED scrape switches to jitter-free exponential backoff — while
        the front door is down there is no window to align, and hammering
        a restarting endpoint on the grid helps nobody. Recovery re-anchors
        the grid at the reattach instant."""
        s = self.scraper
        stop = stop or threading.Event()
        clock = s.clock
        start = clock()
        end = start + float(duration_s)
        next_t = start
        down_attempts = 0
        while clock() < end and not stop.is_set():
            rec = s.scrape_once()
            if rec is None:
                # endpoint unreachable: scrape_once already reported the
                # scrape_error event; back off (bounded) instead of gridding
                down_attempts += 1
                if down_attempts >= self.max_reconnects:
                    self.give_up = {
                        "reason": "reconnect_exhausted",
                        "attempts": down_attempts,
                        "cursor": s.events_cursor,
                    }
                    ev = {"event": "monitor_attach_giveup", **self.give_up,
                          "t_s": s._rel(clock()), "mark": s._mark}
                    s.events.add(ev)
                    s._emit("monitor_event", **ev)
                    break
                delay = min(
                    self.reconnect_max_s,
                    self.reconnect_backoff_s * (2.0 ** (down_attempts - 1)),
                )
                if stop.wait(delay):
                    break
                next_t = clock()  # re-anchor the grid at whatever comes next
                continue
            if down_attempts:
                # recovered: the kept per-source cursor resumes the event
                # tail across the restart (start_seq epochs — no gaps, no
                # duplicates), and the grid re-anchors here
                self.reattaches += 1
                ev = {"event": "monitor_reattach",
                      "after_attempts": down_attempts,
                      "cursor": s.events_cursor,
                      "t_s": s._rel(clock()), "mark": s._mark}
                s.events.add(ev)
                s._emit("monitor_event", **ev)
                down_attempts = 0
            self.tick(rec)
            next_t += s.interval_s
            now = clock()
            if now > next_t:
                ev = {"event": "late_scrape", "t_s": s._rel(now),
                      "late_s": round(now - next_t, 4),
                      "slots_skipped": int((now - next_t) // s.interval_s),
                      "mark": s._mark}
                s.events.add(ev)
                s._emit("monitor_event", **ev)
                while next_t <= now:
                    next_t += s.interval_s
            elif stop.wait(next_t - now):
                break
        return self.ticks

    def summary(self) -> dict:
        """The ``handsoff`` block the dryrun commits inside its
        ``monitor_summary`` (the report's hands-off gate evidence)."""
        return {
            "ticks": self.ticks,
            "decisions": len(self.decisions),
            "scale_events": [
                {"direction": d.get("direction"),
                 "backends": d.get("backends"),
                 "decision": d.get("decision"),
                 "alert_episode": d.get("alert_episode"),
                 "burn_alert": d.get("burn_alert")}
                for d in self.decisions
            ],
            "reattaches": self.reattaches,
            "give_up": self.give_up,
        }
