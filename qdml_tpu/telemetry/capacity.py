"""Trace-replay capacity planner: ``qdml-tpu plan``
(docs/TELEMETRY.md "capacity planner").

PR 15's phase spans answer "where did the time go"; the production
question at fleet scale is "how many backends hold X rps at p99 <= Y ms".
This module closes that loop with a discrete-event queue model of the
batcher -> engine -> fetch pipeline whose inputs come from COMMITTED
artifacts, never from a live system:

- **service-time distributions**: each phase's committed quantile summary
  (``{n, mean_ms, p50_ms, p95_ms, p99_ms, max_ms}``) becomes an
  inverse-CDF piecewise-linear distribution (:class:`QuantileDist`) —
  the committed artifacts carry per-phase QUANTILES, not raw spans, so
  sampling interpolates the empirical CDF through its committed points;
- **arrival replay**: arrivals re-synthesize the traced arrival process
  (``arrival.process`` + ``offered_rps`` + ``n_requests`` from the
  window's own summary) — Poisson / MMPP-burst / uniform, seeded;
- **the queue core**: :func:`simulate_queue`, a c-server FIFO
  discrete-event simulation (Lindley recursion over a free-server heap).
  Its correctness is pinned against the EXACT M/D/1 waiting-time CDF
  (Crommelin's formula, :func:`md1_wait_cdf`) and the M/M/1 closed form
  in tests/test_capacity.py;
- **validation** (``plan --validate``): replay each committed window
  against ITSELF — phase dists + unattributed residual + replayed
  arrivals must reproduce the window's measured client p99 and
  throughput inside the documented band (predicted p99 within a factor
  of :data:`P99_BAND` either way, throughput within
  :data:`RPS_BAND_FRAC`). Windows without phase spans (trace sampling
  off) validate through the router's exactly-merged wire-latency
  distribution instead. This is a real consistency check, not a replay
  of the answer: client-side total-latency quantiles are NOT derivable
  from per-phase quantiles without the model's composition assumptions
  (independent phase draws, interpolated CDFs, constant residual), and
  a wrong queue model fails it at any utilization above noise;
- **planning** (``plan --target-rps=X --p99-ms=Y``): sweep backend
  counts; per candidate fleet size the DES makes queue wait ENDOGENOUS
  (service = the compute dist at ``workers`` servers per backend, the
  other phases ride along as exogenous adders), answering the hosts-for-
  X-rps question with the full predicted latency distribution, not a
  mean.

Validation band rule (docs/TELEMETRY.md): the band is |log(pred/meas)|
<= log(P99_BAND) for p99 and |pred-meas|/meas <= RPS_BAND_FRAC for
throughput. The 2-core CI harness carries real scheduler noise in its
tails; re-runs on quiet hardware can tighten both constants — but a band
this wide already rejects a planner that is wrong about WHICH regime a
window is in (queueing-dominated vs service-dominated vs wire-dominated).

Host-side only (no jax): ``qdml-tpu plan`` dispatches before the CLI's
platform/distributed init, like ``report``.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import math
import random

#: validation band: predicted p99 within this factor of measured (either way)
P99_BAND = 2.0
#: wire-mode band: the router's wire span cannot see client-side connection
#: queueing (a client stalling before the front socket inflates the measured
#: client tail with time no server/router span contains — observed factor
#: ~3.6-4.5 on a committed contended fleet window), so the weaker model gets
#: an order-of-magnitude band; throughput stays at the tight RPS_BAND_FRAC,
#: and the phase-span windows the PLANNER consumes hold the 2x P99_BAND
WIRE_P99_BAND = 6.0
#: validation band: predicted throughput within this fraction of measured
RPS_BAND_FRAC = 0.15

#: the routed request pipeline's phases, in span order (telemetry/tracing.py
#: PHASES + the router tier's wire/pick)
PHASE_ORDER = ("batch_wait", "queue_wait", "compute", "fetch", "wire", "pick")


class QuantileDist:
    """Inverse-CDF piecewise-linear distribution through committed
    quantile points. The q=0 anchor is set below p50 (at p50/4) — the
    artifacts do not carry a minimum, and anchoring at 0 would bias the
    body of a tight distribution downward."""

    def __init__(self, points: list[tuple[float, float]]):
        pts = sorted((float(q), max(0.0, float(v))) for q, v in points)
        if not pts or pts[0][0] > 0.0:
            lo = pts[0][1] if pts else 0.0
            pts.insert(0, (0.0, lo * 0.25))
        self.points = pts

    @classmethod
    def from_summary(cls, ph: dict | None) -> "QuantileDist | None":
        """From a committed ``{p50_ms, p95_ms, p99_ms, max_ms}`` block
        (phase summaries and Histogram.summary() share the shape)."""
        if not ph or ph.get("p50_ms") is None:
            return None
        pts = [(0.5, ph["p50_ms"])]
        for q, key in ((0.95, "p95_ms"), (0.99, "p99_ms"), (1.0, "max_ms")):
            if ph.get(key) is not None:
                pts.append((q, ph[key]))
        return cls(pts)

    def quantile(self, q: float) -> float:
        pts = self.points
        if q <= pts[0][0]:
            return pts[0][1]
        for (q0, v0), (q1, v1) in zip(pts, pts[1:]):
            if q <= q1:
                if q1 == q0:
                    return v1
                w = (q - q0) / (q1 - q0)
                return v0 + w * (v1 - v0)
        return pts[-1][1]

    def sample(self, rng: random.Random) -> float:
        return self.quantile(rng.random())

    def mean(self) -> float:
        """Mean of the piecewise-linear CDF (trapezoid over segments)."""
        total = 0.0
        for (q0, v0), (q1, v1) in zip(self.points, self.points[1:]):
            total += (q1 - q0) * (v0 + v1) / 2.0
        return total


def _percentile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(math.ceil(q * len(sorted_vals))) - 1))
    return sorted_vals[idx]


# ---------------------------------------------------------------------------
# arrivals + the queue core
# ---------------------------------------------------------------------------


def replay_arrivals(
    n: int,
    rate: float,
    process: str = "poisson",
    burstiness: float = 1.0,
    seed: int = 0,
) -> list[float]:
    """Re-synthesize the traced arrival process: ``n`` arrival times at
    mean ``rate``/s. Poisson draws exponential interarrivals; mmpp
    modulates between a hot state (rate * burstiness) and a cold state
    (balancing the mean); uniform is the deterministic pacer."""
    rng = random.Random(seed)
    if rate <= 0 or n <= 0:
        return [0.0] * max(0, n)
    out: list[float] = []
    t = 0.0
    if process == "uniform":
        step = 1.0 / rate
        return [i * step for i in range(n)]
    if process == "mmpp" and burstiness > 1.0:
        hot = rate * burstiness
        cold = rate / burstiness
        phase_len = max(4, n // 8)
        for i in range(n):
            r = hot if (i // phase_len) % 2 == 0 else cold
            t += rng.expovariate(r)
            out.append(t)
        return out
    for _ in range(n):
        t += rng.expovariate(rate)
        out.append(t)
    return out


def simulate_queue(
    arrivals: list[float], services: list[float], servers: int = 1
) -> list[float]:
    """c-server FIFO queue by discrete-event simulation: returns each
    job's queue WAIT (start - arrival), in arrival order. The free-server
    heap is the c-server generalization of the Lindley recursion; tests
    pin it against the exact M/D/1 and M/M/1 waiting-time laws."""
    free = [0.0] * max(1, int(servers))
    heapq.heapify(free)
    waits = []
    for t, s in zip(arrivals, services):
        f = heapq.heappop(free)
        start = f if f > t else t
        waits.append(start - t)
        heapq.heappush(free, start + s)
    return waits


# -- closed forms (the queue core's ground truth in tests) -------------------


def md1_wait_cdf(t: float, lam: float, d: float) -> float:
    """Exact M/D/1 waiting-time CDF (Crommelin):
    ``P(W <= t) = (1-rho) * sum_{j=0}^{floor(t/d)}
    (lam*(j*d - t))^j / j! * exp(-lam*(j*d - t))``. Stable in float64 for
    the moderate-utilization regimes the tests use (the alternating terms
    stay far from cancellation at rho <= ~0.8, t/d <= ~30)."""
    if t < 0:
        return 0.0
    rho = lam * d
    if rho >= 1.0:
        return 0.0
    k = int(t // d)
    s = 0.0
    for j in range(k + 1):
        u = lam * (j * d - t)  # <= 0
        s += (u ** j) / math.factorial(j) * math.exp(-u)
    return max(0.0, min(1.0, (1.0 - rho) * s))


def md1_wait_quantile(q: float, lam: float, d: float) -> float:
    """Invert :func:`md1_wait_cdf` numerically (bisection)."""
    lo, hi = 0.0, d
    while md1_wait_cdf(hi, lam, d) < q:
        hi *= 2.0
        if hi > 1e6 * d:
            return hi
    for _ in range(80):
        mid = (lo + hi) / 2.0
        if md1_wait_cdf(mid, lam, d) < q:
            lo = mid
        else:
            hi = mid
    return (lo + hi) / 2.0


def mm1_sojourn_quantile(q: float, lam: float, mu: float) -> float:
    """M/M/1 sojourn (wait + service) quantile: exponential with rate
    ``mu - lam``."""
    return -math.log(1.0 - q) / (mu - lam)


# ---------------------------------------------------------------------------
# artifact models
# ---------------------------------------------------------------------------


def load_summary(path: str) -> dict:
    """The window's ``serve_summary`` record from a committed JSONL."""
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("kind") == "serve_summary":
                return rec
    raise ValueError(f"no serve_summary record in {path}")


def window_model(summary: dict) -> dict:
    """What the committed window supports: ``mode='phases'`` when the
    window carries phase spans (trace sampling on), else ``mode='wire'``
    when the router's exactly-merged wire-latency distribution is there,
    else ``mode=None`` (not validatable)."""
    phases = {
        name: QuantileDist.from_summary((summary.get("phases") or {}).get(name))
        for name in PHASE_ORDER
    }
    phases = {k: v for k, v in phases.items() if v is not None}
    lat = summary.get("latency_ms") or {}
    recon = ((summary.get("trace") or {}).get("reconciliation")) or {}
    if phases:
        # unattributed residual: client-measured mean minus the phase-sum
        # mean — client-side overhead the spans cannot see, carried as a
        # constant shift (reconciliation block when present, else derived)
        resid = recon.get("mean_unattributed_ms")
        if resid is None and lat.get("mean_ms") is not None:
            resid = max(
                0.0,
                lat["mean_ms"] - sum(d.mean() for d in phases.values()),
            )
        return {"mode": "phases", "phases": phases,
                "residual_ms": float(resid or 0.0)}
    wire = QuantileDist.from_summary(
        ((summary.get("router") or {}).get("wire_latency_ms"))
    )
    if wire is not None:
        resid = 0.0
        if lat.get("mean_ms") is not None:
            resid = max(0.0, lat["mean_ms"] - wire.mean())
        return {"mode": "wire", "phases": {"wire": wire},
                "residual_ms": float(resid)}
    return {"mode": None, "phases": {}, "residual_ms": 0.0}


def _measured(summary: dict) -> dict:
    lat = summary.get("latency_ms") or {}
    return {
        "n": int(summary.get("n_requests") or summary.get("completed") or 0),
        "rps": float(summary.get("rps") or 0.0),
        "offered_rps": float(
            summary.get("offered_rps") or summary.get("rps") or 0.0
        ),
        "p99_ms": lat.get("p99_ms"),
        "mean_ms": lat.get("mean_ms"),
        "process": ((summary.get("arrival") or {}).get("process")) or "poisson",
        "burstiness": float(
            ((summary.get("arrival") or {}).get("burstiness")) or 1.0
        ),
    }


def validate_window(path: str, n_samples: int = 20000, seed: int = 0) -> dict:
    """Self-replay one committed window: sample every phase (plus the
    residual), replay the arrival process, and compare the predicted
    client p99 + throughput against the window's own measurements."""
    summary = load_summary(path)
    model = window_model(summary)
    meas = _measured(summary)
    row = {"path": path, "mode": model["mode"],
           "measured_p99_ms": meas["p99_ms"], "measured_rps": meas["rps"]}
    if model["mode"] is None or not meas["p99_ms"] or meas["n"] <= 0:
        row.update(predicted_p99_ms=None, p99_ratio=None, ok=None,
                   note="window carries neither phase spans nor wire quantiles")
        return row
    rng = random.Random(seed * 7919 + 13)
    totals = []
    for _ in range(n_samples):
        totals.append(
            sum(d.sample(rng) for d in model["phases"].values())
            + model["residual_ms"]
        )
    totals.sort()
    pred_p99 = _percentile(totals, 0.99)
    pred_mean = sum(totals) / len(totals)
    # throughput: replay the arrivals, complete each at arrival + sampled
    # latency; the predicted rate is requests over the completion span
    arr = replay_arrivals(meas["n"], meas["offered_rps"], meas["process"],
                          meas["burstiness"], seed=seed)
    rng2 = random.Random(seed * 104729 + 7)
    done = [
        t + (sum(d.sample(rng2) for d in model["phases"].values())
             + model["residual_ms"]) / 1e3
        for t in arr
    ]
    span = max(done) - min(arr) if done else 0.0
    pred_rps = meas["n"] / span if span > 0 else 0.0
    p99_ratio = pred_p99 / meas["p99_ms"]
    rps_err = abs(pred_rps - meas["rps"]) / meas["rps"] if meas["rps"] else None
    band = P99_BAND if model["mode"] == "phases" else WIRE_P99_BAND
    ok = (
        abs(math.log(p99_ratio)) <= math.log(band)
        and rps_err is not None and rps_err <= RPS_BAND_FRAC
    )
    row.update(
        predicted_p99_ms=round(pred_p99, 3),
        predicted_mean_ms=round(pred_mean, 3),
        measured_mean_ms=meas["mean_ms"],
        predicted_rps=round(pred_rps, 2),
        p99_ratio=round(p99_ratio, 4),
        rps_err=None if rps_err is None else round(rps_err, 4),
        band={"p99_factor": band, "rps_frac": RPS_BAND_FRAC},
        ok=ok,
    )
    return row


def validate_windows(paths: list[str], n_samples: int = 20000,
                     seed: int = 0) -> dict:
    rows = [validate_window(p, n_samples=n_samples, seed=seed) for p in paths]
    judged = [r for r in rows if r.get("ok") is not None]
    ratios = [abs(math.log(r["p99_ratio"])) for r in judged if r.get("p99_ratio")]
    errs = [r["rps_err"] for r in judged if r.get("rps_err") is not None]
    return {
        "rows": rows,
        "n_windows": len(judged),
        "ok": bool(judged) and all(r["ok"] for r in judged),
        "max_p99_ratio": (
            round(math.exp(max(ratios)), 4) if ratios else None
        ),
        "max_rps_err": round(max(errs), 4) if errs else None,
        "band": {"p99_factor": P99_BAND, "wire_p99_factor": WIRE_P99_BAND,
                 "rps_frac": RPS_BAND_FRAC},
    }


# ---------------------------------------------------------------------------
# planning sweep
# ---------------------------------------------------------------------------


def plan_backends(
    trace_path: str,
    target_rps: float,
    p99_ms: float,
    max_backends: int = 8,
    workers: int = 1,
    n_samples: int = 4000,
    seed: int = 0,
) -> dict:
    """Sweep fleet sizes against a target: for each candidate backend
    count the DES makes queue wait ENDOGENOUS — arrivals at the target
    rate hash-split across backends, each backend a ``workers``-server
    queue whose service is the traced compute(+fetch) distribution — and
    the other phases ride along as exogenous adders. Returns the sweep
    table and the smallest fleet meeting the p99 target (None when even
    ``max_backends`` misses it)."""
    summary = load_summary(trace_path)
    model = window_model(summary)
    if model["mode"] != "phases":
        raise ValueError(
            f"{trace_path} carries no phase spans — plan needs a traced "
            "window (serve.trace_sample > 0)"
        )
    phases = model["phases"]
    service_d = [d for name, d in phases.items() if name in ("compute", "fetch")]
    adders = [d for name, d in phases.items()
              if name not in ("compute", "fetch", "queue_wait")]
    rows = []
    answer = None
    for k in range(1, max(1, int(max_backends)) + 1):
        rng = random.Random(seed * 31 + k)
        per = max(1, n_samples // k)
        lam = target_rps / k
        all_latency: list[float] = []
        stable = True
        for _b in range(k):
            arr = replay_arrivals(per, lam, "poisson", seed=rng.randrange(1 << 30))
            svc = [sum(d.sample(rng) for d in service_d) / 1e3 for _ in range(per)]
            mean_svc = sum(svc) / len(svc) if svc else 0.0
            rho = lam * mean_svc / max(1, workers)
            if rho >= 0.98:
                stable = False
            waits = simulate_queue(arr, svc, servers=workers)
            for w, s in zip(waits, svc):
                extra = sum(d.sample(rng) for d in adders)
                all_latency.append(
                    (w + s) * 1e3 + extra + model["residual_ms"]
                )
        all_latency.sort()
        pred = _percentile(all_latency, 0.99)
        meets = stable and pred <= p99_ms
        rows.append({
            "backends": k,
            "per_backend_rps": round(lam, 2),
            "utilization": round(rho, 4),
            "stable": stable,
            "predicted_p99_ms": round(pred, 3),
            "meets_target": meets,
        })
        if meets and answer is None:
            answer = k
    return {
        "trace": trace_path,
        "target_rps": target_rps,
        "p99_target_ms": p99_ms,
        "workers_per_backend": workers,
        "sweep": rows,
        "backends_needed": answer,
    }


def emit_target(plan_rec: dict) -> dict:
    """The planner->autoscaler handoff record (``plan --emit-target``):
    the answer (``backends_needed``) plus everything it was conditioned on
    — target, workers, trace path — sealed under ``assumptions_sha``, a
    sha256 over the canonical planning inputs AND the full sweep table.
    The fleet autoscaler (control/fleet_scale.py) records the sha in every
    ``fleet_scale_event`` it emits while obeying this target, so a
    decision trail always says WHICH planning run it was obeying; a re-plan
    against a different trace or target changes the sha even when the
    answer count happens to match."""
    basis = {
        "trace": plan_rec["trace"],
        "target_rps": plan_rec["target_rps"],
        "p99_target_ms": plan_rec["p99_target_ms"],
        "workers_per_backend": plan_rec["workers_per_backend"],
        "sweep": plan_rec["sweep"],
    }
    sha = hashlib.sha256(
        json.dumps(basis, sort_keys=True).encode()
    ).hexdigest()
    return {
        "backends_needed": plan_rec["backends_needed"],
        "target_rps": plan_rec["target_rps"],
        "p99_target_ms": plan_rec["p99_target_ms"],
        "workers_per_backend": plan_rec["workers_per_backend"],
        "trace": plan_rec["trace"],
        "assumptions_sha": sha,
    }


# ---------------------------------------------------------------------------
# CLI: qdml-tpu plan
# ---------------------------------------------------------------------------


def _arg(argv: list[str], name: str, default):
    return next(
        (a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")),
        default,
    )


def plan_main(argv: list[str]) -> int:
    """``qdml-tpu plan --trace=W1.jsonl[,W2.jsonl...] --validate
    [--json=out.json] [--seed=0]`` gates every window's self-replay
    inside the band (exit 0 iff all pass); ``qdml-tpu plan
    --trace=traced.jsonl --target-rps=X --p99-ms=Y [--max-backends=8]
    [--workers=1]`` answers the capacity question; add
    ``--emit-target=target.json`` to also write the sealed
    planner->autoscaler handoff record (:func:`emit_target`). Host-side
    only."""
    traces = [p for p in (_arg(argv, "trace", "") or "").split(",") if p]
    if not traces:
        print("plan needs --trace=<window.jsonl>[,more.jsonl]")
        return 2
    seed = int(_arg(argv, "seed", "0"))
    out_json = _arg(argv, "json", None)
    if any(a == "--validate" for a in argv):
        rep = validate_windows(traces, seed=seed)
        print(json.dumps({"plan_validation": rep}, indent=2))
        if out_json:
            with open(out_json, "w") as fh:
                json.dump(rep, fh, indent=2)
        return 0 if rep["ok"] else 3
    target = _arg(argv, "target-rps", None)
    p99 = _arg(argv, "p99-ms", None)
    if target is None or p99 is None:
        print("plan needs --validate, or --target-rps=X with --p99-ms=Y")
        return 2
    rep = plan_backends(
        traces[0], float(target), float(p99),
        max_backends=int(_arg(argv, "max-backends", "8")),
        workers=int(_arg(argv, "workers", "1")),
        seed=seed,
    )
    print(json.dumps({"plan": rep}, indent=2))
    if out_json:
        with open(out_json, "w") as fh:
            json.dump(rep, fh, indent=2)
    target_json = _arg(argv, "emit-target", None)
    if target_json:
        # emitted even when backends_needed is None (the autoscaler's
        # loader refuses the null — an unmeetable plan must fail LOUDLY
        # at consumption, not silently vanish at emission)
        with open(target_json, "w") as fh:
            json.dump({"fleet_target": emit_target(rep)}, fh, indent=2)
    return 0 if rep["backends_needed"] is not None else 3
