"""Device/step counters: percentile histograms, memory stats, loop clocks.

The pre-telemetry loggers reported only means (``StepTimer.steps_per_sec``);
a flapping tunnelled backend hides multi-second stalls inside a good-looking
mean, so everything here reports p50/p95/max as well. :class:`StepClock` is
the shared train-loop instrumentation: the first dispatch of a run is the
compile+first-execute step and is recorded separately; subsequent steps
accumulate into steady-state (and host-transfer) histograms flushed as one
``counters`` record per epoch, alongside a device-memory snapshot and the
persistent-compile-cache hit/miss counters.
"""

from __future__ import annotations

import contextlib
import sys
import time
from typing import Iterator

from qdml_tpu.telemetry import spans as _spans


class Histogram:
    """Streaming duration collector; summarizes as p50/p95/max (ms)."""

    __slots__ = ("_vals",)

    def __init__(self):
        self._vals: list[float] = []

    def add(self, seconds: float) -> None:
        self._vals.append(seconds)

    def __len__(self) -> int:
        return len(self._vals)

    def reset(self) -> None:
        self._vals = []

    def merge(self, other: "Histogram") -> "Histogram":
        """Fold another histogram's samples into this one (the per-worker
        serving collectors aggregate this way). Exact, not approximate: the
        collector keeps raw samples, so merged quantiles equal quantiles of
        the concatenated sample set (property-tested in
        ``tests/test_numerics.py``). Returns ``self`` for chaining."""
        self._vals.extend(other._vals)
        return self

    def sum(self) -> float:
        """Exact sample sum (same unit the samples were added in). Counters
        that must aggregate EXACTLY across processes ship (n, sum) — two
        integers/floats that add — where quantiles cannot (the raw samples
        live in the producing process; see the router's phase aggregation)."""
        return sum(self._vals)

    def summary(self, unit: str | None = "ms") -> dict | None:
        """``{"n", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms"}`` or
        None if empty (p99 exists for the serving path, whose SLOs are tail
        latencies — train-loop readers ignore the extra key).

        ``unit=None`` summarizes UNITLESS samples honestly: no *1e3 scaling,
        unsuffixed keys (``mean``/``p50``/``p95``/``p99``/``max``) — the
        batch-fill / queue-depth / confidence collectors are counts and
        fractions, not durations, and used to be stored "as seconds" and
        rescaled on the way out."""
        if not self._vals:
            return None
        v = sorted(self._vals)

        def pct(p: float) -> float:
            return v[min(len(v) - 1, int(round(p / 100.0 * (len(v) - 1))))]

        if unit == "ms":
            fmt = lambda s: round(s * 1e3, 3)  # noqa: E731
            sfx = "_ms"
        else:
            fmt = lambda s: round(s, 4)  # noqa: E731
            sfx = ""
        return {
            "n": len(v),
            f"mean{sfx}": fmt(sum(v) / len(v)),
            f"p50{sfx}": fmt(pct(50)),
            f"p95{sfx}": fmt(pct(95)),
            f"p99{sfx}": fmt(pct(99)),
            f"max{sfx}": fmt(v[-1]),
        }


def device_memory_snapshot() -> dict | None:
    """Live-buffer count + per-device memory stats where the backend exposes
    them (``memory_stats()`` is None on CPU; fields degrade to absent, the
    snapshot itself never raises). None when jax was never imported."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    out: dict = {"devices": []}
    try:
        out["live_arrays"] = len(jax.live_arrays())
    except Exception:  # lint: disable=broad-except(live_arrays is backend-dependent diagnostics — never load-bearing)
        pass
    try:
        devs = jax.local_devices()
    except Exception:  # lint: disable=broad-except(no device enumeration means host-only counters)
        return out
    for d in devs:
        ent: dict = {"id": d.id, "kind": getattr(d, "device_kind", "?")}
        try:
            stats = d.memory_stats()
        except Exception:  # lint: disable=broad-except(per-device memory_stats is unsupported on some backends)
            stats = None
        if stats:
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
                if k in stats:
                    ent[k] = stats[k]
        out["devices"].append(ent)
    return out


class _StepCtx:
    """Handle yielded by :meth:`StepClock.step`; ``transfer()`` marks where
    dispatch ends and the host transfer/sync begins."""

    __slots__ = ("t_transfer",)

    def __init__(self):
        self.t_transfer: float | None = None

    def transfer(self) -> None:
        self.t_transfer = time.perf_counter()


class StepClock:
    """Per-loop step timing: compile vs steady state vs host transfer.

    >>> clock = StepClock("hdce_train")
    >>> with clock.step() as st:
    ...     state, m = train_step(state, batch)   # dispatch
    ...     st.transfer()                         # host transfer starts here
    ...     loss = float(m["loss"])
    >>> clock.epoch_end(epoch=0)                  # one counters record

    The first ``step()`` of the clock's life is the compile+first-execute
    dispatch: recorded as ``compile_s`` (and a ``compile_first_step`` span),
    excluded from the steady-state histogram. With async dispatch the
    pre-``transfer()`` segment is enqueue time and the transfer segment
    carries the device execution being waited on — exactly the host-side
    stall structure the tunnelled backend needs watched.
    """

    def __init__(self, name: str, sink=None):
        self.name = name
        self._sink = sink
        self.compile_s: float | None = None
        self.steps = Histogram()
        self.transfers = Histogram()

    def _target(self):
        return self._sink if self._sink is not None else _spans.get_sink()

    @contextlib.contextmanager
    def step(self) -> Iterator[_StepCtx]:
        ctx = _StepCtx()
        t0 = time.perf_counter()
        yield ctx
        t1 = time.perf_counter()
        if self.compile_s is None:
            self.compile_s = t1 - t0
            target = self._target()
            if target is not None and getattr(target, "active", False):
                target.emit(
                    "span",
                    name="compile_first_step",
                    path=f"{self.name}/compile_first_step",
                    depth=0,
                    dur_s=round(self.compile_s, 6),
                )
        else:
            self.steps.add(t1 - t0)
            if ctx.t_transfer is not None:
                self.transfers.add(t1 - ctx.t_transfer)

    def epoch_end(self, **tags) -> None:
        """Flush one ``counters`` record (step/transfer percentiles, memory
        snapshot, compile-cache hits/misses) and reset the histograms."""
        target = self._target()
        if target is not None and getattr(target, "active", False):
            from qdml_tpu.utils.compile_cache import compile_cache_stats

            target.emit(
                "counters",
                name=self.name,
                compile_s=round(self.compile_s, 6) if self.compile_s else None,
                step=self.steps.summary(),
                host_transfer=self.transfers.summary(),
                # explicit count (0 instead of a null summary): the
                # zero-steady-state-host-transfer contract of the scan-fused
                # loops is asserted off this field (tests/test_train.py), and
                # a reappearing transfer must be visible as a number, not as
                # the difference between null and non-null
                host_transfers=len(self.transfers),
                memory=device_memory_snapshot(),
                compile_cache=compile_cache_stats(),
                **tags,
            )
        self.steps.reset()
        self.transfers.reset()
