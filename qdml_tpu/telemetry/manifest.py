"""Run manifests: the provenance header every telemetry artifact starts with.

Ad-hoc result JSONs have repeatedly lost the knobs that produced them (the
r5 scan A/B records carried no ``rng_impl``/``trig_impl``; the pre-round-3
bench artifacts conflated two baseline scales). The manifest makes that class
of omission structural: config + content hash, git SHA, JAX/device topology,
the effective perf knobs, and the seeds, captured once at startup and written
as the first line of the run's JSONL.

jax is only touched if ``include_jax`` (and then lazily), so the bench
parent — which must never import jax (see ``bench.py``'s probe design) — can
still stamp host-side manifests.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import socket
import subprocess
import sys
import time
from typing import Any, Sequence

SCHEMA_VERSION = 1


def config_hash(cfg: Any) -> str:
    """Stable 16-hex content hash of a (nested) config dataclass or dict."""
    d = dataclasses.asdict(cfg) if dataclasses.is_dataclass(cfg) else cfg
    blob = json.dumps(d, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def effective_knobs(cfg: Any) -> dict:
    """The performance-relevant knobs whose omission has bitten before."""
    return {
        "rng_impl": cfg.data.rng_impl,
        "trig_impl": cfg.data.trig_impl,
        "moments_dtype": cfg.train.moments_dtype,
        "scan_steps": cfg.train.scan_steps,
        "optimizer": cfg.train.optimizer,
        "model_dtype": cfg.model.dtype,
        "conv_impl": cfg.model.conv_impl,
        "quantum_backend": cfg.quantum.backend,
        "quantum_impl": cfg.quantum.impl,
        "quantum_autotune": cfg.quantum.autotune,
        "mesh": {
            "data_axis": cfg.mesh.data_axis,
            "model_axis": cfg.mesh.model_axis,
            "fed_axis": cfg.mesh.fed_axis,
        },
    }


def _git_info() -> dict | None:
    """Best-effort repo SHA + dirty flag; None outside a usable git checkout."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    try:
        sha = subprocess.run(
            ["git", "rev-parse", "HEAD"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=root,
        )
        if sha.returncode != 0:
            return None
        status = subprocess.run(
            ["git", "status", "--porcelain"],
            capture_output=True,
            text=True,
            timeout=5,
            cwd=root,
        )
        return {
            "sha": sha.stdout.strip(),
            "dirty": bool(status.stdout.strip()) if status.returncode == 0 else None,
        }
    except Exception:  # lint: disable=broad-except(git absent or not a repo — the manifest ships without provenance rather than dying)
        return None


def _jax_info() -> dict:
    """JAX/device topology; errors degrade to a structured record, never raise."""
    try:
        import jax

        devs = jax.devices()
        return {
            "version": jax.__version__,
            "backend": jax.default_backend(),
            "process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "device_count": len(devs),
            "local_device_count": jax.local_device_count(),
            "device_kinds": sorted({d.device_kind for d in devs}),
        }
    except Exception as e:  # lint: disable=broad-except(a manifest must never kill a run; the failure is recorded in the manifest itself)
        return {"error": f"{type(e).__name__}: {e}"}


def run_manifest(
    cfg: Any = None,
    argv: Sequence[str] | None = None,
    include_jax: bool = True,
    extra: dict | None = None,
) -> dict:
    """Build the run-manifest record (``kind: "manifest"``).

    ``cfg`` (an :class:`qdml_tpu.config.ExperimentConfig`) adds the config
    hash, effective knobs, seeds and the full config dump. ``include_jax=False``
    keeps the manifest jax-free for host-side tools.
    """
    man: dict = {
        "kind": "manifest",
        "schema": SCHEMA_VERSION,
        "ts": round(time.time(), 3),
        "argv": list(argv) if argv is not None else None,
        "host": socket.gethostname(),
        "pid": os.getpid(),
        "python": sys.version.split()[0],
        "git": _git_info(),
        "jax": _jax_info() if include_jax else None,
    }
    if cfg is not None:
        man["name"] = getattr(cfg, "name", None)
        man["config_hash"] = config_hash(cfg)
        man["knobs"] = effective_knobs(cfg)
        man["seeds"] = {"data": cfg.data.seed, "train": cfg.train.seed}
        man["config"] = dataclasses.asdict(cfg)
    if extra:
        man.update(extra)
    return man
