"""Continuous fleet monitoring: the time-series scraper behind
``qdml-tpu monitor`` (docs/TELEMETRY.md "monitoring").

PR 15 decomposed every request's latency into phase spans and the fleet
tier aggregates exact counters — but both are consumed once, at end of
run. This module watches a LIVE serve/route address continuously:

- **scrape discipline**: only the cheap observability verbs, ever —
  ``{"op": "health"}`` (1 Hz contract, no histogram merges),
  ``{"op": "metrics"}`` (exact merged counters), and — when event tailing
  is on — ``{"op": "events"}`` (the cursor tail over the event spine,
  telemetry/events.py). The monitor never sends an inference request, so
  an attached monitor provably leaves the request path alone (the dryruns
  pin an all-zero request-path compile delta and a backend counter audit,
  scripts/monitor_dryrun.py, scripts/live_fleet_dryrun.py);
- **windowing**: cumulative counters are DIFFERENCED between consecutive
  scrapes into fixed-width windows (the PR-10 snapshot-differencing
  pattern the FleetController uses), through :func:`counter_delta` — the
  one sanctioned reset-safe helper. A restarted backend's counters start
  over; naive subtraction yields a negative "rate" that would page on
  recovery. ``counter_delta`` clamps the window and FLAGS it, and the
  scraper emits a structured ``counter_reset`` record instead of garbage
  (the ``unwindowed-cumulative-rate`` lint rule keeps ad-hoc
  cumulative/wall-time divisions out of the rest of the tree);
- **restart attribution**: the health verb's ``start_seq`` construction
  epoch (serve/server.py) names WHICH backend restarted between scrapes —
  ``uptime_s`` alone misses a restart older than the poll gap;
- **bounded state**: in-memory history lives in fixed-size rings
  (:class:`Ring`); a monitor attached for a week holds the same memory as
  one attached for a minute. The full stream appends to manifest-headed
  JSONL (kinds: ``monitor_timeseries``, ``monitor_event``,
  ``counter_reset``, ``monitor_alert``, ``monitor_summary``).

Burn-rate evaluation itself lives in telemetry/burnrate.py; the capacity
planner in telemetry/capacity.py. All three are host-side tools — no jax
import anywhere on this path (``qdml-tpu monitor`` dispatches before the
CLI's platform/distributed init, like ``report`` and ``lint``).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from qdml_tpu.telemetry.events import publish as publish_event


def counter_delta(prev, cur) -> tuple[float, bool]:
    """Reset-safe cumulative-counter differencing: ``(delta, reset)``.

    The sanctioned way to turn two snapshots of a monotonic counter into a
    window. When ``cur < prev`` the source restarted (process death, pool
    re-spawn, an aggregation that lost a member mid-poll): the honest
    window is unknowable, so the delta clamps to ``cur`` (everything the
    reborn counter has seen) and ``reset=True`` tells the caller to emit a
    structured ``counter_reset`` instead of feeding detectors a negative
    rate. ``None`` snapshots count as 0 (a backend that has not reported
    yet)."""
    p = float(prev or 0)
    c = float(cur or 0)
    if c < p:
        return c, True
    return c - p, False


class SnapshotDiff:
    """Named cumulative counters differenced across polls (reset-safe).

    One instance per monitored stream; :meth:`window` returns this poll's
    delta for one named counter and records the new snapshot. Resets are
    per-name: one backend's restart must not poison every other counter's
    window."""

    def __init__(self):
        self._prev: dict[str, float] = {}

    def window(self, name: str, cur) -> tuple[float, bool]:
        delta, reset = counter_delta(self._prev.get(name), cur)
        self._prev[name] = float(cur or 0)
        return delta, reset


class Ring:
    """Fixed-capacity record history (newest-wins, O(1) append).

    The monitor's only in-memory state: render/evaluate reads walk the
    ring, the JSONL stream keeps the full history on disk."""

    def __init__(self, cap: int = 512):
        self._q: deque = deque(maxlen=int(cap))

    def add(self, rec: dict) -> None:
        self._q.append(rec)

    def __len__(self) -> int:
        return len(self._q)

    def __iter__(self):
        return iter(list(self._q))

    def last(self) -> dict | None:
        return self._q[-1] if self._q else None


def _num(x) -> float:
    """A counter that may arrive as an int, a float, or a per-kind dict
    (the fleet aggregation's ``shed``/``faults`` blocks sum per kind)."""
    if isinstance(x, dict):
        return float(sum(v or 0 for v in x.values()))
    return float(x or 0)


def _breaker_totals(m: dict, h: dict) -> dict:
    """Fast-fail/admission counters + state, from whichever view carries
    them: the single-host snapshot's top-level ``breaker`` block, or the
    fleet aggregation's per-backend rows."""
    blk = m.get("breaker") or h.get("breaker")
    if isinstance(blk, dict):
        return {
            "fast_fails": _num(blk.get("fast_fails")),
            "admitted": _num(blk.get("admitted")),
            "states": {"_": str(blk.get("state"))},
        }
    out = {"fast_fails": 0.0, "admitted": 0.0, "states": {}}
    for bid, row in (m.get("per_backend") or {}).items():
        b = (row or {}).get("breaker")
        if isinstance(b, dict):
            out["fast_fails"] += _num(b.get("fast_fails"))
            out["admitted"] += _num(b.get("admitted"))
            out["states"][str(bid)] = str(b.get("state"))
    return out


class MonitorScraper:
    """The continuous scrape loop over one poller (SocketPoller at a serve
    or router address, FleetPoller in-process, or any object with
    ``health()``/``metrics()``).

    Each :meth:`scrape_once`:

    1. polls ``health`` + ``metrics`` (the ONLY verbs it ever sends);
    2. differences every cumulative counter into this window
       (:class:`SnapshotDiff`), emitting ``counter_reset`` records for any
       that went backwards;
    3. derives ``monitor_event`` records from snapshot changes — backend
       restart (``start_seq`` changed / ``uptime_s`` went down),
       quarantine-set growth, breaker transitions, swap-epoch bumps,
       router ejection/re-admission deltas;
    4. feeds the windowed error/total pairs into the burn-rate alerter
       (telemetry/burnrate.py) and emits any ``monitor_alert``
       transitions;
    5. appends one ``monitor_timeseries`` record.

    ``mark(tag)`` labels subsequent windows (the dryrun tags its baseline
    / fault / recovery segments, and the alert-expectation report gate is
    judged per tag). ``feed_external`` lets a harness wire client-side
    ledgers (stranded futures live in the loadgen, not the server) into
    the same alerter.
    """

    #: burn signals derived from server-side counters every scrape
    SIGNALS = ("slo", "shed", "breaker", "quarantine", "router")

    def __init__(
        self,
        poller,
        sink=None,
        interval_s: float = 1.0,
        alerter=None,
        ring: int = 512,
        clock=time.monotonic,
        tail_events: bool = False,
    ):
        self.poller = poller
        self.sink = sink
        self.interval_s = float(interval_s)
        self.alerter = alerter
        self.clock = clock
        self.ring = Ring(ring)
        self.events = Ring(ring)
        self.alerts = Ring(ring)
        self.diff = SnapshotDiff()
        self.seq = 0
        self.scrape_errors = 0
        self.resets_total = 0
        self._t0: float | None = None
        self._last_t: float | None = None
        self._mark = ""
        self._marks: list[str] = []
        self._prev_backends: dict[str, dict] = {}
        self._prev_breaker_states: dict[str, str] = {}
        self._prev_swap_epoch: int | None = None
        self._prev_quarantined = 0
        # event-spine tail state (telemetry/events.py): the cursor is the
        # poller's verbatim reply cursor — per-source ``(start_seq, seq)``
        # pairs from a router, one pair from a single host — so resume after
        # a reconnect (or a backend restart) has no gaps and no duplicates.
        # The loss ledger is the report's always-armed zero-loss gate:
        # event_drops tracks the endpoints' cumulative ring evictions,
        # events_lost the evictions that lapped THIS cursor specifically.
        self.tail_events = bool(tail_events)
        self.events_cursor: dict | None = None
        self.events_seen = 0
        self.event_drops = 0
        self.events_lost = 0

    # -- emission ------------------------------------------------------------

    def _emit(self, kind: str, **payload) -> dict:
        if self.sink is not None and getattr(self.sink, "active", True):
            self.sink.emit(kind, **payload)
        if kind != "spine_event":
            # monitor records join the event spine too — but a tailed
            # envelope must NOT be re-published: a monitor co-resident with
            # its router would echo the spine into itself forever
            publish_event(kind, tier="monitor", **payload)
        return payload

    def mark(self, tag: str) -> None:
        """Label windows scraped from now on (dryrun segments; the
        per-segment alert-expectation gate keys on these)."""
        self._mark = str(tag)
        if self._mark and self._mark not in self._marks:
            self._marks.append(self._mark)
        self._emit("monitor_event", event="mark", mark=self._mark,
                   t_s=self._rel(self.clock()))

    def _rel(self, t: float) -> float:
        if self._t0 is None:
            self._t0 = t
        return round(t - self._t0, 4)

    # -- derived events ------------------------------------------------------

    def _backend_rows(self, h: dict) -> dict[str, dict]:
        per = h.get("per_backend")
        if isinstance(per, dict):
            return {str(k): (v or {}) for k, v in per.items()}
        return {str(h.get("host_id") or "local"): h}

    def _derive_events(self, h: dict, t_s: float) -> list[dict]:
        evs: list[dict] = []
        rows = self._backend_rows(h)
        # membership deltas (elastic fleet, docs/FLEET.md): a backend id
        # appearing after the first scrape was admitted, one disappearing
        # was retired — the timeline then correlates scale events with burn
        # trajectories. The first scrape seeds silently (the boot-time set
        # is not an admission), and per-backend diff state is dropped on
        # retirement so a later same-id re-admission diffs fresh.
        if self._prev_backends:
            for bid in rows.keys() - self._prev_backends.keys():
                evs.append({"event": "backend_admitted", "backend": bid,
                            "state": rows[bid].get("state")})
            for bid in self._prev_backends.keys() - rows.keys():
                evs.append({"event": "backend_retired", "backend": bid})
                del self._prev_backends[bid]
        for bid, row in rows.items():
            prev = self._prev_backends.get(bid)
            seq, up = row.get("start_seq"), row.get("uptime_s")
            if prev is not None:
                p_seq, p_up = prev.get("start_seq"), prev.get("uptime_s")
                restarted = (
                    seq is not None and p_seq is not None and seq != p_seq
                ) or (
                    seq is None and up is not None and p_up is not None
                    and up < p_up
                )
                if restarted:
                    evs.append({"event": "backend_restart", "backend": bid,
                                "start_seq": seq, "uptime_s": up})
                if row.get("poll_ok") is False and prev.get("poll_ok") is True:
                    evs.append({"event": "backend_unreachable", "backend": bid})
            self._prev_backends[bid] = {
                "start_seq": seq, "uptime_s": up,
                "poll_ok": row.get("poll_ok"),
            }
        q = h.get("quarantined")
        qn = len(q) if isinstance(q, (list, tuple)) else int(q or 0)
        if qn > self._prev_quarantined:
            evs.append({"event": "quarantine",
                        "delta": qn - self._prev_quarantined, "now": qn})
        self._prev_quarantined = qn
        swap = h.get("swap_epoch")
        if swap is not None and self._prev_swap_epoch is not None \
                and swap != self._prev_swap_epoch:
            evs.append({"event": "swap_epoch", "from": self._prev_swap_epoch,
                        "to": swap})
        if swap is not None:
            self._prev_swap_epoch = int(swap)
        return evs

    def _breaker_events(self, states: dict[str, str]) -> list[dict]:
        evs = []
        for bid, st in states.items():
            p = self._prev_breaker_states.get(bid)
            if p is not None and st != p and st != "None":
                evs.append({"event": "breaker_transition", "backend": bid,
                            "from": p, "to": st})
            self._prev_breaker_states[bid] = st
        return evs

    # -- the scrape ----------------------------------------------------------

    def scrape_once(self) -> dict | None:
        """One window: poll, difference, derive, alert, emit. Returns the
        ``monitor_timeseries`` payload (None on a failed poll — the scrape
        survives a restarting endpoint and reports it)."""
        t = self.clock()
        t_s = self._rel(t)
        try:
            h = self.poller.health()
            m = self.poller.metrics()
        except Exception as e:  # lint: disable=broad-except(a monitor must survive its target restarting mid-scrape: the failed poll is itself the observation, reported as a scrape_error event)
            self.scrape_errors += 1
            ev = {"event": "scrape_error", "t_s": t_s,
                  "error": f"{type(e).__name__}: {e}"}
            self.events.add(ev)
            self._emit("monitor_event", **ev)
            return None
        dt = None if self._last_t is None else round(t - self._last_t, 4)
        self._last_t = t

        resets: list[str] = []

        def win(name: str, cur) -> float:
            d, reset = self.diff.window(name, cur)
            if reset:
                resets.append(name)
            return d

        d_completed = win("completed", m.get("completed"))
        d_shed = win("shed", _num(m.get("shed")))
        d_restarts = win("restarts", m.get("restarts"))
        d_faults = win("faults", _num(m.get("faults")))
        slo = m.get("slo") or {}
        d_slo_n = win("slo_n", slo.get("n"))
        d_slo_met = win("slo_met", slo.get("met"))
        brk = _breaker_totals(m, h)
        d_ff = win("breaker_fast_fails", brk["fast_fails"])
        d_adm = win("breaker_admitted", brk["admitted"])
        router = h.get("router") or {}
        d_fwd = win("router_forwarded", router.get("forwarded"))
        d_rfail = win("router_failed", router.get("failed_forwards"))
        d_fov = win("router_failovers", router.get("failovers"))
        d_eject = win("router_ejections", router.get("ejections"))
        d_readmit = win("router_readmissions", router.get("readmissions"))

        for name in resets:
            self.resets_total += 1
            self._emit("counter_reset", counter=name, t_s=t_s,
                       mark=self._mark)

        evs = self._derive_events(h, t_s)
        evs.extend(self._breaker_events(brk["states"]))
        if d_restarts > 0:
            evs.append({"event": "replica_restart", "delta": d_restarts})
        if d_eject > 0:
            evs.append({"event": "backend_ejected", "delta": d_eject})
        if d_readmit > 0:
            evs.append({"event": "backend_readmitted", "delta": d_readmit})
        for ev in evs:
            ev.setdefault("t_s", t_s)
            ev.setdefault("mark", self._mark)
            self.events.add(ev)
            self._emit("monitor_event", **ev)

        replicas = int(h.get("replicas") or h.get("workers") or 1)
        quarantine_errs = (
            sum(e.get("delta", 1) for e in evs
                if e["event"] in ("quarantine", "replica_restart",
                                  "backend_restart"))
        )
        burn = {}
        fired: list[dict] = []
        if self.alerter is not None and dt is not None:
            self.alerter.feed(t_s, "slo", d_slo_n - d_slo_met, d_slo_n)
            self.alerter.feed(t_s, "shed", d_shed, d_completed + d_shed)
            self.alerter.feed(t_s, "breaker", d_ff, d_adm + d_ff)
            self.alerter.feed(t_s, "quarantine", quarantine_errs,
                              max(1, replicas))
            if router:
                self.alerter.feed(t_s, "router", d_rfail + d_fov, d_fwd)
            fired = self.alerter.evaluate(t_s, mark=self._mark)
            for a in fired:
                self.alerts.add(a)
                self._emit("monitor_alert", **a)
            burn = self.alerter.burns(t_s)

        self.seq += 1
        rec = {
            "seq": self.seq,
            "t_s": t_s,
            "dt_s": dt,
            "mark": self._mark,
            "completed": d_completed,
            "rps": None if not dt else round(d_completed / dt, 3),
            "shed": d_shed,
            "faults": d_faults,
            "restarts": d_restarts,
            "slo": (
                None if d_slo_n <= 0
                else {"n": d_slo_n, "met": d_slo_met,
                      "attainment": round(d_slo_met / d_slo_n, 4)}
            ),
            "breaker": {"fast_fails": d_ff, "admitted": d_adm,
                        "states": brk["states"]},
            "router": (
                None if not router
                else {"forwarded": d_fwd, "failed": d_rfail,
                      "failovers": d_fov, "ejections": d_eject,
                      "readmissions": d_readmit}
            ),
            "queue_depth": int(h.get("queue_depth") or 0),
            "replicas": replicas,
            "backends": h.get("backends"),
            "backends_live": h.get("backends_live"),
            "swap_epoch": h.get("swap_epoch"),
            "resets": resets or None,
            "burn": burn or None,
            "alerts": [a["signal"] for a in fired] or None,
        }
        if self.tail_events:
            spine = self.scrape_events()
            rec["spine"] = {
                "events": len(spine),
                "event_drops": self.event_drops,
                "events_lost": self.events_lost,
            }
        self.ring.add(rec)
        self._emit("monitor_timeseries", **rec)
        return rec

    def scrape_events(self) -> list[dict]:
        """Tail the endpoint's event spine from the last seen cursor — the
        third and last sanctioned scrape verb (``{"op": "events"}``). Each
        received envelope re-emits into the monitor stream as a
        ``spine_event`` record (nested under ``ev`` — envelopes carry their
        own ``kind``/``ts``), and the reply's loss ledger folds into
        ``event_drops``/``events_lost``. A poller without an ``events``
        verb downgrades to the two-verb scrape silently."""
        if not hasattr(self.poller, "events"):
            return []
        try:
            t = self.poller.events(self.events_cursor)
        except Exception as e:  # lint: disable=broad-except(the events tail must survive its target restarting mid-scrape exactly like health/metrics: the failed poll is the observation, and the kept cursor resumes the tail on reconnect)
            self.scrape_errors += 1
            ev = {"event": "scrape_error", "verb": "events",
                  "t_s": self._rel(self.clock()),
                  "error": f"{type(e).__name__}: {e}"}
            self.events.add(ev)
            self._emit("monitor_event", **ev)
            return []
        evs = t.get("events") or []
        if "cursor" in t:
            # aggregated router reply: per-source cursors, passed back
            # verbatim next poll (each survives its own backend's restarts
            # through the start_seq epoch)
            self.events_cursor = t["cursor"]
        else:
            self.events_cursor = {"start_seq": t.get("start_seq"),
                                  "seq": t.get("next_seq")}
        self.event_drops = max(self.event_drops, int(t.get("dropped") or 0))
        self.events_lost += int(t.get("lost") or 0)
        self.events_seen += len(evs)
        for e in evs:
            self._emit("spine_event", ev=e)
        return evs

    def feed_external(self, signal: str, errors: float, total: float) -> None:
        """Client-side ledgers (stranded futures, give-ups) into the same
        alerter: the server cannot observe a client that hung forever, so
        harnesses that hold the loadgen summary wire it here."""
        if self.alerter is not None:
            t_s = self._rel(self.clock())
            self.alerter.feed(t_s, signal, errors, total)
            for a in self.alerter.evaluate(t_s, mark=self._mark):
                self.alerts.add(a)
                self._emit("monitor_alert", **a)

    def run(self, duration_s: float, stop: threading.Event | None = None) -> int:
        """Scrape every ``interval_s`` for ``duration_s`` (or until
        ``stop``); returns the number of windows taken.

        Scrapes anchor to an ABSOLUTE monotonic grid (``next_t +=
        interval``): the old sleep-after-each-scrape schedule accumulated
        every scrape's latency as skew, so a week-long attachment drifted
        its window boundaries by hours. A scrape that overruns its slot
        emits an honest ``late_scrape`` event (how late, how many slots it
        blew through) and realigns to the next FUTURE slot — no burst of
        catch-up scrapes, and no silent pretense the cadence held."""
        stop = stop or threading.Event()
        start = self.clock()
        end = start + float(duration_s)
        next_t = start
        while self.clock() < end and not stop.is_set():
            self.scrape_once()
            next_t += self.interval_s
            now = self.clock()
            if now > next_t:
                ev = {"event": "late_scrape", "t_s": self._rel(now),
                      "late_s": round(now - next_t, 4),
                      "slots_skipped": int((now - next_t) // self.interval_s),
                      "mark": self._mark}
                self.events.add(ev)
                self._emit("monitor_event", **ev)
                while next_t <= now:
                    next_t += self.interval_s
            elif stop.wait(next_t - now):
                break
        return self.seq

    def summary(self, extra: dict | None = None) -> dict:
        """The ``monitor_summary`` payload (emitted by :meth:`finish`):
        window/alert/reset totals, per-mark alert counts, peak burn per
        signal — the facts the report's monitor gates read."""
        by_mark: dict[str, int] = {m: 0 for m in self._marks}
        by_signal: dict[str, int] = {}
        firing = resolved = 0
        for a in self.alerts:
            if a.get("state") == "firing":
                firing += 1
                by_mark[a.get("mark") or ""] = by_mark.get(a.get("mark") or "", 0) + 1
                by_signal[a["signal"]] = by_signal.get(a["signal"], 0) + 1
            elif a.get("state") == "resolved":
                resolved += 1
        out = {
            "windows": self.seq,
            "interval_s": self.interval_s,
            "duration_s": self._rel(self.clock()) if self._t0 is not None else 0.0,
            "scrape_errors": self.scrape_errors,
            "counter_resets": self.resets_total,
            "events": len(self.events),
            "alerts": {"fired": firing, "resolved": resolved,
                       "by_mark": by_mark, "by_signal": by_signal},
            "peak_burn": None if self.alerter is None else self.alerter.peaks(),
        }
        if self.tail_events:
            # the spine loss ledger the always-armed event_drops report
            # gate reads: endpoint ring evictions + evictions past this
            # cursor — "zero event loss" means BOTH stayed zero
            out["event_drops"] = self.event_drops + self.events_lost
            out["spine"] = {"events": self.events_seen,
                            "ring_dropped": self.event_drops,
                            "cursor_lost": self.events_lost}
        if extra:
            out.update(extra)
        return out

    def finish(self, extra: dict | None = None) -> dict:
        rec = self.summary(extra)
        self._emit("monitor_summary", **rec)
        return rec


# ---------------------------------------------------------------------------
# CLI: qdml-tpu monitor
# ---------------------------------------------------------------------------


def _arg(argv: list[str], name: str, default):
    return next(
        (a.split("=", 1)[1] for a in argv if a.startswith(f"--{name}=")),
        default,
    )


def monitor_main(argv: list[str]) -> int:
    """``qdml-tpu monitor --addr=HOST:PORT [--interval=1.0] [--duration=30]
    [--out=monitor.jsonl] [--slo-target=0.99] [--threshold=8]
    [--fast=0 --slow=0 (0 = scale to duration)] [--debounce=2]`` — attach,
    scrape, alert, summarize; or ``qdml-tpu monitor --render
    --current=monitor.jsonl [--events=a.jsonl,b.jsonl] [--out=timeline.md]``
    to render the committed stream as the markdown timeline dashboard.

    ``--attach`` turns the scrape into the HANDS-OFF loop (docs/CONTROL.md,
    telemetry/attach.py): every finished window also ticks a
    :class:`FleetAutoscaler` acting through the endpoint's ``{"op":
    "fleet"}`` verb, the event spine is tailed per window, and a front-door
    restart reconnects with backoff (``monitor_reattach``; typed give-up
    exit 3 after ``--max-reconnects``, never a traceback). Knobs:
    ``--min-backends/--max-backends/--queue-high/--queue-low/
    --scale-debounce/--cooldown/--max-reconnects/--dry-run``, plus
    ``--target=plan.json`` to pin a planner target.
    Host-side only: no jax, no config, no inference on the scrape path."""
    from qdml_tpu.telemetry.burnrate import BurnAlerter, render_timeline

    if any(a == "--render" for a in argv):
        cur = _arg(argv, "current", None)
        if not cur:
            print("monitor --render needs --current=<monitor.jsonl>")
            return 2
        records = []
        with open(cur) as fh:
            for line in fh:
                line = line.strip()
                if line:
                    records.append(json.loads(line))
        extra = []
        ev_paths = _arg(argv, "events", "")
        for p in [x for x in ev_paths.split(",") if x]:
            with open(p) as fh:
                for line in fh:
                    line = line.strip()
                    if line:
                        extra.append(json.loads(line))
        md = render_timeline(records, extra_events=extra)
        out = _arg(argv, "out", None)
        if out:
            with open(out, "w") as fh:
                fh.write(md)
            print(f"wrote {out}")
        else:
            print(md)
        return 0

    addr = _arg(argv, "addr", None)
    if not addr or ":" not in addr:
        print("monitor needs --addr=HOST:PORT (a serve or route endpoint)")
        return 2
    host, port = addr.rsplit(":", 1)
    interval = float(_arg(argv, "interval", "1.0"))
    duration = float(_arg(argv, "duration", "30"))
    out_path = _arg(argv, "out", "monitor.jsonl")
    slo_target = float(_arg(argv, "slo-target", "0.99"))
    threshold = float(_arg(argv, "threshold", "8"))
    fast = float(_arg(argv, "fast", "0"))
    slow = float(_arg(argv, "slow", "0"))
    debounce = int(_arg(argv, "debounce", "2"))

    from qdml_tpu.control.loop import SocketPoller
    from qdml_tpu.telemetry.manifest import run_manifest
    from qdml_tpu.utils.metrics import MetricsLogger

    alerter = BurnAlerter.for_run(
        duration_s=duration, interval_s=interval, slo_target=slo_target,
        threshold=threshold, fast_s=fast or None, slow_s=slow or None,
        debounce=debounce,
    )
    logger = MetricsLogger(
        out_path, echo=False,
        manifest=run_manifest(argv=["monitor"] + list(argv), include_jax=False),
    )
    attach = any(a == "--attach" for a in argv)
    scraper = MonitorScraper(
        SocketPoller(host, int(port), timeout_s=max(5.0, interval * 4)),
        sink=logger.telemetry, interval_s=interval, alerter=alerter,
        tail_events=attach,
    )
    give_up = None
    try:
        if attach:
            from qdml_tpu.control.fleet_scale import (
                FleetAutoscaler, load_planner_target,
            )
            from qdml_tpu.telemetry.attach import MonitorAttachment

            # the actuator is a SEPARATE poller: the scrape path stays on
            # the three read verbs, the fleet verb is the acting path
            actuator = SocketPoller(
                host, int(port), timeout_s=max(5.0, interval * 4)
            )
            autoscaler = FleetAutoscaler(
                lambda n: actuator.fleet(backends=n),
                min_backends=int(_arg(argv, "min-backends", "1")),
                max_backends=int(_arg(argv, "max-backends", "4")),
                queue_high=float(_arg(argv, "queue-high", "32")),
                queue_low=float(_arg(argv, "queue-low", "2")),
                debounce=int(_arg(argv, "scale-debounce", "2")),
                cooldown_ticks=int(_arg(argv, "cooldown", "5")),
                sink=logger.telemetry,
                dry_run=any(a == "--dry-run" for a in argv),
            )
            target = _arg(argv, "target", None)
            if target:
                autoscaler.set_planner_target(load_planner_target(target))
            attachment = MonitorAttachment(
                scraper, autoscaler,
                max_reconnects=int(_arg(argv, "max-reconnects", "8")),
            )
            attachment.run(duration)
            give_up = attachment.give_up
            summary = scraper.finish(extra={"handsoff": attachment.summary()})
        else:
            scraper.run(duration)
            summary = scraper.finish()
    finally:
        logger.close()
    print(json.dumps({"monitor": summary}, default=str))
    return 3 if give_up else 0
