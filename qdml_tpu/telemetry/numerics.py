"""Numerics flight recorder: on-device training probes + divergence watchdog.

PR 1 made *time* observable (spans, step clocks) and PR 2 made *serving*
observable (tail latencies, shed counts); nothing yet explains *why a run
went bad*. A NaN loss in the QSC loop (QuantumNAT's Gaussian parameter-noise
injection is exactly the knob that silently destabilizes training) or a
slowly exploding gradient norm used to surface as a garbage checkpoint hours
later. This module makes per-step numerics first-class artifacts:

- :func:`probe_tree` — jit-safe gradient/update statistics computed ON DEVICE
  inside the existing train step (global + per-branch grad norms, update-to-
  param ratios, a fused nonfinite count). The step function returns them in
  its metrics dict, so they ride the step's existing output: no extra
  compiles (the probe is part of the one compiled program — pinned by
  ``tests/test_numerics.py`` against the ``utils/compile_cache`` counters)
  and ONE extra device→host transfer per *logged* step only (the scalars sit
  on device until the recorder's cadence fetches them).
- :class:`Watchdog` — the trip policy: nonfinite loss/grads/updates, or a
  configurable grad-norm ceiling (``train.watchdog_grad_norm_max``).
- :class:`FlightRecorder` — the per-loop integration object every trainer
  drives: emits ``numerics`` records into the run's manifest-headed JSONL on
  the ``train.probe_every`` cadence, snapshots last-known-good params, and on
  a watchdog trip dumps a post-mortem bundle to
  ``<results_dir>/<run>/flightrec/`` (bundle.json: reason, offending
  step/epoch/batch info, rng key, probe history tail; ``last_good`` params
  via :mod:`qdml_tpu.train.checkpoint`) before raising a typed
  :class:`DivergenceError` that names the dump.

Formats and semantics: ``docs/FLIGHTREC.md``.
"""

from __future__ import annotations

import json
import math
import os
import time
from collections import deque
from collections.abc import Mapping
from typing import Any

import numpy as np

# NOTE: jax is imported lazily inside the functions that need it — this
# module rides in ``qdml_tpu.telemetry``'s namespace, which the bench PARENT
# process imports, and that process must never import jax (bench.py's probe
# design: a hung tunnelled backend must not be able to hang the harness).
from qdml_tpu.telemetry import spans as _spans
from qdml_tpu.telemetry.core import is_primary

HISTORY_TAIL = 32  # probe records retained for the post-mortem bundle
# last-good param snapshot cadence when probes are compiled out
# (probe_every=0 with the watchdog still armed): the loss checks alone
# qualify a step as clean, and without SOME refresh cadence every dump
# would "restore" to the step-0 init params.
LAST_GOOD_FALLBACK_EVERY = 100


class DivergenceError(RuntimeError):
    """Training diverged (NaN/Inf or grad-norm explosion) and the watchdog
    converted the would-be garbage run into a typed failure. ``dump_dir``
    points at the flight-recorder bundle (``None`` when this process is not
    the primary writer); ``reason`` is the trip condition."""

    def __init__(self, message: str, dump_dir: str | None, reason: str):
        super().__init__(message)
        self.dump_dir = dump_dir
        self.reason = reason


# ---------------------------------------------------------------------------
# On-device probes (traceable; called inside the jitted train steps)
# ---------------------------------------------------------------------------


def _sumsq(tree):
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.float32(0.0)
    return sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)


def _nonfinite_count(tree):
    import jax
    import jax.numpy as jnp

    leaves = jax.tree.leaves(tree)
    if not leaves:
        return jnp.int32(0)
    return sum(
        jnp.sum(~jnp.isfinite(l.astype(jnp.float32))) for l in leaves
    ).astype(jnp.int32)


def probe_tree(grads, params=None, updates=None) -> dict:
    """Numerics probe over one step's gradient (and optionally param/update)
    trees. Traceable: pure reductions to scalars, safe under ``jit``,
    ``lax.scan``, ``vmap`` and ``shard_map`` (replicated inputs; all outputs
    are tiny). Norms accumulate in f32 regardless of leaf dtype.

    Returns (all jnp scalars):

    - ``grad_norm`` — global L2 norm of ``grads``;
    - ``branch_grad_norm`` — per-top-level-branch L2 norms (the keys are the
      tree's static child names, so the dict structure is trace-stable);
    - ``param_norm`` / ``update_norm`` / ``update_ratio``
      (= ``update_norm / (param_norm + 1e-12)``) when the trees are given;
    - ``nonfinite`` — fused NaN/Inf element count over grads AND updates (one
      int32: a single flag the watchdog can test with one comparison).
    """
    import jax.numpy as jnp

    out: dict[str, Any] = {}
    out["grad_norm"] = jnp.sqrt(_sumsq(grads))
    if isinstance(grads, Mapping):
        out["branch_grad_norm"] = {
            str(k): jnp.sqrt(_sumsq(v)) for k, v in grads.items()
        }
    nonfinite = _nonfinite_count(grads)
    if params is not None:
        out["param_norm"] = jnp.sqrt(_sumsq(params))
    if updates is not None:
        out["update_norm"] = jnp.sqrt(_sumsq(updates))
        nonfinite = nonfinite + _nonfinite_count(updates)
        if params is not None:
            out["update_ratio"] = out["update_norm"] / (out["param_norm"] + 1e-12)
    out["nonfinite"] = nonfinite
    return out


# ---------------------------------------------------------------------------
# Host side: jsonification, watchdog policy, flight recorder
# ---------------------------------------------------------------------------


def _j(x):
    """JSON-safe view of a fetched probe leaf: finite floats stay numeric,
    nonfinite become strings (strict-JSON consumers must not choke on a NaN
    the recorder exists to report); small arrays become lists, large ones a
    summary."""
    if isinstance(x, Mapping):
        return {k: _j(v) for k, v in x.items()}
    arr = np.asarray(x)
    if arr.ndim == 0:
        v = arr.item()
        if isinstance(v, float) and not math.isfinite(v):
            return str(v)
        return v
    if arr.size <= 16:
        return [_j(v) for v in arr.reshape(-1)]
    finite = arr[np.isfinite(arr)] if np.issubdtype(arr.dtype, np.floating) else arr
    return {
        "shape": list(arr.shape),
        "min": _j(finite.min()) if finite.size else None,
        "max": _j(finite.max()) if finite.size else None,
        "last": _j(arr.reshape(-1)[-1]),
    }


class Watchdog:
    """Divergence trip policy over fetched losses and probes.

    Trips (returns the reason string) on:

    - nonfinite loss — checked whenever the loop hands one over. Per-step
      dispatch fetches the loss every step, so the check runs every step
      there; the scan-fused loops fetch ONLY on the probe cadence (the
      zero-steady-state-transfer contract, ``FlightRecorder.should_fetch``)
      and additionally feed the epoch-aggregate loss sum through
      :meth:`FlightRecorder.on_epoch_loss` — with ``probe_every=0`` the
      aggregate check is the armed path (NaN propagates through the sum), at
      epoch granularity and zero extra transfers;
    - a nonzero fused ``nonfinite`` probe count (NaN/Inf in grads/updates);
    - ``grad_norm`` above ``grad_norm_max`` (0 disables the ceiling — the
      NaN/Inf trips stay armed).

    Array-valued losses/probes (scan chunks stack (K,), the nat sweep stacks
    members (E,)) are checked elementwise: ANY bad step/member trips.
    """

    def __init__(self, grad_norm_max: float = 0.0):
        self.grad_norm_max = float(grad_norm_max)

    def check(self, loss=None, probe: dict | None = None) -> str | None:
        if loss is not None:
            larr = np.asarray(loss, dtype=np.float64)
            if not np.isfinite(larr).all():
                return f"nonfinite loss ({_j(larr)})"
        if probe is not None:
            nf = int(np.sum(np.asarray(probe.get("nonfinite", 0))))
            if nf > 0:
                return f"{nf} nonfinite gradient/update element(s)"
            gn = np.asarray(probe.get("grad_norm", 0.0), dtype=np.float64)
            if not np.isfinite(gn).all():
                return f"nonfinite grad norm ({_j(gn)})"
            if self.grad_norm_max > 0 and float(np.max(gn)) > self.grad_norm_max:
                return (
                    f"grad norm {float(np.max(gn)):g} exceeds ceiling "
                    f"{self.grad_norm_max:g}"
                )
        return None


class FlightRecorder:
    """Per-trainer numerics recorder + watchdog harness.

    One instance per train loop (``FlightRecorder("qsc_train", cfg,
    workdir=...)``); the loop calls :meth:`note_good` once on its initial
    params and :meth:`on_step` once per host-visible step with the step's
    metrics dict (device leaves — the probe is fetched here, on the logging
    cadence, never per step). ``numerics`` records go to the explicit sink or
    the process-global telemetry sink, exactly like :class:`StepClock`.

    Disabled cleanly: ``train.probe_every == 0`` stops the records,
    ``train.watchdog == False`` stops the trips; with both off, ``on_step``
    is a counter increment.
    """

    def __init__(self, name: str, cfg, workdir: str | None = None, sink=None):
        self.name = name
        self.cfg = cfg
        self.workdir = workdir
        self._sink = sink
        self.probe_every = int(cfg.train.probe_every)
        self.watchdog = (
            Watchdog(grad_norm_max=cfg.train.watchdog_grad_norm_max)
            if cfg.train.watchdog
            else None
        )
        self.dump_root = os.path.join(cfg.eval.results_dir, cfg.name, "flightrec")
        self._n = 0
        self._history: deque[dict] = deque(maxlen=HISTORY_TAIL)
        self._last_good: tuple[int, Any] | None = None  # (step, params copy)

    @property
    def enabled(self) -> bool:
        return self.probe_every > 0 or self.watchdog is not None

    def _target(self):
        return self._sink if self._sink is not None else _spans.get_sink()

    def should_fetch(self) -> bool:
        """Whether the NEXT :meth:`on_step` call lands on the logging cadence
        (first step of the run, or a ``probe_every`` multiple).

        The scan-fused train loops use this to decide whether to pay the
        device->host loss sync for a dispatch at all: off-cadence dispatches
        enqueue back-to-back with ZERO host transfers (the dispatch-gap
        elimination contract, pinned in ``tests/test_train.py``), and the
        watchdog's loss/probe checks ride the same cadence — ``probe_every=0``
        fetches nothing in steady state. Mirrors :meth:`on_step`'s internal
        cadence exactly; a drift between the two would either fetch losses
        nobody logs or log records with no loss.
        """
        if self.probe_every <= 0:
            return False
        nxt = self._n + 1
        return nxt == 1 or nxt % self.probe_every == 0

    def note_good(self, params) -> None:
        """Snapshot known-good params (a COPY — the train steps donate their
        state, so a kept reference would alias invalidated buffers). Trainers
        call this once before the loop: the init/restored params are good by
        construction, so even a first-step divergence has a restore point."""
        if self.watchdog is None:
            return
        import jax
        import jax.numpy as jnp

        self._last_good = (self._n, jax.tree.map(jnp.copy, params))

    def on_step(
        self,
        epoch: int,
        metrics: Mapping | None,
        loss=None,
        params=None,
        batch_info: dict | None = None,
        rng=None,
    ) -> None:
        """One host-visible step: log on cadence, feed the watchdog, raise
        :class:`DivergenceError` (after dumping) on a trip.

        ``metrics`` is the step's metric dict with device leaves (its
        ``probe`` entry is fetched — one transfer — only on logging steps);
        ``loss`` is the already-transferred host loss (scalar or the scan
        chunk / member vector); ``params``/``batch_info``/``rng`` feed the
        last-good snapshot and the post-mortem bundle.
        """
        has_checkify = isinstance(metrics, Mapping) and "checkify_err" in metrics
        if not self.enabled and not has_checkify:
            return
        import jax

        self._n += 1
        if has_checkify:
            # runtime sanitizer (train.checkify): the step's checkify error
            # rides the metrics dict; fetching it is the mode's one
            # per-step host sync. A tripped check is a divergence with an
            # op-precise reason — same dump, same typed error as the
            # watchdog's aggregate NaN trips.
            from qdml_tpu.telemetry.sanitizer import error_message

            msg = error_message(metrics["checkify_err"])
            if msg is not None:
                reason = f"checkify: {msg.splitlines()[0]}"
                dump_dir = self.dump(
                    reason, epoch, batch_info=batch_info, rng=rng, loss=loss,
                    metrics=metrics,
                )
                raise DivergenceError(
                    f"{self.name} tripped a checkify check at step {self._n} "
                    f"(epoch {epoch}): {reason}"
                    + (f" — flight-recorder dump: {dump_dir}" if dump_dir else ""),
                    dump_dir,
                    reason,
                )
        probe_host = None
        probe = metrics.get("probe") if isinstance(metrics, Mapping) else None
        if (
            probe is not None
            and self.probe_every > 0
            and (self._n == 1 or self._n % self.probe_every == 0)
        ):
            probe_host = jax.device_get(probe)  # the one extra transfer
            rec = {
                "step": self._n,
                "epoch": int(epoch),
                "loss": _j(loss) if loss is not None else None,
                **{k: _j(v) for k, v in probe_host.items()},
            }
            self._history.append(rec)
            target = self._target()
            if target is not None and getattr(target, "active", False):
                target.emit("numerics", name=self.name, **rec)
        if self.watchdog is None:
            return
        reason = self.watchdog.check(loss=loss, probe=probe_host)
        if reason is None:
            # retain last-good on a cadence, never per step (a tree copy per
            # step would double param traffic for pure bookkeeping): the
            # probe cadence when probes log, a fixed fallback cadence when
            # probes are compiled out and only the loss checks qualify steps
            snap = probe_host is not None or (
                self.probe_every <= 0 and self._n % LAST_GOOD_FALLBACK_EVERY == 0
            )
            if snap and params is not None:
                import jax.numpy as jnp

                self._last_good = (self._n, jax.tree.map(jnp.copy, params))
            return
        dump_dir = self.dump(reason, epoch, batch_info=batch_info, rng=rng, loss=loss,
                             probe_host=probe_host, metrics=metrics)
        raise DivergenceError(
            f"{self.name} diverged at step {self._n} (epoch {epoch}): {reason}"
            + (f" — flight-recorder dump: {dump_dir}" if dump_dir else ""),
            dump_dir,
            reason,
        )

    def on_epoch_loss(self, epoch: int, loss) -> None:
        """Watchdog check over an epoch's ALREADY-FETCHED loss aggregate.

        The scan-fused loops accumulate losses on device and fetch once per
        epoch; NaN/Inf propagates through the sum, so this one check catches
        any divergence the cadence-gated per-dispatch checks skipped —
        including the ``probe_every=0`` mode, where NO in-loop fetch happens
        and this is the only armed loss check. Costs nothing: the epoch
        fetch already happened for the history. Trips exactly like
        :meth:`on_step` (dump + typed :class:`DivergenceError`)."""
        if self.watchdog is None or loss is None:
            return
        reason = self.watchdog.check(loss=loss)
        if reason is None:
            return
        reason = f"epoch-aggregate {reason}"
        dump_dir = self.dump(reason, epoch, loss=loss)
        raise DivergenceError(
            f"{self.name} diverged during epoch {epoch} (aggregate over the "
            f"epoch's fused dispatches): {reason}"
            + (f" — flight-recorder dump: {dump_dir}" if dump_dir else ""),
            dump_dir,
            reason,
        )

    # -- post-mortem --------------------------------------------------------

    def dump(
        self,
        reason: str,
        epoch: int,
        batch_info: dict | None = None,
        rng=None,
        loss=None,
        probe_host: dict | None = None,
        metrics: Mapping | None = None,
    ) -> str | None:
        """Write the post-mortem bundle; returns its directory. Every process
        joins the orbax ``last_good`` save (it is a multi-host COLLECTIVE —
        a primary-only save would leave the primary waiting on peers that
        already raised), while the plain-JSON bundle and telemetry record are
        primary-only like every other shared write. Best-effort by design: a
        failing dump must not mask the DivergenceError itself."""
        dump_dir = os.path.join(self.dump_root, f"{self.name}-step{self._n:06d}")
        try:
            os.makedirs(dump_dir, exist_ok=True)
            if probe_host is None and isinstance(metrics, Mapping) and "probe" in metrics:
                try:
                    import jax

                    probe_host = jax.device_get(metrics["probe"])
                except Exception:  # lint: disable=broad-except(post-mortem fetch of possibly donated/poisoned buffers — the bundle ships without the probe)
                    probe_host = None
            last_good_meta = None
            if self._last_good is not None:
                from qdml_tpu.train.checkpoint import save_checkpoint

                good_step, good_params = self._last_good
                save_checkpoint(
                    dump_dir,
                    "last_good",
                    {"params": good_params},
                    {"step": good_step, "name": self.cfg.name, "loop": self.name},
                )
                last_good_meta = {"step": good_step, "checkpoint": "last_good"}
            if not is_primary():
                return dump_dir
            from qdml_tpu.telemetry.manifest import config_hash

            bundle = {
                "kind": "flightrec_bundle",
                "ts": round(time.time(), 3),
                "name": self.name,
                "run": self.cfg.name,
                "config_hash": config_hash(self.cfg),
                "reason": reason,
                "step": self._n,
                "epoch": int(epoch),
                "loss": _j(loss) if loss is not None else None,
                "batch_info": _j(batch_info) if batch_info else None,
                "rng_key": _j(np.asarray(rng)) if rng is not None else None,
                "probe": _j(probe_host) if probe_host else None,
                "probe_history": list(self._history),
                "last_good": last_good_meta,
                "workdir": self.workdir,
            }
            with open(os.path.join(dump_dir, "bundle.json"), "w") as fh:
                json.dump(bundle, fh, indent=2)
            target = self._target()
            if target is not None and getattr(target, "active", False):
                target.emit(
                    "flightrec_dump",
                    name=self.name,
                    reason=reason,
                    step=self._n,
                    epoch=int(epoch),
                    dump_dir=dump_dir,
                )
            return dump_dir
        except Exception as e:  # lint: disable=broad-except(a failing dump must not mask the DivergenceError about to be raised)
            print(f"[flightrec] dump failed: {type(e).__name__}: {e}", flush=True)
            return None
