"""Spawn and supervise REAL backend serve processes (dryrun/test harness).

The fleet dryrun's backends are genuine ``qdml-tpu serve`` processes — own
interpreter, own JAX runtime, own warmup, own compile-cache counters — not
in-process stand-ins: the router tier's whole claim is that the socket
layer spans PROCESSES, so the proof must too. :func:`spawn_backend` launches
one with ``--serve.port=0`` (or a fixed port the chaos respawn path reuses),
reads the post-bind startup banner (serve/server.run_server prints it AFTER
the socket is bound, with the ACTUAL port and the stable ``host_id``), and
returns a handle that can kill (SIGKILL — the backend-loss chaos class),
stall (SIGSTOP/SIGCONT — the hung-host class) and reap the process.

Real multi-host deployments run one ``qdml-tpu serve`` per host under their
own supervisor and hand the router ``fleet.backends``; this module exists so
the committed dryrun and the tests exercise the identical process topology
on one machine.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field


@dataclass
class BackendProc:
    """One spawned ``qdml-tpu serve`` process + its learned identity."""

    proc: subprocess.Popen
    host: str
    port: int
    host_id: str
    banner: dict
    log_path: str | None = None
    _stopped: bool = field(default=False, repr=False)

    @property
    def addr(self) -> tuple[str, int]:
        return (self.host, self.port)

    def alive(self) -> bool:
        return self.proc.poll() is None

    def kill(self) -> None:
        """SIGKILL — the chaos backend-loss class (no drain, no goodbye)."""
        self._stopped = True
        if self.alive():
            self.proc.kill()
        self.proc.wait(timeout=30.0)

    def stall(self) -> None:
        """SIGSTOP — the hung-host class: the process holds its sockets but
        answers nothing; the router must eject it on timeouts."""
        os.kill(self.proc.pid, signal.SIGSTOP)

    def resume(self) -> None:
        os.kill(self.proc.pid, signal.SIGCONT)

    def terminate(self, timeout_s: float = 30.0) -> None:
        """Polite stop (SIGINT first — run_server's KeyboardInterrupt path
        flushes counters — then SIGKILL)."""
        self._stopped = True
        if not self.alive():
            self.proc.wait(timeout=timeout_s)
            return
        self.proc.send_signal(signal.SIGINT)
        try:
            self.proc.wait(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=timeout_s)


def spawn_backend(
    overrides: list[str],
    port: int = 0,
    host: str = "127.0.0.1",
    env: dict | None = None,
    log_path: str | None = None,
    timeout_s: float = 600.0,
    python: str | None = None,
) -> BackendProc:
    """Launch ``python -m qdml_tpu.cli serve`` with ``overrides`` (dotted
    config flags, ``--train.workdir=...`` included so the backend restores
    the harness's checkpoints) and block until its post-bind banner names
    the actual port. Stdout goes to ``log_path`` after the banner (the
    banner line itself is parsed here); stderr follows stdout."""
    cmd = [
        python or sys.executable, "-m", "qdml_tpu.cli", "serve",
        f"--serve.host={host}", f"--serve.port={port}", *overrides,
    ]
    child_env = dict(os.environ)
    child_env.setdefault("JAX_PLATFORMS", "cpu")
    # the child resolves `qdml_tpu` from THIS package's root, not from the
    # caller's cwd (a harness running from a scratch directory would
    # otherwise spawn backends that die on import)
    import qdml_tpu

    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(qdml_tpu.__file__)))
    child_env["PYTHONPATH"] = pkg_root + (
        os.pathsep + child_env["PYTHONPATH"] if child_env.get("PYTHONPATH") else ""
    )
    if env:
        child_env.update(env)
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=child_env, text=True, bufsize=1,
    )
    # the banner wait must enforce timeout_s against a child that hangs
    # SILENTLY (a wedged warmup prints nothing): a blocking readline would
    # only re-check the deadline between lines, so a reader thread feeds a
    # queue and the deadline governs the queue waits
    import queue as _queue
    import threading as _threading

    out_q: _queue.Queue = _queue.Queue()

    def _pump():
        try:
            for pumped in proc.stdout:
                out_q.put(pumped)
        except ValueError:
            pass  # stdout closed at reap
        out_q.put(None)  # EOF sentinel

    _threading.Thread(target=_pump, daemon=True, name="backend-banner-pump").start()
    deadline = time.monotonic() + timeout_s
    lines: list[str] = []
    banner = None
    while banner is None:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            proc.kill()
            raise TimeoutError(
                f"backend produced no startup banner within {timeout_s}s:\n"
                + "".join(lines[-30:])
            )
        try:
            line = out_q.get(timeout=min(remaining, 1.0))
        except _queue.Empty:
            continue
        if line is None:
            proc.wait(timeout=30.0)
            raise RuntimeError(
                "backend exited before announcing "
                f"(rc={proc.returncode}):\n" + "".join(lines[-30:])
            )
        lines.append(line)
        if '"serving"' in line:
            try:
                banner = json.loads(line)
            except json.JSONDecodeError:
                continue  # a log line that merely mentions the key
    bound = int(banner["serving"].rsplit(":", 1)[1])
    handle = BackendProc(
        proc=proc, host=host, port=bound,
        host_id=str(banner.get("host_id") or f"{host}:{bound}"),
        banner=banner, log_path=log_path,
    )
    # keep draining the pump's queue on a side thread so the child never
    # blocks on a full pipe (warmup cost tables and telemetry echoes are
    # chatty) — the pump thread owns proc.stdout, this one owns the queue
    def _drain():
        sink = open(log_path, "a") if log_path else None
        try:
            while True:
                out_line = out_q.get()
                if out_line is None:
                    break  # EOF: the pump saw stdout close
                if sink is not None:
                    sink.write(out_line)
                    sink.flush()
        finally:
            if sink is not None:
                sink.close()

    _threading.Thread(target=_drain, daemon=True, name=f"backend-log-{bound}").start()
    return handle
