"""Fleet router core: per-backend tables, ejection, balancing, verb fan-out.

One :class:`FleetRouter` fronts N backend ``qdml-tpu serve`` processes
("hosts"). It speaks NOTHING new on the wire: every forward is one
:class:`~qdml_tpu.serve.client.ServeClient` exchange carrying the full
retry/dedup/deadline contract (docs/RESILIENCE.md), and every verb the
router serves is the backend verb fanned out or aggregated:

- **inference** — pick a backend (consistent hashing on the request id, or
  least-queue-depth over the health poll's cached view), forward, and fail
  over to the next live host on transport failure. Retries of one id are
  deduped FLEET-WIDE by the router (:class:`RouterDedup`): a retried id
  re-attaches to the in-flight or just-served forward even when the original
  backend has since been ejected — the server-side dedup window only holds
  within one host.
- **ejection / re-admission** — per-backend :class:`BackendState` runs the
  breaker state machine (serve/breaker.py semantics: closed → open on
  ``eject_failures`` consecutive transport failures, open → half-open after
  ``eject_s``, half-open closes after ``readmit_probes`` successful probes
  and re-opens on one failure). The health poll thread drives re-admission
  even when no traffic is flowing.
- **swap** — fans to ALL live backends concurrently with all-or-report-
  partial semantics: every live backend's outcome is reported per host_id;
  ejected hosts are listed as skipped (they re-resolve the newest
  checkpoints at re-admission or restart — docs/FLEET.md); ``ok`` is true
  iff every LIVE backend swapped.
- **scale** — fleet-level replica target: the router differences the target
  against the polled per-host replica counts and grows the deepest-queue
  host / shrinks the shallowest-queue host one replica at a time (the
  autoscaler's "which host" decision, docs/CONTROL.md).
- **membership** — elastic: :meth:`FleetRouter.add_backend` splices a
  WARMED host into the consistent-hash ring (the lifecycle manager in
  fleet/lifecycle.py verifies warm=true + zero request-path compiles
  before ever calling it), and :meth:`FleetRouter.retire_backend` is
  drain-then-remove: the victim's vnodes leave the ring first (fresh
  requests stop hashing to it), in-flight forwards complete, then the
  host leaves the table. Ring points are keyed on the STABLE backend
  address, so a resize moves ONLY the added/removed host's arcs (~1/N of
  the id space) and every surviving host keeps its keys — the property
  that lets server-side dedup windows and in-flight retries survive a
  membership change (pinned in tests/test_fleet_elastic.py).
- **metrics / health** — aggregation: counters (completed, sheds, SLO
  n/met, per-scenario prediction counts and confidence SUMS, dispatch row
  ledgers, compile-cache counters) SUM exactly across hosts — the fleet
  controller windows the aggregate by differencing polls exactly as it does
  one host's. Wire latency is the router's own per-backend histograms merged
  via the exact ``Histogram.merge``; each backend's own latency summary
  rides in the per-backend rows (summaries cannot merge exactly — the raw
  samples live in the backend process).

Thread model: the asyncio front-end (fleet/frontend.py) runs
:meth:`FleetRouter.request` on executor threads; each backend keeps a small
borrow/return pool of ``ServeClient`` connections (one per concurrent
in-flight exchange, the client's documented contract). The ejection state
machine and the router dedup table are the cross-thread state — both hold
their locks for every touch (graftlint LOCK_MAP, analysis/project.py).
"""

from __future__ import annotations

import hashlib
import threading

from qdml_tpu.utils import lockdep
import time
from bisect import bisect_right
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

from qdml_tpu.serve.breaker import CLOSED, HALF_OPEN, OPEN
from qdml_tpu.serve.client import ServeClient, ServeClientError
from qdml_tpu.telemetry import Histogram
from qdml_tpu.telemetry.events import ensure_bus
from qdml_tpu.telemetry.events import publish as publish_event
from qdml_tpu.telemetry.spans import get_sink
from qdml_tpu.telemetry.tracing import trace_sampled

# transport-level failures that count against a backend's ejection state;
# a typed ok=false REPLY (bad_request, shed) is a healthy backend answering
_FORWARD_ERRORS = (ServeClientError, ConnectionError, TimeoutError, OSError)

_RING_VNODES = 64  # virtual nodes per backend on the consistent-hash ring


def _emit_event(name: str, **fields) -> None:
    """Structured fleet event (backend_ejected / backend_readmitted /
    fleet_lifecycle / router_swap) into the run's telemetry stream, if one
    is active — and onto the process-global event spine always, so the
    front door's ``{"op": "events"}`` tail sees the router tier's own
    events alongside the per-backend ones it aggregates."""
    sink = get_sink()
    if sink is not None and getattr(sink, "active", False):
        sink.emit("counters", name=name, **fields)
    publish_event(name, tier="router", **fields)


def _hash_point(key: str) -> int:
    return int.from_bytes(hashlib.md5(key.encode()).digest()[:8], "big")


def _ring_points(backends: list) -> tuple[list[int], list[int]]:
    """(sorted ring points, parallel backend-index list) over the non-
    draining members. Points are keyed on the stable address, so a host
    contributes the SAME points in every rebuild — membership changes move
    only the changed host's arcs (the bounded-key-movement property)."""
    points = sorted(
        (_hash_point(f"{b.addr}#{v}"), i)
        for i, b in enumerate(backends)
        if not b.draining
        for v in range(_RING_VNODES)
    )
    return [p for p, _ in points], [i for _, i in points]


def parse_backends(spec: str, default: tuple[str, int] | None = None) -> list[tuple[str, int]]:
    """``"host:port,host:port"`` -> address list (``fleet.backends``).
    Empty spec falls back to ``default`` (the single local serve endpoint)."""
    addrs: list[tuple[str, int]] = []
    for part in (p.strip() for p in spec.split(",") if p.strip()):
        host, _, port = part.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"bad backend endpoint {part!r}; expected host:port")
        addrs.append((host, int(port)))
    if not addrs:
        if default is None:
            raise ValueError("fleet.backends is empty and no default endpoint given")
        addrs = [default]
    return addrs


class BackendState:
    """Per-backend ejection state machine — the serve/breaker.py shape
    (closed/open/half-open, hysteresis via probes) keyed on transport
    failures instead of queue depth: ``eject_failures`` CONSECUTIVE failures
    open (eject) the backend, ``eject_s`` later it half-opens, and
    ``readmit_probes`` consecutive successful probes close (re-admit) it;
    one half-open failure re-opens. Clock injected for deterministic tests."""

    def __init__(
        self,
        eject_failures: int = 3,
        eject_s: float = 1.0,
        readmit_probes: int = 2,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.eject_failures = max(1, int(eject_failures))
        self.eject_s = float(eject_s)
        self.readmit_probes = max(1, int(readmit_probes))
        self.clock = clock
        self._lock = lockdep.Lock("BackendState._lock")
        self._state = CLOSED
        self._fails = 0        # consecutive failures while closed
        self._oks = 0          # consecutive half-open probe successes
        self._opened_at = 0.0
        self._ejections = 0
        self._readmissions = 0

    def allow(self, now: float | None = None) -> bool:
        """May this backend receive a request/probe now? Runs the open ->
        half-open transition (time-based), so polling allow() alone is
        enough to start re-admission probing."""
        now = self.clock() if now is None else now
        with self._lock:
            if self._state == OPEN:
                if now - self._opened_at < self.eject_s:
                    return False
                self._state = HALF_OPEN
                self._oks = 0
            return True  # closed and half-open both admit (probes bounded by caller traffic)

    def record_success(self) -> bool:
        """One successful exchange/probe; True iff this one RE-ADMITTED the
        backend (half-open -> closed edge)."""
        with self._lock:
            self._fails = 0
            if self._state == HALF_OPEN:
                self._oks += 1
                if self._oks >= self.readmit_probes:
                    self._state = CLOSED
                    self._readmissions += 1
                    return True
            return False

    def record_failure(self, now: float | None = None) -> bool:
        """One transport failure; True iff this one EJECTED the backend
        (closed/half-open -> open edge)."""
        now = self.clock() if now is None else now
        with self._lock:
            if self._state == HALF_OPEN:
                self._state = OPEN
                self._opened_at = now
                self._ejections += 1
                return True
            if self._state == CLOSED:
                self._fails += 1
                if self._fails >= self.eject_failures:
                    self._state = OPEN
                    self._opened_at = now
                    self._ejections += 1
                    return True
            else:  # already open: refresh the ejection clock
                self._opened_at = now
            return False

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def live(self) -> bool:
        """Closed or half-open — the backend may receive traffic."""
        with self._lock:
            return self._state != OPEN

    def summary(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._fails,
                "ejections": self._ejections,
                "readmissions": self._readmissions,
            }


class Backend:
    """One backend host: address, learned identity, ejection state, a small
    borrow/return pool of :class:`ServeClient` connections, the health
    poll's cached facts, and the router-side wire-latency histogram."""

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        retries: int = 1,
        eject_failures: int = 3,
        eject_s: float = 1.0,
        readmit_probes: int = 2,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.host = host
        self.port = int(port)
        self.addr = f"{host}:{port}"
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self._seed = int(seed)
        self.state = BackendState(
            eject_failures=eject_failures, eject_s=eject_s,
            readmit_probes=readmit_probes, clock=clock,
        )
        # identity learned from the first health reply (serve stamps host_id
        # + listen into every health/metrics reply); the address stands in
        # until the backend has answered once
        self.host_id: str = self.addr
        self.listen: str | None = None
        # draining flag (docs/FLEET.md "elastic fleet"): set by the router's
        # retirement path AFTER the host's vnodes leave the ring — readers
        # (poll rows, balancing, fan-outs) see it as a typed "draining"
        # state; plain bool, replaced atomically, never mutated in place
        self.draining: bool = False
        # health-poll cache (single-writer poll thread, newest-wins reads)
        self.queue_depth: int = 0
        self.replicas: int = 0
        self.swap_epoch: int = 0
        # restart-visibility epoch forwarded from the backend's health reply
        # (docs/TELEMETRY.md "monitoring"): a monitor behind the router sees
        # per-backend restarts without polling each host itself
        self.uptime_s: float | None = None
        self.start_seq: int | None = None
        self.last_poll_ts: float = 0.0
        self.poll_ok: bool = False
        # router-side wire metrics, guarded by _mlock (request threads add
        # concurrently; Histogram is a plain list underneath)
        self._mlock = lockdep.Lock("Backend._mlock")
        self._latency = Histogram()
        self._forwarded = 0
        self._failed = 0
        # forwards currently on the wire to this host — the retirement
        # drain's "in-flight reaches zero" condition reads it
        self._inflight = 0
        # connection pool (LIFO: reuse the warmest socket first)
        self._clients: list[ServeClient] = []
        self._clients_lock = lockdep.Lock("Backend._clients_lock")
        self._made = 0

    # -- connection pool ----------------------------------------------------

    def _borrow(self) -> ServeClient:
        with self._clients_lock:
            if self._clients:
                return self._clients.pop()
            self._made += 1
            n = self._made
        return ServeClient(
            self.host, self.port, timeout_s=self.timeout_s,
            retries=self.retries, seed=self._seed * 997 + n,
        )

    def _restore(self, client: ServeClient) -> None:
        with self._clients_lock:
            self._clients.append(client)

    def close(self) -> None:
        with self._clients_lock:
            clients, self._clients = self._clients, []
        for c in clients:
            c.close_connection()

    # -- exchanges ----------------------------------------------------------

    def call(self, msg: dict, timeout_s: float | None = None,
             idempotent: bool = True) -> dict:
        """One request/reply exchange through the pool, with the router-side
        wire-latency and forward accounting. Transport failures propagate
        (the router's failover loop owns record_failure/record_success)."""
        client = self._borrow()
        with self._mlock:
            self._inflight += 1
        t0 = time.perf_counter()
        try:
            rep = client.call(
                msg, timeout_s=timeout_s,
                deadline_ms=msg.get("deadline_ms"), idempotent=idempotent,
            )
        except BaseException:
            with self._mlock:
                self._failed += 1
                self._inflight -= 1
            self._restore(client)
            raise
        with self._mlock:
            self._forwarded += 1
            self._inflight -= 1
            self._latency.add(time.perf_counter() - t0)
        self._restore(client)
        return rep

    def inflight(self) -> int:
        """Forwards currently on the wire to this host (the drain gate)."""
        with self._mlock:
            return self._inflight

    def wire_metrics(self) -> tuple[Histogram, int, int]:
        """(latency histogram copy, forwarded, failed) under the lock — the
        aggregation's exact-merge input."""
        with self._mlock:
            h = Histogram()
            h.merge(self._latency)
            return h, self._forwarded, self._failed

    def poll_row(self) -> dict:
        """The cheap per-backend health row (no backend round-trip — the
        poll thread's cached view)."""
        age = None if not self.last_poll_ts else round(
            time.monotonic() - self.last_poll_ts, 4
        )
        row = {
            "host_id": self.host_id,
            "addr": self.addr,
            "listen": self.listen,
            "queue_depth": self.queue_depth,
            "replicas": self.replicas,
            "swap_epoch": self.swap_epoch,
            "uptime_s": self.uptime_s,
            "start_seq": self.start_seq,
            "poll_ok": self.poll_ok,
            "poll_age_s": age,
            **self.state.summary(),
        }
        if self.draining:
            # the typed retirement state (docs/FLEET.md "elastic fleet"):
            # off the ring, finishing in-flight work — distinct from an
            # ejection (which is involuntary and re-admits)
            row["state"] = "draining"
        return row


class RouterDedup:
    """Fleet-wide idempotent-id dedup: one entry per in-flight (or recently
    SERVED) request id, so a retried id re-attaches to the original forward
    — across router failover, not just within one backend's server-side
    window (the server's DedupCache discipline, lifted one tier). Entries
    insert in clock order, so TTL eviction pops from the head (amortized
    O(1), same argument as serve/server.DedupCache). Only ok replies stay
    pinned: a failed/shed forward is forgotten the moment it completes, so
    the client's next retry re-dispatches."""

    def __init__(self, ttl_s: float, clock: Callable[[], float] = time.monotonic):
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._lock = lockdep.Lock("RouterDedup._lock")
        self._entries: dict = {}  # rid -> {"ev": Event, "rep": dict|None, "ts": float}
        self.hits = 0

    def begin(self, rid) -> tuple[dict, bool]:
        """(entry, fresh): fresh=True means this caller owns the forward and
        must call :meth:`finish`; fresh=False means wait on ``entry["ev"]``
        and read ``entry["rep"]`` (the retry re-attachment path)."""
        now = self.clock()
        with self._lock:
            while self._entries:
                head = next(iter(self._entries))
                ent = self._entries[head]
                if now - ent["ts"] < self.ttl_s or not ent["ev"].is_set():
                    break  # fresh, or still in flight (never evict in-flight)
                del self._entries[head]
            ent = self._entries.get(rid)
            if ent is not None:
                self.hits += 1
                return ent, False
            ent = {"ev": threading.Event(), "rep": None, "ts": now}
            self._entries[rid] = ent
            return ent, True

    def finish(self, rid, entry: dict, rep: dict | None) -> None:
        """Resolve the entry for every waiter; pin it only when ``rep`` is a
        served ok reply."""
        entry["rep"] = rep
        entry["ev"].set()
        pin = isinstance(rep, dict) and rep.get("ok") is True
        if not pin:
            with self._lock:
                cur = self._entries.get(rid)
                if cur is entry:
                    del self._entries[rid]


def _trace_prepend_router(rep: dict, rid, pick_s: float | None,
                          attempts: list[dict]) -> dict:
    """Compose the reply's wire-format trace: router spans (balancing pick,
    one ``wire`` span per attempt — failed attempts included, so failover
    retries read as separate spans) PREPENDED to the backend's own phases.
    All router durations are router-clock measurements of router-owned
    intervals; the backend's phase durations pass through untouched. The
    successful attempt's wire span is NET — its exchange duration minus the
    backend's own reported serve total — so the phase list PARTITIONS the
    request's time instead of counting the backend twice; that subtraction
    is duration-minus-duration (clock-skew-free — what is never done is
    differencing the two hosts' timestamps). Failed attempts have no server
    total: their wire span is the full measured attempt."""
    if not isinstance(rep, dict):
        return rep
    rep = dict(rep)
    backend_tr = rep.get("trace") if isinstance(rep.get("trace"), dict) else {}
    phases: list = []
    if pick_s is not None:
        phases.append(["pick", round(pick_s * 1e3, 3)])
    phases += [["wire", a["wire_ms"]] for a in attempts]
    phases += list(backend_tr.get("phases") or [])
    detail = dict(backend_tr.get("detail") or {})
    detail["router"] = {
        "attempts": attempts,
        "failover_retries": sum(1 for a in attempts if not a.get("ok")),
    }
    tr: dict = {"id": rid, "phases": phases, "detail": detail}
    if isinstance(backend_tr.get("total_ms"), (int, float)):
        # the backend's enqueue->resolve total (ITS clock): kept verbatim —
        # the client-side reconciliation compares its OWN wall clock against
        # the phase-duration sum, never against this foreign timestamp base
        tr["total_ms"] = backend_tr["total_ms"]
    rep["trace"] = tr
    return rep


def _trace_dedup_reattach(rep: dict, rid, wait_s: float) -> dict:
    """Trace for a retry that re-attached to the original in-flight forward:
    one ``dedup_wait`` span (this retry dispatched NOTHING) prepended to the
    original reply's trace, plus the detail flag the dryrun's kill-spanning
    dedup pin reads."""
    if not isinstance(rep, dict):
        return rep
    rep = dict(rep)
    orig = rep.get("trace") if isinstance(rep.get("trace"), dict) else {}
    detail = dict(orig.get("detail") or {})
    detail["dedup_reattached"] = True
    tr = {
        "id": rid,
        "phases": [["dedup_wait", round(wait_s * 1e3, 3)]]
        + list(orig.get("phases") or []),
        "detail": detail,
    }
    if isinstance(orig.get("total_ms"), (int, float)):
        tr["total_ms"] = orig["total_ms"]
    rep["trace"] = tr
    return rep


class FleetRouter:
    """The front-door fan-out over per-host replica pools (docs/FLEET.md)."""

    def __init__(
        self,
        backends: list[tuple[str, int]],
        balance: str = "hash",
        timeout_s: float = 10.0,
        retries: int = 1,
        eject_failures: int = 3,
        eject_s: float = 1.0,
        readmit_probes: int = 2,
        poll_interval_s: float = 0.5,
        failover: int = 2,
        dedup_ttl_s: float = 30.0,
        seed: int = 0,
        clock: Callable[[], float] = time.monotonic,
        trace_sample: float = 0.0,
    ):
        if balance not in ("hash", "least_queue"):
            raise ValueError(f"fleet.balance must be hash|least_queue, got {balance!r}")
        if not backends:
            raise ValueError("a fleet router needs at least one backend")
        self.balance = balance
        self.failover = max(0, int(failover))
        self.poll_interval_s = float(poll_interval_s)
        # Request-tracing sample rate (telemetry/tracing.py, same knob the
        # serve tier reads — serve.trace_sample): a sampled (or client-forced
        # "trace": true) request is forwarded with the trace bit set so the
        # backend decomposes its own latency, and the router PREPENDS its
        # tier's spans — balancing pick, one wire span PER ATTEMPT (failover
        # retries stay visible as separate spans), dedup re-attachment wait.
        # Every router span is measured on the router's own clock around its
        # own send->reply exchange; backend clocks are never read.
        self.trace_sample = float(trace_sample)
        # per-backend construction knobs, kept so an elastically ADDED host
        # gets the same contract as the boot-time set
        self._backend_opts = dict(
            timeout_s=timeout_s, retries=retries,
            eject_failures=eject_failures, eject_s=eject_s,
            readmit_probes=readmit_probes, clock=clock,
        )
        self._seed = int(seed)
        self.backends = [
            Backend(h, p, seed=seed + i, **self._backend_opts)
            for i, (h, p) in enumerate(backends)
        ]
        self._next_backend_seq = len(self.backends)
        self.dedup = RouterDedup(dedup_ttl_s) if dedup_ttl_s > 0 else None
        # a re-attached retry must outwait the WHOLE failover sweep the
        # original forward may legitimately still be walking — budgeting for
        # one backend's retries alone would time the waiter out (typed
        # router_timeout) on a request that then completes and pins
        self._dedup_wait_s = (self.failover + 1) * timeout_s * (retries + 1) + 5.0
        # consistent-hash ring: _RING_VNODES virtual points per backend,
        # keyed on the STABLE address (host_ids are learned later) — adding
        # or removing a host moves ONLY its own arcs (~1/N of the id space);
        # every surviving host's points are bit-identical across rebuilds.
        # Membership changes REPLACE ring + index + backend list together
        # under _ring_lock; the lists themselves are never mutated in place,
        # so a reader's snapshot is always internally consistent.
        self._ring_lock = lockdep.Lock("FleetRouter._ring_lock")
        self._ring, self._ring_idx = _ring_points(self.backends)
        self._failovers = 0
        self._no_backend = 0
        self._counter_lock = lockdep.Lock("FleetRouter._counter_lock")
        # traced requests' NET wire spans (exchange minus backend-reported
        # serve total; failed attempts at full duration) — raw samples live
        # HERE, so the fleet phase table's wire row has exact quantiles while
        # backend phases aggregate by exact (n, sum). Request executor
        # threads add concurrently: every touch holds _trace_lock
        # (graftlint LOCK_MAP, analysis/project.py).
        self._trace_lock = lockdep.Lock("FleetRouter._trace_lock")
        self._trace_wire = Histogram()
        self._poll_stop = threading.Event()
        self._poll_thread: threading.Thread | None = None
        # the router's own restart-visibility epoch (same contract as the
        # backends': a monitor scraping the front detects a router restart)
        self._monitor_t0 = time.monotonic()
        self._start_seq = int(time.time() * 1000)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "FleetRouter":
        """Prime the backend table with one synchronous health sweep (learn
        host_ids, mark dead hosts before the first request), then start the
        poll thread."""
        self.poll_once()
        if self._poll_thread is None:
            self._poll_stop.clear()
            self._poll_thread = threading.Thread(
                target=self._poll_loop, daemon=True, name="fleet-router-poll"
            )
            self._poll_thread.start()
        return self

    def stop(self) -> None:
        if self._poll_thread is not None:
            self._poll_stop.set()
            self._poll_thread.join(timeout=10.0)
            self._poll_thread = None
        for b in self.backends:
            b.close()

    # -- health polling (ejection + re-admission + least-queue freshness) ----

    def _poll_loop(self) -> None:
        while not self._poll_stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # lint: disable=broad-except(the poll thread is the re-admission engine — a transient poll failure must be reported and survived, not end health tracking for the whole fleet)
                _emit_event("router_poll_error", error=f"{type(e).__name__}: {e}")

    def poll_once(self) -> None:
        """One health sweep over every backend: refresh the cached queue
        depth/replica count/identity, and feed the ejection state machine —
        a dead host ejects without traffic, and an ejected host's successful
        probes re-admit it without traffic. Draining hosts stay in the sweep:
        the monitor keeps seeing their typed state until retirement."""
        for b in list(self.backends):
            self._poll_backend(b)

    def _poll_backend(self, b: Backend) -> None:
        if not b.state.allow():
            return  # still inside its eject window: no probe yet
        try:
            rep = b.call({"op": "health"}, timeout_s=min(b.timeout_s, 2.0))
            h = rep.get("health") or {}
        except _FORWARD_ERRORS as e:
            b.poll_ok = False
            if b.state.record_failure():
                _emit_event(
                    "backend_ejected", backend=b.host_id, addr=b.addr,
                    reason=f"health_poll: {type(e).__name__}",
                )
            return
        b.poll_ok = True
        b.last_poll_ts = time.monotonic()
        b.queue_depth = int(h.get("queue_depth") or 0)
        b.replicas = int(h.get("replicas") or h.get("workers") or 1)
        b.swap_epoch = int(h.get("swap_epoch") or 0)
        if h.get("uptime_s") is not None:
            b.uptime_s = float(h["uptime_s"])
        if h.get("start_seq") is not None:
            b.start_seq = int(h["start_seq"])
        if h.get("host_id"):
            b.host_id = str(h["host_id"])
        if h.get("listen"):
            b.listen = str(h["listen"])
        if b.state.record_success():
            _emit_event(
                "backend_readmitted", backend=b.host_id, addr=b.addr
            )

    # -- elastic membership (docs/FLEET.md "elastic fleet") ------------------

    def add_backend(self, host: str, port: int) -> Backend:
        """Splice one backend into the fleet: ring resize moving only the
        NEW host's arcs. The caller owns the admission criteria — the
        lifecycle manager (fleet/lifecycle.py) health-verifies warm=true and
        zero request-path compiles BEFORE calling this; the router itself
        only refuses duplicates. Emits ``backend_admitted``."""
        addr = f"{host}:{int(port)}"
        if any(b.addr == addr for b in self.backends):
            raise ValueError(f"backend {addr} is already a fleet member")
        with self._ring_lock:
            b = Backend(
                host, int(port),
                seed=self._seed + self._next_backend_seq, **self._backend_opts,
            )
            self._next_backend_seq += 1
            self.backends = self.backends + [b]
            self._ring, self._ring_idx = _ring_points(self.backends)
        # learn identity (host_id/listen) immediately so membership events
        # and per-backend rows attribute to the stable id, not the address
        self._poll_backend(b)
        _emit_event("backend_admitted", backend=b.host_id, addr=b.addr)
        return b

    def _find_backend(self, key) -> Backend:
        for b in self.backends:
            if b is key or b.host_id == key or b.addr == key:
                return b
        raise KeyError(f"no fleet member {key!r}")

    def begin_retire(self, key) -> Backend:
        """Start drain-then-retire for one member (by Backend, host_id, or
        address): its vnodes leave the ring NOW — fresh requests stop
        hashing to it, surviving hosts keep every key they had — and the
        host reports the typed ``draining`` state until removal. Refuses to
        drain the last non-draining member."""
        b = self._find_backend(key)
        with self._ring_lock:
            if b.draining:
                return b
            remaining = [
                x for x in self.backends if not x.draining and x is not b
            ]
            if not remaining:
                raise ValueError(
                    f"cannot retire {b.host_id}: it is the last fleet member"
                )
            b.draining = True
            self._ring, self._ring_idx = _ring_points(self.backends)
        _emit_event("backend_draining", backend=b.host_id, addr=b.addr)
        return b

    def finish_retire(self, key) -> dict:
        """Remove a drained member from the table and close its connection
        pool. The router's dedup entries for replies it served stay pinned
        for the TTL — a retry issued across the retirement re-attaches at
        the ROUTER and never needs the departed host. Emits
        ``backend_retired``."""
        b = self._find_backend(key)
        with self._ring_lock:
            self.backends = [x for x in self.backends if x is not b]
            self._ring, self._ring_idx = _ring_points(self.backends)
        b.close()
        _emit_event("backend_retired", backend=b.host_id, addr=b.addr)
        return {"backend": b.host_id, "addr": b.addr,
                "inflight_at_removal": b.inflight()}

    def retire_backend(
        self, key, wait_s: float = 30.0, poll_s: float = 0.05
    ) -> dict:
        """The blocking drain-then-remove composition: stop admitting (ring
        resize), wait for the host's in-flight forwards to reach zero
        (bounded by ``wait_s``), then remove it. Returns the drain record;
        ``drained`` is False iff the wait timed out with forwards still on
        the wire (the record reports how many — the dryrun gates on zero)."""
        b = self.begin_retire(key)
        deadline = time.monotonic() + float(wait_s)
        while b.inflight() > 0 and time.monotonic() < deadline:
            time.sleep(poll_s)
        stranded = b.inflight()
        rec = self.finish_retire(b)
        rec.update(drained=stranded == 0, inflight_at_removal=stranded)
        return rec

    # -- balancing ----------------------------------------------------------

    def _candidates(self, rid) -> list[Backend]:
        """Backend preference order for one request id: the hash ring walked
        from the id's point (stable id -> host affinity, so retries land
        where the server-side dedup window holds), or the live backends by
        ascending polled queue depth. Draining hosts are off the ring (and
        filtered from the queue-depth order): a retiring backend receives no
        fresh work while it finishes its in-flight forwards."""
        with self._ring_lock:
            ring, ring_idx, backends = self._ring, self._ring_idx, self.backends
        if self.balance == "least_queue":
            pool = [b for b in backends if not b.draining]
            pool.sort(key=lambda b: b.queue_depth)
            return pool
        if not ring:
            return []
        start = bisect_right(ring, _hash_point(str(rid)))
        members = len(ring) // _RING_VNODES
        order, seen = [], set()
        for k in range(len(ring)):
            i = ring_idx[(start + k) % len(ring)]
            if i not in seen:
                seen.add(i)
                order.append(i)
            if len(order) == members:
                break
        return [backends[i] for i in order]

    # -- the request path ---------------------------------------------------

    def request(self, msg: dict) -> dict:
        """Forward one inference request: fleet-wide dedup, balanced backend
        choice, bounded failover, typed give-up. Blocking (the asyncio
        front-end calls this on executor threads). Traced requests (client
        ``"trace": true`` or the router's own id-hash sample) get the trace
        bit forwarded downstream and the router's spans prepended to the
        backend's reply trace."""
        rid = msg.get("id")
        trace = bool(msg.get("trace")) or (
            rid is not None and trace_sampled(rid, self.trace_sample)
        )
        if trace and not msg.get("trace"):
            msg = {**msg, "trace": True}
        if self.dedup is not None and rid is not None:
            entry, fresh = self.dedup.begin(rid)
            if not fresh:
                # retry re-attachment: the original forward (possibly to a
                # backend that has SINCE been ejected) answers this retry —
                # exactly one dispatch fleet-wide per id
                t_wait = time.perf_counter() if trace else None
                if not entry["ev"].wait(self._dedup_wait_s):
                    return {"id": rid, "ok": False,
                            "reason": "router_timeout: original forward still in flight"}
                rep = dict(entry["rep"] or {"id": rid, "ok": False,
                                            "reason": "router_error: empty dedup entry"})
                if trace:
                    # the retry's own story: it waited on the ORIGINAL
                    # dispatch (zero new wire exchanges) — the span that
                    # makes "identical reply, one dispatch" attributable
                    rep = _trace_dedup_reattach(
                        rep, rid, time.perf_counter() - t_wait
                    )
                return rep
            try:
                rep = self._forward(msg, rid, trace=trace)
            except BaseException:
                self.dedup.finish(rid, entry, None)
                raise
            self.dedup.finish(rid, entry, rep)
            return rep
        return self._forward(msg, rid, trace=trace)

    def _forward(self, msg: dict, rid, trace: bool = False) -> dict:
        tried = 0
        last_err: Exception | None = None
        attempts: list[dict] = []
        t_pick = time.perf_counter() if trace else None
        candidates = self._candidates(rid)
        pick_s = (time.perf_counter() - t_pick) if trace else None
        for b in candidates:
            if tried > self.failover:
                break
            if not b.state.allow():
                continue
            tried += 1
            t_wire = time.perf_counter() if trace else None
            try:
                rep = b.call(msg)
            except _FORWARD_ERRORS as e:
                if trace:
                    # the failed attempt's wire span stays in the trace: a
                    # failover retry is exactly the tail event the
                    # decomposition exists to attribute
                    failed_ms = round((time.perf_counter() - t_wire) * 1e3, 3)
                    attempts.append({
                        "backend": b.host_id,
                        "wire_ms": failed_ms,
                        "exchange_ms": failed_ms,
                        "ok": False,
                        "error": type(e).__name__,
                    })
                    with self._trace_lock:
                        self._trace_wire.add(failed_ms / 1e3)
                last_err = e
                if b.state.record_failure():
                    _emit_event(
                        "backend_ejected", backend=b.host_id, addr=b.addr,
                        reason=f"forward: {type(e).__name__}",
                    )
                with self._counter_lock:
                    self._failovers += 1
                continue
            b.state.record_success()
            if trace:
                exchange_ms = round((time.perf_counter() - t_wire) * 1e3, 3)
                backend_tr = rep.get("trace") if isinstance(rep, dict) else None
                server_ms = (
                    backend_tr.get("total_ms")
                    if isinstance(backend_tr, dict)
                    and isinstance(backend_tr.get("total_ms"), (int, float))
                    else None
                )
                # NET wire: exchange minus the backend's own serve total —
                # duration-minus-duration (never a cross-host timestamp
                # difference), so the trace's phases partition the request's
                # time instead of counting the backend twice
                wire_ms = (
                    round(max(0.0, exchange_ms - server_ms), 3)
                    if server_ms is not None
                    else exchange_ms
                )
                attempt = {
                    "backend": b.host_id,
                    "wire_ms": wire_ms,
                    "exchange_ms": exchange_ms,
                    "ok": True,
                }
                if server_ms is not None:
                    attempt["server_ms"] = server_ms
                attempts.append(attempt)
                with self._trace_lock:
                    self._trace_wire.add(wire_ms / 1e3)
                rep = _trace_prepend_router(rep, rid, pick_s, attempts)
            return rep
        with self._counter_lock:
            self._no_backend += 1
        rep = {
            "id": rid, "ok": False,
            "reason": (
                "no_backend: "
                + (f"{tried} forward(s) failed "
                   f"({type(last_err).__name__}: {last_err})" if last_err
                   else "all backends ejected")
            ),
        }
        if trace and attempts:
            # a traced give-up still reports where its time went: every
            # failed attempt's wire span, no backend phases to append
            rep = _trace_prepend_router(rep, rid, pick_s, attempts)
        return rep

    # -- fan-out / aggregated verbs -----------------------------------------

    def live_backends(self) -> list[Backend]:
        """Members that may receive fresh work: not ejected, not draining
        (a retiring host still finishes in-flight forwards, but fan-outs
        and scaling must not hand it anything new)."""
        return [b for b in self.backends if b.state.live() and not b.draining]

    def swap_fanout(self, tags: dict | None = None) -> dict:
        """``{"op": "swap"}`` to every LIVE backend concurrently; all-or-
        report-partial: per-host outcomes keyed by host_id, ejected hosts
        reported as skipped, ``ok`` true iff every live backend swapped.
        Raises only when NO backend could be reached at all (the deployer's
        tick_failed path)."""
        live = self.live_backends()
        skipped = [b.host_id for b in self.backends if not b.state.live()]
        if not live:
            raise ConnectionError("swap fan-out: no live backends")
        msg: dict = {"op": "swap"}
        if tags is not None:
            msg["tags"] = tags

        def _one(b: Backend) -> tuple[str, dict]:
            try:
                # swaps are NOT idempotent-retried (serve/client.swap's
                # contract): one attempt, outcome reported
                rep = b.call(dict(msg), idempotent=False)
            except _FORWARD_ERRORS as e:
                if b.state.record_failure():
                    _emit_event(
                        "backend_ejected", backend=b.host_id, addr=b.addr,
                        reason=f"swap: {type(e).__name__}",
                    )
                return b.host_id, {"ok": False,
                                   "reason": f"unreachable: {type(e).__name__}: {e}"}
            b.state.record_success()
            out = {"ok": bool(rep.get("ok"))}
            if rep.get("ok"):
                out["swap"] = rep.get("swap")
            else:
                out["reason"] = rep.get("reason")
            return b.host_id, out

        with ThreadPoolExecutor(max_workers=max(1, len(live))) as ex:
            results = dict(ex.map(_one, live))
        ok_count = sum(1 for r in results.values() if r["ok"])
        rec = {
            "ok": ok_count == len(live),
            "partial": 0 < ok_count < len(live) or bool(skipped),
            "ok_count": ok_count,
            "fanned_to": len(live),
            "skipped": skipped,
            "backends": results,
        }
        _emit_event("router_swap", **{k: rec[k] for k in
                                      ("ok", "partial", "ok_count", "fanned_to")})
        return rec

    def scale_fleet(self, replicas: int) -> dict:
        """Fleet-level replica target: difference against the polled per-host
        counts and move one replica at a time — grow the deepest-queue live
        host, shrink the shallowest-queue one (never below 1/host). All
        arithmetic runs on a LOCAL snapshot of the per-host counts: the poll
        thread is the single writer of ``Backend.replicas``, and a health
        reply polled before a scale landing mid-loop would otherwise reset
        the count and desynchronize the absolute targets this sends."""
        self.poll_once()  # act on fresh counts, not a stale poll
        live = self.live_backends()
        if not live:
            raise ConnectionError("scale: no live backends")
        target = max(len(live), int(replicas))  # >= 1 replica per live host
        actions = []
        counts = {b: b.replicas for b in live}
        total = sum(counts.values())
        before = total

        def _set(b: Backend, n: int) -> None:
            rec = b.call({"op": "scale", "replicas": n}, idempotent=False)
            if not rec.get("ok"):
                raise RuntimeError(
                    f"scale on {b.host_id} failed: {rec.get('reason')}"
                )
            counts[b] = n
            actions.append({"backend": b.host_id, "replicas": n})

        while total < target:
            b = max(live, key=lambda x: (x.queue_depth, -counts[x]))
            _set(b, counts[b] + 1)
            total += 1
        while total > target:
            shrinkable = [b for b in live if counts[b] > 1]
            if not shrinkable:
                break
            b = min(shrinkable, key=lambda x: (x.queue_depth, counts[x]))
            _set(b, counts[b] - 1)
            total -= 1
        return {"replicas_before": before, "replicas": total, "actions": actions}

    def router_summary(self) -> dict:
        """The router's own counters + merged wire latency (exact across
        backends: the raw per-backend histograms live router-side)."""
        merged = Histogram()
        forwarded = failed = 0
        per_wire = {}
        for b in self.backends:
            h, f, x = b.wire_metrics()
            merged.merge(h)
            forwarded += f
            failed += x
            per_wire[b.host_id] = {"forwarded": f, "failed": x,
                                   "latency_ms": h.summary()}
        with self._counter_lock:
            failovers, no_backend = self._failovers, self._no_backend
        wire_summary = merged.summary()
        if wire_summary is not None:
            # (n, sum_ms) ride along so the wire phase row aggregates by the
            # same exact-sum rule as the backend phase blocks — here the raw
            # samples DO live router-side, so the quantiles are exact too
            wire_summary["sum_ms"] = round(merged.sum() * 1e3, 3)
        return {
            "balance": self.balance,
            "backends": len(self.backends),
            "backends_live": len(self.live_backends()),
            "forwarded": forwarded,
            "failed_forwards": failed,
            "failovers": failovers,
            "no_backend": no_backend,
            "dedup_hits": 0 if self.dedup is None else self.dedup.hits,
            "ejections": sum(b.state.summary()["ejections"] for b in self.backends),
            "readmissions": sum(
                b.state.summary()["readmissions"] for b in self.backends
            ),
            "wire_latency_ms": wire_summary,
            "per_backend_wire": per_wire,
        }

    def health(self) -> dict:
        """The front ``{"op": "health"}`` payload: cheap (cached poll facts
        only — no backend round-trips, the 1 Hz contract)."""
        rows = {b.host_id: b.poll_row() for b in self.backends}
        return {
            "fleet": True,
            "warm": True,
            "backends": len(self.backends),
            "backends_live": len(self.live_backends()),
            "backends_draining": sum(1 for b in self.backends if b.draining),
            "queue_depth": sum(b.queue_depth for b in self.backends),
            "replicas": sum(b.replicas for b in self.backends),
            "swap_epoch": min(
                (b.swap_epoch for b in self.backends), default=0
            ),
            "uptime_s": round(time.monotonic() - self._monitor_t0, 3),
            "start_seq": self._start_seq,
            "router": self.router_summary(),
            "per_backend": rows,
        }

    def live_events(self, cursor: dict | None = None, limit: int = 512) -> dict:
        """The front ``{"op": "events"}`` payload: the router process's own
        spine tail plus every live backend's, aggregated.

        ``cursor`` is the previous reply's ``cursor`` block passed back
        verbatim — per-source ``{"start_seq", "seq"}`` pairs keyed
        ``"router"`` / backend host_id, so each source's tail survives ITS
        OWN restarts independently (an epoch-mismatched pair restarts that
        source from its buffer head; the others are untouched). Events
        concatenate per source in seq order — per-backend ordering is
        preserved, cross-backend order is by source, not wall clock (the
        envelopes carry ``ts`` for a reader that wants a merged timeline).
        ``dropped``/``lost`` sum the per-source loss ledgers: loss anywhere
        in the fleet is visible at the front door."""
        cursor = cursor if isinstance(cursor, dict) else {}
        events: list[dict] = []
        cursors: dict[str, dict] = {}
        dropped = lost = 0

        def fold(source: str, tail: dict) -> None:
            nonlocal dropped, lost
            for e in tail.get("events") or []:
                events.append({**e, "source": source})
            cursors[source] = {"start_seq": tail.get("start_seq"),
                               "seq": tail.get("next_seq")}
            dropped += int(tail.get("dropped") or 0)
            lost += int(tail.get("lost") or 0)

        fold("router", ensure_bus().tail(cursor.get("router"), limit=limit))
        for b in self.backends:
            if not b.state.live():
                continue
            try:
                rep = b.call({
                    "op": "events", "cursor": cursor.get(b.host_id),
                    "limit": int(limit),
                })
                tail = rep.get("events") or {}
            except _FORWARD_ERRORS as e:
                if b.state.record_failure():
                    _emit_event(
                        "backend_ejected", backend=b.host_id, addr=b.addr,
                        reason=f"events: {type(e).__name__}",
                    )
                continue
            b.state.record_success()
            fold(b.host_id, tail)
        return {"fleet": True, "events": events, "cursor": cursors,
                "dropped": dropped, "lost": lost}

    def live_metrics(self) -> dict:
        """The front ``{"op": "metrics"}`` payload: every live backend's
        metrics verb polled and AGGREGATED — raw counter sums (exact; the
        fleet controller differences two polls into windows exactly as it
        does one host's), the router's own exactly-merged wire latency, and
        the full per-backend rows (the per-host view a blended blob would
        bury)."""
        per_backend: dict[str, dict] = {}
        agg = {
            "fleet": True,
            "completed": 0, "batches": 0, "restarts": 0,
            "shed": {}, "faults": {},
            "queue_depth_now": 0, "workers": 0, "replicas": 0,
            "slo": None, "per_scenario": None, "dispatch": None,
            "compile_cache_after_warmup": None,
            "rows": None,
            "buckets": None,
            "swap_epoch": None,
            "breaker": None,
        }
        slo_n = slo_met = 0
        slo_seen = False
        # per-phase (n, sum_ms) EXACT sums across backends: quantiles cannot
        # cross a process boundary exactly (the raw samples live in each
        # backend), but counts and sums add — so the fleet mean per phase is
        # exact, and the per-backend rows keep their own exact quantiles.
        # The router's own wire phase is appended below from ITS raw
        # histogram (router-side samples: exact quantiles AND sums).
        phase_sum: dict[str, dict] = {}
        trace_sampled_n = 0
        trace_seen = False
        per_scen: dict[str, dict] = {}
        disp_over = disp_routed = 0
        disp_mode: set[str] = set()
        disp_seen = False
        cache_sum: dict[str, int] = {}
        cache_seen = False
        rows_sum: dict[str, int] = {}
        rows_seen = False
        for b in self.backends:
            if not b.state.live():
                continue
            try:
                rep = b.call({"op": "metrics"})
                m = rep.get("metrics") or {}
            except _FORWARD_ERRORS as e:
                if b.state.record_failure():
                    _emit_event(
                        "backend_ejected", backend=b.host_id, addr=b.addr,
                        reason=f"metrics: {type(e).__name__}",
                    )
                continue
            b.state.record_success()
            per_backend[b.host_id] = {
                "listen": b.listen or m.get("listen"),
                "completed": m.get("completed"),
                "rps": m.get("rps"),
                "goodput_rps": m.get("goodput_rps"),
                "latency_ms": m.get("latency_ms"),
                "phases": m.get("phases"),
                "trace": m.get("trace"),
                "queue_depth_now": m.get("queue_depth_now"),
                "replicas": m.get("replicas", m.get("workers")),
                "workers": m.get("workers"),
                "swap_epoch": m.get("swap_epoch"),
                "slo": m.get("slo"),
                "per_scenario": m.get("per_scenario"),
                "compile_cache_after_warmup": m.get("compile_cache_after_warmup"),
                "breaker": m.get("breaker"),
                **self.state_row(b),
            }
            agg["completed"] += int(m.get("completed") or 0)
            agg["batches"] += int(m.get("batches") or 0)
            agg["restarts"] += int(m.get("restarts") or 0)
            for k, v in (m.get("shed") or {}).items():
                agg["shed"][k] = agg["shed"].get(k, 0) + v
            for k, v in (m.get("faults") or {}).items():
                agg["faults"][k] = agg["faults"].get(k, 0) + v
            agg["queue_depth_now"] += int(m.get("queue_depth_now") or 0)
            agg["workers"] += int(m.get("workers") or 0)
            agg["replicas"] += int(m.get("replicas") or 1)
            slo = m.get("slo")
            if isinstance(slo, dict):
                slo_seen = True
                slo_n += int(slo.get("n") or 0)
                slo_met += int(slo.get("met") or 0)
            for k, v in (m.get("per_scenario") or {}).items():
                row = per_scen.setdefault(k, {"n": 0, "conf_sum": 0.0})
                row["n"] += int(v.get("n") or 0)
                row["conf_sum"] += float(v.get("conf_sum") or 0.0)
            for name, blk in (m.get("phases") or {}).items():
                if not isinstance(blk, dict):
                    continue
                row = phase_sum.setdefault(name, {"n": 0, "sum_ms": 0.0})
                row["n"] += int(blk.get("n") or 0)
                row["sum_ms"] += float(blk.get("sum_ms") or 0.0)
            tcov = m.get("trace")
            if isinstance(tcov, dict):
                trace_seen = True
                trace_sampled_n += int(tcov.get("sampled") or 0)
            disp = m.get("dispatch")
            if isinstance(disp, dict):
                disp_seen = True
                disp_over += int(disp.get("overflow_rows") or 0)
                disp_routed += int(disp.get("routed_rows") or 0)
                if disp.get("mode"):
                    disp_mode.add(str(disp["mode"]))
            cache = m.get("compile_cache_after_warmup")
            if isinstance(cache, dict):
                cache_seen = True
                for k, v in cache.items():
                    cache_sum[k] = cache_sum.get(k, 0) + int(v or 0)
            rows = m.get("rows")
            if isinstance(rows, dict):
                rows_seen = True
                for k, v in rows.items():
                    rows_sum[k] = rows_sum.get(k, 0) + int(v or 0)
            if agg["buckets"] is None:
                agg["buckets"] = m.get("buckets")
            se = m.get("swap_epoch")
            if se is not None:
                agg["swap_epoch"] = (
                    se if agg["swap_epoch"] is None else min(agg["swap_epoch"], se)
                )
        if slo_seen and slo_n:
            agg["slo"] = {"n": slo_n, "met": slo_met,
                          "attainment": round(slo_met / slo_n, 4)}
        if per_scen:
            for k, row in per_scen.items():
                if row["n"]:
                    row["conf_sum"] = round(row["conf_sum"], 4)
                    row["conf_mean"] = round(row["conf_sum"] / row["n"], 4)
            agg["per_scenario"] = per_scen
        if disp_seen:
            agg["dispatch"] = {
                "mode": (disp_mode.pop() if len(disp_mode) == 1
                         else "mixed" if disp_mode else None),
                "overflow_rows": disp_over,
                "routed_rows": disp_routed,
                "overflow_rate": (
                    round(disp_over / disp_routed, 6) if disp_routed else 0.0
                ),
            }
        if cache_seen:
            # per-key SUM across hosts: all-zero iff EVERY live backend's
            # request path stayed compile-free since its own warmup
            agg["compile_cache_after_warmup"] = cache_sum
        if rows_seen:
            agg["rows"] = rows_sum
        agg["backends_polled"] = len(per_backend)
        rsum = self.router_summary()  # once: it copies+merges every
        # backend's latency histogram under its lock
        agg["latency_ms"] = rsum["wire_latency_ms"]
        # fleet phase decomposition: backend phases as exact (n, sum_ms,
        # mean_ms) sums; the router's OWN wire phase (net spans from traced
        # requests) appended with full exact quantiles — its raw samples
        # live here
        phases: dict[str, dict] = {}
        for name, row in phase_sum.items():
            entry = {"n": row["n"], "sum_ms": round(row["sum_ms"], 3)}
            if row["n"]:
                entry["mean_ms"] = round(row["sum_ms"] / row["n"], 3)
            phases[name] = entry
        with self._trace_lock:
            wire_summary = self._trace_wire.summary()
            if wire_summary is not None:
                wire_summary["sum_ms"] = round(self._trace_wire.sum() * 1e3, 3)
        if wire_summary is not None:
            phases["wire"] = wire_summary
        agg["phases"] = phases or None
        if trace_seen:
            agg["trace"] = {
                "sampled": trace_sampled_n,
                "completed": agg["completed"],
                "fraction": (
                    round(trace_sampled_n / agg["completed"], 4)
                    if agg["completed"]
                    else None
                ),
            }
        agg["router"] = rsum
        agg["per_backend"] = per_backend
        return agg

    @staticmethod
    def state_row(b: Backend) -> dict:
        return {"state": "draining" if b.draining else b.state.state}
