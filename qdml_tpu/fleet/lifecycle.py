"""Backend lifecycle manager: spawn -> warm -> admit / drain -> retire.

PR 16 shipped the *decide* half of the elastic fleet (capacity planner,
burn-rate alerting); this module is the *act* half — the piece that turns
"backends_needed: 3" into three warmed processes behind the router.
:class:`BackendLifecycle` supervises the full state machine
(docs/FLEET.md "elastic fleet"):

    spawn ──▶ warming ──▶ admitted ──▶ draining ──▶ retired
                 │
                 └──▶ quarantined   (failed admission: killed, fleet untouched)

The two invariants the committed dryrun (results/fleet_elastic/) gates:

- **a cold backend is never admitted** — :meth:`scale_up` launches a real
  ``qdml-tpu serve`` process (fleet/spawn.py), waits for its post-bind
  banner (printed AFTER AOT warmup + autotune complete), then health-
  verifies ``warm=true`` and a ZERO request-path compile-cache delta over
  the live verbs BEFORE :meth:`FleetRouter.add_backend` ever runs. Any
  verification failure (including a process killed mid-admission)
  quarantines the standby: it is terminated and the serving fleet never
  saw it.
- **retirement strands nothing** — :meth:`scale_down` is drain-then-exit
  through the router's ring-safe machinery: vnodes leave the ring first
  (typed ``draining`` state, no fresh admissions), in-flight forwards
  complete, the host leaves the table (router-side dedup entries keep
  answering retries for the TTL), and only then — after ``dedup_grace_s``
  for any direct-connected client's server-side dedup window — does the
  process get SIGINT (run_server's flush path).

Every transition emits a structured ``fleet_lifecycle`` record; the
fleet-tier autoscaler (control/fleet_scale.py) drives :meth:`scale_to`
and the router front door exposes it as ``{"op": "fleet"}``
(``qdml-tpu fleet-scale``).

Thread model: the autoscaler tick thread drives scale_up/scale_down while
status readers (the fleet verb) walk the member table — ``_members`` and
``_procs`` hold ``_lock`` for every touch (graftlint LOCK_MAP,
analysis/project.py). The underlying membership mutation is the router's
own ``_ring_lock`` discipline; one scale operation at a time is serialized
by ``_scale_lock`` so concurrent fleet verbs cannot interleave half-grown
fleets.
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time

from qdml_tpu.fleet.router import FleetRouter, _emit_event
from qdml_tpu.fleet.spawn import spawn_backend
from qdml_tpu.serve.client import ServeClient, ServeClientError

#: transport/shape failures during admission verification — all of them
#: quarantine the standby (a backend that cannot prove it is warm is cold)
_VERIFY_ERRORS = (
    ServeClientError, ConnectionError, TimeoutError, OSError,
    RuntimeError, ValueError, KeyError,
)


class AdmissionFailed(RuntimeError):
    """A spawned standby failed its warm/zero-compile verification."""


def verify_warm(host: str, port: int, timeout_s: float = 10.0) -> dict:
    """The admission criteria, checked over the LIVE verbs (not the banner
    alone — the process must prove it answers): ``health.warm`` must be
    true and every ``compile_cache_after_warmup`` counter must be zero
    (a request-path compile after warmup means the AOT cover is
    incomplete — admitting it would ship compile stalls into the serving
    tail). Returns the verified facts; raises :class:`AdmissionFailed`."""
    client = ServeClient(host, port, timeout_s=timeout_s, retries=0)
    try:
        rep = client.health()
        h = (rep.get("health") or {}) if rep.get("ok") else {}
        if not h.get("warm"):
            raise AdmissionFailed(f"{host}:{port} reports warm={h.get('warm')!r}")
        m = (client.metrics().get("metrics")) or {}
    finally:
        client.close_connection()
    cache = m.get("compile_cache_after_warmup")
    if not isinstance(cache, dict):
        raise AdmissionFailed(
            f"{host}:{port} metrics carry no compile_cache_after_warmup"
        )
    nonzero = {k: v for k, v in cache.items() if v}
    if nonzero:
        raise AdmissionFailed(
            f"{host}:{port} has request-path compiles after warmup: {nonzero}"
        )
    return {
        "warm": True,
        "host_id": h.get("host_id"),
        "replicas": h.get("replicas"),
        "compile_cache_after_warmup": cache,
    }


class BackendLifecycle:
    """Supervised elastic membership over one :class:`FleetRouter`.

    ``spawn_overrides`` are the dotted-config CLI flags every spawned
    backend gets (``--train.workdir=...`` included, so it restores the same
    checkpoints as the boot-time fleet). ``spawn_fn``/``verify_fn`` are
    injectable for tests (the default pair launches and verifies real
    ``qdml-tpu serve`` subprocesses)."""

    def __init__(
        self,
        router: FleetRouter,
        spawn_overrides: list[str] | tuple[str, ...] = (),
        host: str = "127.0.0.1",
        spawn_timeout_s: float = 600.0,
        verify_timeout_s: float = 10.0,
        drain_wait_s: float = 30.0,
        dedup_grace_s: float = 0.0,
        log_dir: str | None = None,
        spawn_fn=None,
        verify_fn=None,
    ):
        self.router = router
        self.spawn_overrides = tuple(spawn_overrides)
        self.host = host
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.verify_timeout_s = float(verify_timeout_s)
        self.drain_wait_s = float(drain_wait_s)
        self.dedup_grace_s = float(dedup_grace_s)
        self.log_dir = log_dir
        self._spawn_fn = spawn_fn or spawn_backend
        self._verify_fn = verify_fn or verify_warm
        # member table: addr -> {"state", "host_id", ...facts}; procs the
        # lifecycle OWNS (spawned here — boot-time backends are not ours to
        # terminate). Autoscaler tick thread writes, fleet-verb status
        # readers iterate: every touch holds _lock.
        self._lock = lockdep.Lock("BackendLifecycle._lock")
        self._members: dict[str, dict] = {}
        self._procs: dict[str, object] = {}
        # one membership change at a time: two concurrent fleet verbs must
        # not interleave their grow/shrink loops
        self._scale_lock = lockdep.Lock("BackendLifecycle._scale_lock")
        self._seq = 0

    # -- bookkeeping ---------------------------------------------------------

    def _record(self, addr: str, state: str, **facts) -> dict:
        with self._lock:
            row = self._members.setdefault(addr, {"addr": addr})
            row.update(state=state, **facts)
            row = dict(row)
        _emit_event("fleet_lifecycle", stage=state, addr=addr,
                    backend=row.get("host_id"))
        return row

    def fleet_size(self) -> int:
        """Serving members (draining hosts are already leaving)."""
        return len([b for b in self.router.backends if not b.draining])

    def status(self) -> dict:
        with self._lock:
            members = {a: dict(r) for a, r in self._members.items()}
            owned = list(self._procs)
        return {
            "backends": self.fleet_size(),
            "backends_draining": sum(
                1 for b in self.router.backends if b.draining
            ),
            "owned": owned,
            "lifecycle": members,
            "fleet": {
                b.host_id: {"addr": b.addr, **self.router.state_row(b)}
                for b in self.router.backends
            },
        }

    # -- spawn-and-warm admission -------------------------------------------

    def scale_up(self) -> dict:
        """Grow the fleet by one WARMED backend. Spawn (banner gates on the
        child's own post-warmup announce), verify over the live verbs, only
        then splice into the ring. Every failure quarantines the standby
        and leaves the serving fleet untouched."""
        with self._lock:
            self._seq += 1
            seq = self._seq
        t0 = time.monotonic()
        log_path = (
            f"{self.log_dir}/backend_elastic_{seq}.log" if self.log_dir else None
        )
        try:
            proc = self._spawn_fn(
                list(self.spawn_overrides), port=0, host=self.host,
                log_path=log_path, timeout_s=self.spawn_timeout_s,
            )
        except (TimeoutError, RuntimeError, OSError) as e:
            rec = self._record(
                f"spawn-{seq}", "quarantined",
                reason=f"spawn: {type(e).__name__}: {e}",
            )
            return {"action": "scale_up", "ok": False, "stage": "spawn",
                    "reason": rec["reason"]}
        addr = f"{proc.host}:{proc.port}"
        with self._lock:
            self._procs[addr] = proc
        self._record(addr, "warming", host_id=proc.host_id,
                     spawn_s=round(time.monotonic() - t0, 3))
        try:
            facts = self._verify_fn(
                proc.host, proc.port, timeout_s=self.verify_timeout_s
            )
        except _VERIFY_ERRORS as e:
            # kill-during-admission lands here: the standby is quarantined
            # (terminated, never admitted) and the fleet keeps serving
            self._quarantine(addr, f"{type(e).__name__}: {e}")
            return {"action": "scale_up", "ok": False, "stage": "quarantined",
                    "addr": addr, "reason": f"{type(e).__name__}: {e}"}
        b = self.router.add_backend(proc.host, proc.port)
        self._record(addr, "admitted", host_id=b.host_id, verified=facts)
        return {
            "action": "scale_up", "ok": True, "stage": "admitted",
            "addr": addr, "backend": b.host_id, "verified": facts,
            "elapsed_s": round(time.monotonic() - t0, 3),
        }

    def _quarantine(self, addr: str, reason: str) -> None:
        with self._lock:
            proc = self._procs.pop(addr, None)
        if proc is not None and proc.alive():
            proc.kill()
        self._record(addr, "quarantined", reason=reason)

    # -- drain-then-retire ---------------------------------------------------

    def _pick_victim(self):
        """Newest lifecycle-owned admitted member first (LIFO — give back
        what we grew before touching the boot-time fleet), else the newest
        non-draining router member."""
        with self._lock:
            owned = [
                a for a, r in self._members.items() if r.get("state") == "admitted"
            ]
        for addr in reversed(owned):
            for b in self.router.backends:
                if b.addr == addr and not b.draining:
                    return b
        candidates = [b for b in self.router.backends if not b.draining]
        if not candidates:
            raise ValueError("no retirable backend")
        return candidates[-1]

    def scale_down(self, key=None) -> dict:
        """Shrink by one: ring-safe drain (no fresh admissions, in-flight
        forwards complete, dedup'd retries keep answering router-side),
        remove from the table, wait ``dedup_grace_s`` for any direct
        client's server-side dedup window, then SIGINT the process if this
        lifecycle spawned it (boot-time backends are left running — their
        supervisor owns them)."""
        victim = self.router._find_backend(key) if key is not None else self._pick_victim()
        addr = victim.addr
        self._record(addr, "draining", host_id=victim.host_id)
        rec = self.router.retire_backend(victim, wait_s=self.drain_wait_s)
        with self._lock:
            proc = self._procs.pop(addr, None)
        if self.dedup_grace_s > 0:
            time.sleep(self.dedup_grace_s)
        terminated = False
        if proc is not None:
            proc.terminate()
            terminated = True
        self._record(addr, "retired", host_id=rec["backend"],
                     drained=rec["drained"], terminated=terminated)
        return {"action": "scale_down", "ok": True, "stage": "retired",
                "addr": addr, "terminated": terminated, **rec}

    # -- the fleet-count lever ----------------------------------------------

    def scale_to(self, backends: int) -> dict:
        """Converge the serving member count to ``backends`` one admission/
        retirement at a time (each one fully verified/drained before the
        next starts). A failed admission aborts the grow loop with the
        failure recorded — a half-warm standby must not be retried blindly
        in a tight loop."""
        n = int(backends)
        if n < 1:
            raise ValueError(f"fleet target must be >= 1, got {n}")
        with self._scale_lock:
            before = self.fleet_size()
            actions: list[dict] = []
            while self.fleet_size() < n:
                rec = self.scale_up()
                actions.append(rec)
                if not rec["ok"]:
                    break
            while self.fleet_size() > n:
                actions.append(self.scale_down())  # lint: disable=blocking-under-lock(scale ops are one-at-a-time by design: _scale_lock is the coarse serializer for admissions/retirements, held only on the control path — the dedup-grace sleep must finish before the next retirement starts)
            after = self.fleet_size()
        return {
            "backends_before": before,
            "backends": after,
            "target": n,
            "ok": after == n,
            "actions": actions,
        }

    def close(self, terminate_owned: bool = True) -> None:
        """Tear down lifecycle-owned processes (harness exit path)."""
        with self._lock:
            procs = dict(self._procs)
            self._procs.clear()
        if terminate_owned:
            for proc in procs.values():
                proc.terminate()
