"""Fleet router tier: the front door that spans hosts (docs/FLEET.md).

Everything below the socket has been fleet-ready for several PRs —
mesh-sharded AOT buckets, drain-safe replica pools, supervision, breaker,
the client retry/dedup contract — but one controller process on one host
still terminated every connection. This package is the missing tier:

- :class:`~qdml_tpu.fleet.router.FleetRouter` — per-backend tables,
  breaker-semantics ejection/re-admission, consistent-hash or
  least-queue-depth balancing, fleet-wide request dedup, ``swap`` fan-out
  and ``metrics``/``health`` aggregation (exact counter sums +
  ``Histogram.merge`` wire latency);
- :func:`~qdml_tpu.fleet.frontend.run_router` / ``qdml-tpu route`` — the
  asyncio front socket speaking the serve protocol verbatim (clients,
  loadgen and the control plane cannot tell a router from a single host);
- :class:`~qdml_tpu.fleet.poller.FleetPoller` — the control plane's
  attachment, so drift adaptation, canary-gated tagged hot-swap and
  queue-depth autoscaling (now choosing WHICH host) span the fleet;
- :mod:`~qdml_tpu.fleet.spawn` — real ``qdml-tpu serve`` subprocess
  harness for the committed dryrun (scripts/fleet_router_dryrun.py);
- :class:`~qdml_tpu.fleet.lifecycle.BackendLifecycle` — elastic
  membership: spawn-and-warm admission (a cold backend is never admitted),
  ring-safe drain-then-retire, the ``{"op": "fleet"}`` /
  ``qdml-tpu fleet-scale`` lever the fleet autoscaler drives
  (docs/FLEET.md "elastic fleet").
"""

from qdml_tpu.fleet.frontend import (  # noqa: F401
    lifecycle_from_config,
    route_async,
    router_from_config,
    run_router,
)
from qdml_tpu.fleet.lifecycle import (  # noqa: F401
    AdmissionFailed,
    BackendLifecycle,
    verify_warm,
)
from qdml_tpu.fleet.poller import FleetPoller  # noqa: F401
from qdml_tpu.fleet.router import (  # noqa: F401
    Backend,
    BackendState,
    FleetRouter,
    RouterDedup,
    parse_backends,
)
from qdml_tpu.fleet.spawn import BackendProc, spawn_backend  # noqa: F401
