"""Front-door socket for the fleet router: the serve protocol, one tier up.

``qdml-tpu route`` runs :func:`run_router`: an asyncio loop accepting the
SAME newline-JSON protocol ``qdml-tpu serve`` speaks — inference lines,
``{"op": "metrics"}``, ``{"op": "health"}``, ``{"op": "swap"}``,
``{"op": "scale"}`` — and hands every message to the
:class:`~qdml_tpu.fleet.router.FleetRouter` on an executor thread (all
backend exchanges are blocking ``ServeClient`` calls). Clients cannot tell
a router from a single host, which is the point: ``run_loadgen_socket``,
``ServeClient``, the fleet controller's ``SocketPoller`` and a human with
``nc`` all work unchanged.

The two scaling axes (docs/FLEET.md "elastic fleet"): ``{"op": "scale",
"replicas": N}`` targets the fleet-total REPLICA count inside the existing
hosts (the router picks which host to resize — replica axis), while
``{"op": "fleet", "backends": N}`` changes the BACKEND-PROCESS count
itself through the attached :class:`~qdml_tpu.fleet.lifecycle.
BackendLifecycle` (spawn-and-warm admission, drain-then-retire). A router
without a lifecycle manager answers the scaling form with the typed
``fleet_scale_unavailable`` reason; the argument-free ``{"op": "fleet"}``
status form always answers with the membership/lifecycle view.

Connection hardening is the serve front-end's, reused verbatim: bounded
reads through :func:`qdml_tpu.serve.server._read_line` (idle/slow-loris
reap with a typed ``idle_timeout`` reply), ``bad_json`` on garbage with the
connection surviving, typed ``bad_request`` + close on an oversized line
(``serve.conn_timeout_s`` / ``serve.max_line_bytes`` govern both tiers).
"""

from __future__ import annotations

import asyncio
import json
import os
import socket
import uuid

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.fleet.router import FleetRouter, parse_backends
from qdml_tpu.serve.server import _read_line


def router_from_config(cfg: ExperimentConfig, seed: int = 0) -> FleetRouter:
    """Build (but do not start) the router from ``cfg.fleet``; an empty
    ``fleet.backends`` fronts the single local serve endpoint."""
    fl = cfg.fleet
    return FleetRouter(
        parse_backends(fl.backends, default=(cfg.serve.host, cfg.serve.port)),
        balance=fl.balance,
        timeout_s=fl.timeout_s,
        retries=fl.retries,
        eject_failures=fl.eject_failures,
        eject_s=fl.eject_s,
        readmit_probes=fl.readmit_probes,
        poll_interval_s=fl.poll_interval_s,
        failover=fl.failover,
        dedup_ttl_s=fl.dedup_ttl_s,
        seed=seed,
        # the SAME knob the serve tier samples on (deterministic id hash):
        # router and backends agree per request without a config handshake
        trace_sample=cfg.serve.trace_sample,
    )


async def _handle_front(
    reader, writer, router: FleetRouter, conn_timeout_s: float,
    lifecycle=None,
) -> None:
    aloop = asyncio.get_running_loop()

    async def _reply(obj: dict) -> None:
        writer.write((json.dumps(obj) + "\n").encode())
        await writer.drain()

    try:
        while True:
            try:
                line = await _read_line(reader, conn_timeout_s)
            except asyncio.TimeoutError:
                await _reply({"ok": False, "reason": "idle_timeout"})
                break
            except (asyncio.LimitOverrunError, ValueError):
                # framing lost mid-line: typed reply and close, exactly like
                # the serve tier (resyncing would misparse the tail)
                await _reply({
                    "ok": False,
                    "reason": "bad_request: line exceeds serve.max_line_bytes",
                })
                break
            if not line:
                break
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                await _reply({"ok": False, "reason": "bad_json"})
                continue
            if not isinstance(msg, dict):
                await _reply({"id": None, "ok": False,
                              "reason": "bad_request: message must be a JSON object"})
                continue
            op = msg.get("op")
            try:
                if op == "health":
                    rep = {"id": msg.get("id"), "ok": True,
                           "health": router.health()}
                elif op == "metrics":
                    # aggregation polls every live backend: off the event
                    # loop, like the serve tier's histogram merge
                    view = await aloop.run_in_executor(None, router.live_metrics)
                    rep = {"id": msg.get("id"), "ok": True, "metrics": view}
                elif op == "events":
                    # aggregated event-spine tail (docs/TELEMETRY.md "event
                    # spine"): the router's own events plus every live
                    # backend's, per-source cursors passed back verbatim.
                    # Off the event loop — it round-trips every backend.
                    cur = msg.get("cursor")
                    if cur is not None and not isinstance(cur, dict):
                        raise ValueError(
                            f"events cursor must be an object, got {cur!r}"
                        )
                    lim = int(msg.get("limit") or 512)
                    view = await aloop.run_in_executor(
                        None, router.live_events, cur, lim
                    )
                    rep = {"id": msg.get("id"), "ok": True, "events": view}
                elif op == "swap":
                    tags = msg.get("tags")
                    if tags is not None and not (
                        isinstance(tags, dict)
                        and all(isinstance(k, str) and isinstance(v, str)
                                for k, v in tags.items())
                    ):
                        raise ValueError(
                            f"swap tags must be a str->str map, got {tags!r}"
                        )
                    rec = await aloop.run_in_executor(
                        None, router.swap_fanout, tags
                    )
                    rep = {"id": msg.get("id"), "ok": bool(rec["ok"]), "swap": rec}
                    if not rec["ok"]:
                        rep["reason"] = "swap_failed: partial fan-out (see swap.backends)"
                elif op == "scale":
                    # replica axis: resize pools INSIDE the existing hosts
                    n = int(msg["replicas"])
                    rec = await aloop.run_in_executor(None, router.scale_fleet, n)
                    rep = {"id": msg.get("id"), "ok": True, "scale": rec}
                elif op == "fleet":
                    # backend-count axis: membership itself. Status form
                    # (no "backends") always answers; the scaling form
                    # needs an attached lifecycle manager.
                    if "backends" not in msg:
                        status = (
                            lifecycle.status() if lifecycle is not None
                            else {
                                "backends": len(router.live_backends()),
                                "backends_draining": sum(
                                    1 for b in router.backends if b.draining
                                ),
                                "fleet": {
                                    b.host_id: {
                                        "addr": b.addr,
                                        **router.state_row(b),
                                    }
                                    for b in router.backends
                                },
                            }
                        )
                        status["elastic"] = lifecycle is not None
                        rep = {"id": msg.get("id"), "ok": True, "fleet": status}
                    elif lifecycle is None:
                        rep = {
                            "id": msg.get("id"), "ok": False,
                            "reason": "fleet_scale_unavailable: router has "
                                      "no lifecycle manager (fleet.elastic)",
                        }
                    else:
                        n = int(msg["backends"])
                        rec = await aloop.run_in_executor(
                            None, lifecycle.scale_to, n
                        )
                        rep = {"id": msg.get("id"), "ok": bool(rec["ok"]),
                               "fleet": rec}
                        if not rec["ok"]:
                            rep["reason"] = (
                                "fleet_scale_failed: converged to "
                                f"{rec['backends']} of {rec['target']} "
                                "(see fleet.actions)"
                            )
                else:
                    # inference: the router needs an id for dedup + hash
                    # affinity; an anonymous request gets a fresh one for
                    # routing and its reply id restored to what was sent
                    anon = "id" not in msg
                    if anon:
                        msg = {**msg, "id": f"anon-{uuid.uuid4().hex[:12]}"}
                    rep = await aloop.run_in_executor(None, router.request, msg)
                    if anon:
                        rep = {**rep, "id": None}
            except (KeyError, TypeError, ValueError) as e:
                rep = {"id": msg.get("id"), "ok": False,
                       "reason": f"bad_request: {e}"}
            except (ConnectionError, RuntimeError, OSError) as e:
                # a fan-out verb that could reach nobody (or a backend scale
                # rejection): typed, retryable, connection survives
                rep = {"id": msg.get("id"), "ok": False,
                       "reason": f"router_error: {type(e).__name__}: {e}"}
            await _reply(rep)
    except (ConnectionResetError, BrokenPipeError):
        pass  # the peer vanished: nothing stranded, forwards resolve router-side
    finally:
        try:
            writer.close()
        except RuntimeError:
            pass


async def route_async(
    router: FleetRouter,
    host: str,
    port: int,
    ready: "asyncio.Future | None" = None,
    conn_timeout_s: float = 30.0,
    max_line_bytes: int = 8_388_608,
    lifecycle=None,
) -> None:
    """Accept front-door connections until cancelled; resolves ``ready``
    with the bound port (port=0 = ephemeral, the test/dryrun pattern).
    ``lifecycle`` (a :class:`~qdml_tpu.fleet.lifecycle.BackendLifecycle`)
    arms the ``{"op": "fleet"}`` scaling form."""
    server = await asyncio.start_server(
        lambda r, w: _handle_front(
            r, w, router, conn_timeout_s, lifecycle=lifecycle
        ),
        host=host,
        port=port,
        limit=max_line_bytes,
    )
    bound = server.sockets[0].getsockname()[1]
    if ready is not None and not ready.done():
        ready.set_result(bound)
    async with server:
        await server.serve_forever()


def lifecycle_from_config(cfg: ExperimentConfig, router: FleetRouter):
    """The ``fleet.elastic`` wiring: a :class:`BackendLifecycle` whose
    spawned backends get ``fleet.spawn_overrides`` (comma-separated dotted
    flags — ``--train.workdir=...`` included so they restore the serving
    checkpoints). Returns None when elasticity is off."""
    if not cfg.fleet.elastic:
        return None
    from qdml_tpu.fleet.lifecycle import BackendLifecycle

    overrides = [
        o.strip() for o in cfg.fleet.spawn_overrides.split(",") if o.strip()
    ]
    return BackendLifecycle(
        router,
        spawn_overrides=overrides,
        spawn_timeout_s=cfg.fleet.spawn_timeout_s,
        drain_wait_s=cfg.fleet.drain_wait_s,
        dedup_grace_s=cfg.fleet.dedup_grace_s,
    )


def run_router(cfg: ExperimentConfig, logger=None) -> None:
    """Blocking entry for ``qdml-tpu route``: prime the backend table,
    announce (actual bound port + router identity + backend table), route
    until interrupted. No checkpoints, no jax compute — the router is pure
    protocol; backends own the models. ``fleet.elastic=true`` attaches a
    lifecycle manager, arming the ``{"op": "fleet"}`` scaling form."""
    router = router_from_config(cfg).start()
    lifecycle = lifecycle_from_config(cfg, router)

    async def _route_announced() -> None:
        aloop = asyncio.get_running_loop()
        ready: asyncio.Future = aloop.create_future()
        task = aloop.create_task(
            route_async(
                router, cfg.fleet.host, cfg.fleet.port, ready,
                conn_timeout_s=cfg.serve.conn_timeout_s,
                max_line_bytes=cfg.serve.max_line_bytes,
                lifecycle=lifecycle,
            )
        )
        await asyncio.wait({task, ready}, return_when=asyncio.FIRST_COMPLETED)
        if task.done():
            return task.result()  # bind failure propagates
        print(
            json.dumps(
                {
                    "routing": f"{cfg.fleet.host}:{ready.result()}",
                    "router_id": f"{socket.gethostname()}-{os.getpid()}",
                    "balance": router.balance,
                    "elastic": lifecycle is not None,
                    "backends": {
                        b.host_id: {"addr": b.addr, "state": b.state.state}
                        for b in router.backends
                    },
                    "backends_live": len(router.live_backends()),
                }
            ),
            flush=True,
        )
        await task

    try:
        asyncio.run(_route_announced())
    except KeyboardInterrupt:
        pass
    finally:
        if lifecycle is not None:
            lifecycle.close()
        router.stop()
        if logger is not None:
            logger.telemetry.write_raw(
                {"kind": "router_summary", **router.router_summary()}
            )
