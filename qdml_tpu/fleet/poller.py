"""FleetPoller: the control plane's attachment to the router tier.

:class:`~qdml_tpu.control.loop.SocketPoller` generalized over the router's
AGGREGATED verbs: the :class:`~qdml_tpu.control.loop.FleetController`'s
drift detection windows the summed per-scenario counters exactly as it
windows one host's (raw sums difference exactly), the queue-depth
autoscaler sees the fleet-total depth and the router chooses WHICH host to
resize (:meth:`FleetRouter.scale_fleet`), and a tagged deploy fans the swap
to every live backend at once.

Two forms, one contract:

- **in-process** — :class:`FleetPoller` wraps a live
  :class:`~qdml_tpu.fleet.router.FleetRouter` object (the dryrun/test
  harness, scripts/fleet_router_dryrun.py);
- **remote** — the router's front socket speaks the serve protocol
  verbatim, so the existing ``SocketPoller`` pointed at the ROUTER address
  is already the remote fleet poller (``qdml-tpu control`` against
  ``fleet.host:fleet.port`` — nothing new on the wire);
  :meth:`FleetPoller.remote` spells that out.

Partial-fan-out discipline: a swap that lands on every LIVE backend is a
success even when ejected hosts were skipped (they re-resolve checkpoints
at re-admission/restart) — a single backend's ejection must never suspend
adaptation for the surviving hosts (docs/FLEET.md). A swap that failed on
a LIVE backend raises, which the controller's ``tick_failed`` path reports
and survives.
"""

from __future__ import annotations

from qdml_tpu.fleet.router import FleetRouter


class FleetPoller:
    """In-process controller attachment to a running :class:`FleetRouter`.
    ``lifecycle`` (a :class:`~qdml_tpu.fleet.lifecycle.BackendLifecycle`)
    arms :meth:`fleet` — the backend-COUNT axis, distinct from
    :meth:`scale`'s replica axis (docs/FLEET.md "elastic fleet")."""

    def __init__(self, router: FleetRouter, lifecycle=None):
        self.router = router
        self.lifecycle = lifecycle

    def metrics(self) -> dict:
        """The aggregated fleet view (summed counters + per-backend rows) —
        the same payload the router's ``{"op": "metrics"}`` verb serves."""
        return self.router.live_metrics()

    def health(self) -> dict:
        """The cheap cached-poll view (per-backend rows carry ``uptime_s`` /
        ``start_seq``, the monitor's restart detectors) — the same payload
        the router's ``{"op": "health"}`` verb serves."""
        return self.router.health()

    def events(self, cursor: dict | None = None, limit: int = 512) -> dict:
        """The aggregated event-spine tail (router's own + every live
        backend's, per-source cursors) — the same payload the router's
        ``{"op": "events"}`` verb serves."""
        return self.router.live_events(cursor, limit=limit)

    def swap(self, tags: dict) -> dict:
        rec = self.router.swap_fanout(tags)
        if not rec["ok"]:
            # a LIVE backend failed to swap: the deploy did not land fleet-
            # wide — typed failure for the controller's tick_failed path
            # (skipped ejected hosts alone never get here: ok stays true)
            raise RuntimeError(
                f"fleet swap partial: {rec['ok_count']}/{rec['fanned_to']} "
                f"live backends swapped ({rec['backends']})"
            )
        return rec

    def scale(self, n: int) -> dict:
        """Replica axis: fleet-total replica target, router picks the host."""
        return self.router.scale_fleet(n)

    def fleet(self, backends: int | None = None) -> dict:
        """Backend-count axis: membership status, or (with ``backends``)
        converge the serving member count through the lifecycle manager —
        the same facts the front door's ``{"op": "fleet"}`` verb serves."""
        if backends is None:
            if self.lifecycle is not None:
                return self.lifecycle.status()
            return {
                "backends": len(self.router.live_backends()),
                "backends_draining": sum(
                    1 for b in self.router.backends if b.draining
                ),
            }
        if self.lifecycle is None:
            raise RuntimeError(
                "fleet_scale_unavailable: poller has no lifecycle manager"
            )
        return self.lifecycle.scale_to(int(backends))

    @staticmethod
    def remote(host: str, port: int, timeout_s: float = 30.0):
        """The remote twin: the router speaks the serve protocol, so the
        control plane's existing socket attachment IS the remote fleet
        poller when pointed at the router's front address."""
        from qdml_tpu.control.loop import SocketPoller

        return SocketPoller(host, port, timeout_s=timeout_s)
