"""Dynamic micro-batcher: bounded queue, coalescing OR continuous admission.

Two admission policies share the queue/shedding machinery:

- **coalesce** (default, the TorchServe/Triton-style dynamic batching the
  bucket engine mode uses): requests coalesce until either ``max_batch`` of
  them are waiting (flush immediately — a full bucket) or the OLDEST waiting
  request has aged ``max_wait_ms`` (flush partial — latency floor beats
  fill). Batches then pad up to the next power-of-two bucket so every shape
  hitting the engine was AOT-compiled at warmup
  (:mod:`qdml_tpu.serve.engine`).
- **continuous** (``continuous=True``, the ragged engine mode's policy —
  vLLM-style continuous batching applied to this pipeline): ``next_batch``
  returns everything queued (up to ``max_batch``) the moment ANY request is
  waiting — the worker dispatches whenever the engine is free instead of
  sleeping out the coalescing window, so an idle engine never sits on a
  non-empty queue. Batching still happens, implicitly: while one dispatch is
  in flight, new arrivals queue and the next dispatch admits them all.

Admission control is deadline-aware and sheds load as typed
:class:`~qdml_tpu.serve.types.Overloaded` results instead of letting the
queue collapse: a full bounded queue rejects at submit; a request whose
deadline has already passed is rejected at submit; one whose deadline expires
while queued is shed at dequeue (running it would waste a bucket slot on an
answer the client has already abandoned).

The clock is injected (``clock=``) so every edge case — max-wait timeout,
deadline expiry at dequeue — is deterministically testable without sleeping
(``tests/test_serve.py``).
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time
from collections import deque
from typing import Callable, Sequence

from qdml_tpu.serve.types import (
    DEADLINE_AT_DEQUEUE,
    DEADLINE_AT_SUBMIT,
    QUEUE_FULL,
    Overloaded,
    Request,
)


def power_of_two_buckets(max_batch: int) -> tuple[int, ...]:
    """``(1, 2, 4, ..., max_batch)`` — max_batch itself is always the last
    bucket even when it is not a power of two, so the batcher's largest batch
    always has an exactly-sized executable."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    out, b = [], 1
    while b < max_batch:
        out.append(b)
        b *= 2
    out.append(max_batch)
    return tuple(out)


def pick_bucket(n: int, buckets: Sequence[int]) -> int:
    """Smallest bucket that fits ``n``; oversize falls back to the LARGEST
    bucket (the engine then serves the batch in largest-bucket chunks rather
    than compiling a fresh shape on the request path)."""
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


class MicroBatcher:
    """Bounded FIFO request queue with max-batch/max-wait coalescing."""

    def __init__(
        self,
        max_batch: int = 64,
        max_wait_s: float = 0.002,
        max_queue: int = 256,
        clock: Callable[[], float] = time.monotonic,
        continuous: bool = False,
    ):
        if max_queue < max_batch:
            raise ValueError(
                f"max_queue ({max_queue}) must hold at least one full batch "
                f"({max_batch})"
            )
        self.max_batch = max_batch
        self.max_wait_s = max_wait_s
        self.max_queue = max_queue
        self.clock = clock
        # continuous admission (the ragged engine mode): next_batch returns
        # whatever is queued instead of waiting out the coalescing window.
        # Mutable on purpose — ServeLoop/ReplicaPool sync it from the warmed
        # engine's measured batching mode (the "auto" race resolves at
        # warmup, after the batcher exists).
        self.continuous = bool(continuous)
        self._q: deque[Request] = deque()
        self._lock = lockdep.Lock("MicroBatcher._lock")
        # Wake signal owned by the QUEUE, not any one consumer: a replica
        # pool runs several ServeLoops draining this one batcher, and a
        # submit must be able to wake whichever replica's worker is idle
        # (an Event wakes every waiter; each loop's bounded wait_hint sleep
        # caps the staleness of a racing clear at max_wait_s, exactly the
        # single-loop behavior). Loops wait on this instead of a private
        # event; submit() sets it on every successful enqueue.
        self.wake = threading.Event()

    @property
    def depth(self) -> int:
        with self._lock:  # deque len is GIL-atomic today, but the lock map
            return len(self._q)  # makes the discipline checkable, not lucky

    def submit(self, req: Request, now: float | None = None) -> Overloaded | None:
        """Admit ``req``; returns an :class:`Overloaded` (and does NOT enqueue)
        when the bounded queue is full or the deadline has already passed,
        else ``None``. ``enqueue_ts`` (stamped here, from this batcher's
        clock) is also the request trace's batcher-enqueue boundary — the
        batch_wait/queue_wait phase split (docs/TELEMETRY.md) is computed
        from it at dequeue, so tracing adds NO extra clock read on submit."""
        now = self.clock() if now is None else now
        req.enqueue_ts = now
        if req.deadline is not None and req.deadline <= now:
            return Overloaded(req.rid, DEADLINE_AT_SUBMIT)
        with self._lock:
            if len(self._q) >= self.max_queue:
                return Overloaded(req.rid, QUEUE_FULL)
            self._q.append(req)
        self.wake.set()
        return None

    def next_batch(
        self, now: float | None = None
    ) -> tuple[list[Request], list[tuple[Request, Overloaded]]]:
        """``(ready, shed)``: up to ``max_batch`` requests when the flush
        policy fires (full batch, or oldest aged past ``max_wait_s``), else
        ``[]``. ``shed`` pairs each queued request whose deadline expired
        before it could be batched with its typed ``Overloaded`` result — the
        REQUEST rides along because the caller must still resolve its future
        (a shed whose future never resolves is a client hung forever)."""
        now = self.clock() if now is None else now
        shed: list[tuple[Request, Overloaded]] = []
        with self._lock:
            if self._q:
                live = deque()
                for r in self._q:
                    if r.deadline is not None and r.deadline <= now:
                        shed.append(
                            (r, Overloaded(r.rid, DEADLINE_AT_DEQUEUE, now - r.enqueue_ts))
                        )
                    else:
                        live.append(r)
                self._q = live
            if not self._q:
                return [], shed
            if not self.continuous:
                full = len(self._q) >= self.max_batch
                aged = (now - self._q[0].enqueue_ts) >= self.max_wait_s
                if not (full or aged):
                    return [], shed
            take = min(len(self._q), self.max_batch)
            return [self._q.popleft() for _ in range(take)], shed

    def wait_hint(self, now: float | None = None) -> float:
        """Seconds until the serve loop should next pump: in coalesce mode,
        until the oldest queued request hits ``max_wait_s``; in continuous
        mode, 0 whenever anything is queued (an idle engine must never sleep
        on a non-empty queue — the one race a submit's wake can lose is a
        worker that checked the queue just before the enqueue, and a zero
        hint closes it). ``max_wait_s`` when the queue is empty (the idle
        sleep bound; submits wake the loop sooner)."""
        now = self.clock() if now is None else now
        with self._lock:
            if not self._q:
                return self.max_wait_s
            if self.continuous:
                return 0.0
            return max(0.0, self.max_wait_s - (now - self._q[0].enqueue_ts))
