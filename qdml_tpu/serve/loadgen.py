"""Open-loop Poisson load generator + offline-parity harness.

Open-loop means arrivals are scheduled by the Poisson clock, NOT by response
completion — the generator keeps offering load while requests are in flight,
which is the only traffic model that exposes queue growth, coalescing
behavior, and load shedding (a closed loop self-throttles and can never
overload the server; Schroeder et al., "Open Versus Closed: A Cautionary
Tale").

Each run reports the three acceptance numbers for the serving engine:

- ``compile_cache_after_warmup`` — all-zero iff NO compile happened on the
  request path (the engine resets the counters when warmup ends);
- parity — per-request estimates must match the offline eval forward on the
  same checkpoint bit-for-bit-modulo-fp (same executable family, same
  params; the padded bucket must not change any real row), reported as
  ``parity_max_abs_err`` plus served-vs-offline NMSE in dB;
- tail latency — p50/p95/p99 per-request latency, throughput, batch-fill.

The summary lands in the run's manifest-headed telemetry JSONL as a
``serve_summary`` record, which ``qdml-tpu report`` diffs (rps into the
throughput gate, latency percentiles into the serving-latency section).
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.serve.engine import ServeEngine
from qdml_tpu.serve.metrics import ServeMetrics
from qdml_tpu.serve.server import ServeLoop
from qdml_tpu.serve.types import Prediction
from qdml_tpu.telemetry import span
from qdml_tpu.utils.metrics import nmse_db


def make_request_samples(cfg: ExperimentConfig, n: int) -> dict[str, np.ndarray]:
    """``n`` fresh request samples past the training range (the eval sweep's
    offset convention, Test.py:127) round-robined over the scenario/user grid;
    returns host arrays: ``x`` (pilot images), ``h_perf`` (ground truth),
    ``indicator`` (true scenario)."""
    geom = ChannelGeometry.from_config(cfg.data)
    i = jnp.arange(n)
    scen = i % cfg.data.n_scenarios
    user = (i // cfg.data.n_scenarios) % cfg.data.n_users
    start = cfg.data.data_len * 3
    batch = make_network_batch(
        jnp.uint32(cfg.data.seed), scen, user, start + i,
        jnp.float32(cfg.data.snr_db), geom,
    )
    return {
        "x": np.asarray(batch["yp_img"], np.float32),
        "h_perf": np.asarray(batch["h_perf"], np.float32),
        "indicator": np.asarray(batch["indicator"]),
    }


def run_loadgen(
    cfg: ExperimentConfig,
    engine: ServeEngine,
    rate: float = 200.0,
    n: int = 256,
    seed: int = 0,
    deadline_ms: float | None = None,
    logger=None,
) -> dict:
    """Drive a warmed (or about-to-be-warmed) engine with Poisson traffic.

    Order matters: the offline parity reference compiles BEFORE
    ``engine.warmup()`` re-arms the compile counters, so the request-path
    compile gate measures serving alone.
    """
    samples = make_request_samples(cfg, n)
    x, h_perf = samples["x"], samples["h_perf"]

    with span("loadgen_offline_reference", n=n):
        offline_h, offline_pred = engine.offline_forward(x)
    with span("serve_warmup", buckets=list(engine.buckets)):
        warm = engine.warmup()

    metrics = ServeMetrics(
        sink=None if logger is None else logger.telemetry, log_requests=n <= 2048
    )
    loop = ServeLoop(engine, metrics=metrics).start()
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, n)
    arrivals = np.cumsum(gaps)

    futures = []
    t0 = time.perf_counter()
    with span("loadgen_traffic", rate_rps=rate, n=n):
        for i in range(n):
            lag = t0 + arrivals[i] - time.perf_counter()
            if lag > 0:
                time.sleep(lag)  # open loop: schedule by the Poisson clock only
            futures.append(loop.submit(x[i], rid=i, deadline_ms=deadline_ms))
        # offered window ends when the LAST request was offered — the result
        # drain must not dilute the offered rate, or an overloaded server
        # would look like a slow generator and mask its own overload
        offered_elapsed = time.perf_counter() - t0
        results = [f.result(timeout=60.0) for f in futures]
    loop.stop()
    cache_after = engine.request_path_compiles()
    # End-of-run poll of the live `{"op": "metrics"}` view, folded SLIM: the
    # summary below is already built from the same (merged) collectors, so
    # only the fields the verb adds ride along — worker/queue/bucket state
    # plus `completed` as a cross-check that the verb saw the same window.
    live = loop.live_metrics()
    live_slim = {
        k: live[k] for k in ("workers", "queue_depth_now", "buckets", "completed")
    }

    done = {r.rid: r for r in results if isinstance(r, Prediction)}
    shed = [r for r in results if not isinstance(r, Prediction)]
    parity_max = 0.0
    nmse_served = nmse_offline = None
    pred_agree = None
    if done:
        ids = sorted(done)
        served_h = np.stack([done[i].h for i in ids])
        off_h, off_p = offline_h[ids], offline_pred[ids]
        parity_max = float(np.max(np.abs(served_h - off_h)))
        pred_agree = float(
            np.mean([done[i].scenario == int(off_p[k]) for k, i in enumerate(ids)])
        )
        pow_ = float(np.sum(h_perf[ids] ** 2))
        nmse_served = nmse_db(float(np.sum((served_h - h_perf[ids]) ** 2)) / pow_)
        nmse_offline = nmse_db(float(np.sum((off_h - h_perf[ids]) ** 2)) / pow_)

    import jax

    # aggregate across ALL serve-loop workers (== metrics when workers=1);
    # worker 0's collector alone would undercount a multi-worker loop
    metrics_all = loop.merged_metrics(sink=metrics._sink)
    summary = metrics_all.summary(
        compile_cache=cache_after,
        # labels the record for report's platform-mismatch disarm: a CPU
        # loadgen diffed against a TPU baseline compares hardware, not code
        platform=jax.default_backend(),
        offered_rps=round(n / offered_elapsed, 2),
        target_rps=rate,
        n_requests=n,
        n_shed=len(shed),
        parity_max_abs_err=parity_max,
        pred_agreement=pred_agree,
        nmse_db_served=nmse_served,
        nmse_db_offline=nmse_offline,
        warmup=warm,
        server_metrics=live_slim,
    )
    metrics_all.flush(compile_cache=cache_after, workers=loop.workers)
    if logger is not None:
        logger.telemetry.write_raw(summary)
    return summary
