"""Open-loop load generator (Poisson / bursty MMPP / diurnal trace) +
offline-parity harness with SLO and fleet reporting.

Open-loop means arrivals are scheduled by the arrival-process clock, NOT by
response completion — the generator keeps offering load while requests are in
flight, which is the only traffic model that exposes queue growth, coalescing
behavior, and load shedding (a closed loop self-throttles and can never
overload the server; Schroeder et al., "Open Versus Closed: A Cautionary
Tale"). Three arrival processes (:func:`arrival_times`):

- ``poisson`` — homogeneous, exponential gaps (the PR-2 baseline);
- ``bursty`` — two-state Markov-modulated Poisson (MMPP): exponential dwell
  times alternate a lull state (``rate/burstiness``) with a burst state
  (balanced so the MEAN rate stays ``rate``) — the flash-crowd shape that
  stresses the bounded queue and deadline shedding;
- ``diurnal`` — an inhomogeneous Poisson replay of a compressed day/night
  rate trace (sinusoidal, peak/trough set by ``burstiness``) via thinning —
  the million-user traffic envelope at test-run scale.

Each run reports the acceptance numbers for the serving engine:

- ``compile_cache_after_warmup`` — all-zero iff NO compile happened on the
  request path (the engine snapshots the counters when warmup ends);
- parity — per-request estimates must match the offline eval forward on the
  same checkpoint bit-for-bit-modulo-fp, reported as ``parity_max_abs_err``
  plus served-vs-offline NMSE in dB;
- tail latency + SLO — p50/p95/p99 per-request latency, throughput, batch
  fill, and (when deadlines are offered) the SLO-attainment fraction;
- goodput — useful-rows/s (``goodput_rps``) and the padding-waste fraction
  (dispatched rows XLA computed for nothing), identical columns in bucket
  and ragged batching modes so the committed bucket-vs-ragged dryrun
  (``results/serve_ragged/``) compares apples to apples;
- fleet — replica count, total workers, mesh topology and per-bucket batch
  sharding, so ``qdml-tpu report`` can gate fleet-level rps / p99 / SLO.

The summary lands in the run's manifest-headed telemetry JSONL as a
``serve_summary`` record, which ``qdml-tpu report`` diffs (rps into the
throughput gate, latency percentiles into the serving-latency section, SLO
attainment into the serving-SLO gate).
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time
from concurrent.futures import TimeoutError as FuturesTimeout

import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.data.channels import ChannelGeometry
from qdml_tpu.data.datasets import make_network_batch
from qdml_tpu.serve.engine import ServeEngine
from qdml_tpu.serve.metrics import ServeMetrics
from qdml_tpu.serve.server import ReplicaPool
from qdml_tpu.serve.types import Prediction
from qdml_tpu.telemetry import span
from qdml_tpu.telemetry.tracing import TraceContext
from qdml_tpu.utils.metrics import nmse_db

ARRIVAL_PROCESSES = ("poisson", "bursty", "diurnal")


def arrival_times(
    n: int,
    rate: float,
    rng: np.random.Generator,
    process: str = "poisson",
    burstiness: float = 4.0,
    period_s: float | None = None,
) -> np.ndarray:
    """``n`` increasing arrival offsets (seconds from t0) with mean rate
    ``rate`` under the named process.

    ``bursty``: two-state MMPP. The lull state offers ``rate/burstiness``;
    the burst state offers ``2*rate - rate/burstiness`` so equal expected
    dwell in each state preserves the mean. Dwells are exponential with mean
    ~20 arrivals, so a run of a few hundred requests sees several
    burst/lull cycles.

    ``diurnal``: inhomogeneous Poisson via thinning against the peak rate of
    a sinusoidal day trace ``rate * (1 + depth*sin(2*pi*t/period))`` with
    ``depth = 1 - 1/burstiness`` (burstiness 4 -> peak/trough ratio 7); the
    ``period_s`` default compresses ~2 "days" into the run.
    """
    if process not in ARRIVAL_PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r} (have {ARRIVAL_PROCESSES})"
        )
    if rate <= 0 or n < 1:
        raise ValueError(f"need rate > 0 and n >= 1, got rate={rate}, n={n}")
    if process == "poisson":
        return np.cumsum(rng.exponential(1.0 / rate, n))
    b = max(1.0, float(burstiness))
    if process == "bursty":
        r_lull = rate / b
        r_burst = 2.0 * rate - r_lull  # equal dwell -> mean stays `rate`
        dwell_mean = 20.0 / rate  # ~20 arrivals per state visit
        rates = (r_lull, r_burst)
        state = int(rng.integers(2))
        t, next_switch = 0.0, float(rng.exponential(dwell_mean))
        out = np.empty(n)
        for i in range(n):
            while True:
                gap = float(rng.exponential(1.0 / rates[state]))
                if t + gap < next_switch:
                    t += gap
                    break
                # no arrival before the state flips: a gap drawn at the old
                # rate must not overrun the new dwell (lull-rate gaps would
                # swallow whole bursts and drag the realized mean under
                # `rate`) — truncate at the switch and resample at the new
                # state's rate; exponentials are memoryless, so this is the
                # exact MMPP law, not an approximation
                t = next_switch
                state ^= 1
                next_switch = t + float(rng.exponential(dwell_mean))
            out[i] = t
        return out
    # diurnal: thinning at the trace's peak rate
    depth = 1.0 - 1.0 / b
    period = float(period_s) if period_s else max(n / rate / 2.0, 1e-3)
    r_max = rate * (1.0 + depth)
    out = np.empty(n)
    t, i = 0.0, 0
    while i < n:
        t += float(rng.exponential(1.0 / r_max))
        r_t = rate * (1.0 + depth * np.sin(2.0 * np.pi * t / period))
        if rng.uniform() * r_max <= r_t:
            out[i] = t
            i += 1
    return out


def make_request_samples(
    cfg: ExperimentConfig,
    n: int,
    drift_at: int | None = None,
    drift_step: int = 0,
    drift_scenario: int = 0,
) -> dict[str, np.ndarray]:
    """``n`` fresh request samples past the training range (the eval sweep's
    offset convention, Test.py:127) round-robined over the scenario/user grid;
    returns host arrays: ``x`` (pilot images), ``h_perf`` (ground truth),
    ``indicator`` (true scenario).

    Drift injection (``drift_at``/``serve.drift_step``, docs/CONTROL.md):
    requests from index ``drift_at`` onward come from the DRIFTED channel
    family table (``family_table`` at ``drift_step``, ``drift_scenario``
    perturbed) with the offered scenario mix shifted toward the drifting
    family (every other post-drift request is drawn from it) — the traffic
    the fleet controller's detectors must notice mid-run. ``drift_at=0``
    makes the whole stream drifted; ``None`` (or ``drift_step=0``) is the
    stationary PR-2 stream, bit-identical to before the knob existed."""
    geom = ChannelGeometry.from_config(cfg.data)
    i = jnp.arange(n)
    scen = i % cfg.data.n_scenarios
    user = (i // cfg.data.n_scenarios) % cfg.data.n_users
    start = cfg.data.data_len * 3

    def _gen(geom_, scen_, user_, idx_):
        batch = make_network_batch(
            jnp.uint32(cfg.data.seed), scen_, user_, idx_,
            jnp.float32(cfg.data.snr_db), geom_,
        )
        return (
            np.asarray(batch["yp_img"], np.float32),
            np.asarray(batch["h_perf"], np.float32),
            np.asarray(batch["indicator"]),
        )

    if drift_at is None or drift_step <= 0 or drift_at >= n:
        x, h_perf, ind = _gen(geom, scen, user, start + i)
        return {"x": x, "h_perf": h_perf, "indicator": ind}
    if not (0 <= drift_scenario < cfg.data.n_scenarios):
        raise ValueError(
            f"drift_scenario must be a scenario id < {cfg.data.n_scenarios}, "
            f"got {drift_scenario}"
        )
    import dataclasses

    k = max(0, int(drift_at))
    geom_d = dataclasses.replace(
        geom, drift_step=int(drift_step), drift_scenario=int(drift_scenario)
    )
    # post-drift mix: every other request from the drifting family, the rest
    # keep the round-robin — the scenario-mix shift rides along with the
    # channel-statistics drift
    j = i[k:]
    scen_d = jnp.where((j - k) % 2 == 0, drift_scenario, scen[k:])
    parts = [_gen(geom, scen[:k], user[:k], start + i[:k])] if k else []
    parts.append(_gen(geom_d, scen_d, user[k:], start + j))
    x, h_perf, ind = (np.concatenate(cols) for cols in zip(*parts))
    return {"x": x, "h_perf": h_perf, "indicator": ind}


def _trace_reconciliation(pairs: list[tuple[float, float]]) -> dict | None:
    """Phase-sum vs end-to-end reconciliation over traced requests: ``pairs``
    of (observed total, sum of reported phase durations), each element
    measured on ONE clock (the total on the observer's clock, the phases as
    durations on their own producers' clocks — durations compare across
    hosts; timestamps never do, docs/TELEMETRY.md clock-skew rule). The
    ``unattributed`` residual is stack/scheduling time no phase claims —
    honest, never re-labeled as wire."""
    if not pairs:
        return None
    n = len(pairs)
    tot = sum(t for t, _ in pairs)
    ph = sum(p for _, p in pairs)
    return {
        "n": n,
        "mean_latency_ms": round(tot / n * 1e3, 3),
        "mean_phase_sum_ms": round(ph / n * 1e3, 3),
        "mean_unattributed_ms": round((tot - ph) / n * 1e3, 3),
        "attributed_fraction": round(ph / tot, 4) if tot > 0 else None,
    }


def _window_stats(
    ids: list[int],
    done: dict,
    offline_h: np.ndarray,
    offline_pred: np.ndarray,
    h_perf: np.ndarray,
    indicator: np.ndarray,
    drift_scenario: int | None = None,
) -> dict | None:
    """Parity/NMSE/confidence stats over one id window of completed results —
    the per-phase view the drift story needs (pre- vs post-drift vs
    recovered), same math as the run-level figures."""
    ids = [i for i in ids if i in done]
    if not ids:
        return None
    served_h = np.stack([done[i].h for i in ids])
    off_h, off_p = offline_h[ids], offline_pred[ids]
    pow_ = float(np.sum(h_perf[ids] ** 2))
    confs = [done[i].confidence for i in ids if done[i].confidence is not None]
    out = {
        "n": len(ids),
        "parity_max_abs_err": float(np.max(np.abs(served_h - off_h))),
        "pred_agreement": float(
            np.mean([done[i].scenario == int(off_p[k]) for k, i in enumerate(ids)])
        ),
        "nmse_db_served": nmse_db(
            float(np.sum((served_h - h_perf[ids]) ** 2)) / pow_
        ),
        "nmse_db_offline": nmse_db(float(np.sum((off_h - h_perf[ids]) ** 2)) / pow_),
        "conf_mean": round(float(np.mean(confs)), 4) if confs else None,
    }
    if drift_scenario is not None:
        # the drifting family's own served NMSE (rows by TRUE scenario): the
        # number the fine-tune must move and the canary must not regress
        rows = [k for k, i in enumerate(ids) if int(indicator[i]) == drift_scenario]
        if rows:
            pw = float(np.sum(h_perf[np.asarray(ids)[rows]] ** 2))
            out["nmse_db_drift_scenario"] = nmse_db(
                float(np.sum((served_h[rows] - h_perf[np.asarray(ids)[rows]]) ** 2))
                / pw
            )
    return out


def run_loadgen(
    cfg: ExperimentConfig,
    engine: ServeEngine,
    rate: float = 200.0,
    n: int = 256,
    seed: int = 0,
    deadline_ms: float | None = None,
    logger=None,
    process: str | None = None,
    replicas: int | None = None,
    pool: ReplicaPool | None = None,
    drift_at: int | None = None,
) -> dict:
    """Drive a warmed (or about-to-be-warmed) engine with open-loop traffic.

    Order matters: the offline parity reference compiles BEFORE
    ``engine.warmup()`` re-arms the compile counters, so the request-path
    compile gate measures serving alone. ``process`` selects the arrival
    process (default ``cfg.serve.arrival``); ``replicas`` sizes the
    :class:`~qdml_tpu.serve.server.ReplicaPool` (default
    ``cfg.serve.replicas``) — every replica shares the one warmup and one
    batcher feed, and the summary merges every replica's metrics exactly.

    ``drift_at`` injects mid-run channel-family drift from the traffic side
    (``serve.drift_step``/``serve.drift_scenario`` shape it; docs/CONTROL.md):
    requests from that index onward come from the drifted family table with
    the scenario mix shifted toward the drifting family, and the summary
    grows a ``windows`` block (pre/post-drift parity, NMSE and confidence)
    plus a ``drift`` fact block.

    ``pool`` attaches to an EXISTING (started) replica pool instead of
    creating one — the fleet-controller harness, where the controller is
    polling the same pool's live metrics while traffic runs. In that mode
    the engine is already warm, so the per-request summary stats are rebuilt
    from this run's results alone (the pool's own collectors span its whole
    lifetime), the compile gate is the counter delta across the traffic
    window only, and the caller keeps ownership of the pool (no stop)."""
    process = process or cfg.serve.arrival
    if process not in ARRIVAL_PROCESSES:
        # fail on the config typo BEFORE the restore/parity-compile/warmup
        # minutes are spent (arrival_times would only catch it after)
        raise ValueError(
            f"unknown arrival process {process!r} (have {ARRIVAL_PROCESSES})"
        )
    drift_step = int(cfg.serve.drift_step)
    drift_scen = int(cfg.serve.drift_scenario)
    drifting = drift_at is not None and drift_step > 0
    samples = make_request_samples(
        cfg, n,
        drift_at=drift_at if drifting else None,
        drift_step=drift_step, drift_scenario=drift_scen,
    )
    x, h_perf = samples["x"], samples["h_perf"]

    from qdml_tpu.utils.compile_cache import compile_cache_stats

    external_pool = pool is not None
    with span("loadgen_offline_reference", n=n):
        offline_h, offline_pred, _offline_conf = engine.offline_forward(x)
    if external_pool:
        if not engine._compiled:
            raise ValueError("run_loadgen(pool=...) requires a started (warmed) pool")
        warm = None
        # the offline-reference compile above happened AFTER this engine's
        # warmup, so the engine-level since-warmup delta can no longer prove
        # anything: gate the TRAFFIC WINDOW instead (snapshot here, diff
        # after the drain)
        cache_before = compile_cache_stats()
    else:
        with span("serve_warmup", buckets=list(engine.buckets)):
            warm = engine.warmup()

    sink = None if logger is None else logger.telemetry
    if not external_pool:
        pool = ReplicaPool(
            engine, replicas=replicas, sink=sink, log_requests=n <= 2048
        ).start()
    rng = np.random.default_rng(seed)
    arrivals = arrival_times(
        n, rate, rng, process=process, burstiness=cfg.serve.burstiness
    )

    futures = []
    t0 = time.perf_counter()
    with span("loadgen_traffic", rate_rps=rate, n=n, process=process):
        for i in range(n):
            lag = t0 + arrivals[i] - time.perf_counter()
            if lag > 0:
                time.sleep(lag)  # open loop: schedule by the arrival clock only
            futures.append(pool.submit(x[i], rid=i, deadline_ms=deadline_ms))
        # offered window ends when the LAST request was offered — the result
        # drain must not dilute the offered rate, or an overloaded server
        # would look like a slow generator and mask its own overload
        offered_elapsed = time.perf_counter() - t0
        # Resolution accounting is part of the measurement (docs/RESILIENCE.md):
        # a future that RESOLVES with a failure is a typed error the client
        # saw (failed_requests); a future that never resolves is a STRANDED
        # client — the invariant the serving stack promises never to break,
        # and the always-armed report gate (serve.stranded_futures == 0)
        # checks. Neither aborts the measurement.
        results = []
        stranded = 0
        failed = 0
        for f in futures:
            try:
                results.append(f.result(timeout=60.0))
            except FuturesTimeout:
                stranded += 1
            except Exception:  # lint: disable=broad-except(a worker-forwarded failure can be ANY engine/chaos exception type — the measurement's job is to COUNT the typed closure the client saw and keep measuring, not to die on the first injected fault)
                failed += 1
    if external_pool:
        cache_after = {
            k: max(0, v - cache_before.get(k, 0))
            for k, v in compile_cache_stats().items()
        }
    else:
        pool.stop()
        cache_after = engine.request_path_compiles()
    # End-of-run poll of the live `{"op": "metrics"}` view, folded SLIM: the
    # summary below is already built from the same (merged) collectors, so
    # only the fields the verb adds ride along — replica/queue/bucket state
    # plus `completed` as a cross-check that the verb saw the same window,
    # plus the verb's trace/phase decomposition so every committed window
    # carries it without a second round-trip (docs/TELEMETRY.md).
    live = pool.live_metrics()
    live_slim = {
        k: live.get(k)
        for k in (
            "workers", "replicas", "replica_completed",
            "queue_depth_now", "buckets", "completed", "swap_epoch",
            "phases", "trace",
        )
    }

    done = {r.rid: r for r in results if isinstance(r, Prediction)}
    shed = [r for r in results if not isinstance(r, Prediction)]
    parity_max = 0.0
    nmse_served = nmse_offline = None
    pred_agree = None
    if done:
        ids = sorted(done)
        served_h = np.stack([done[i].h for i in ids])
        off_h, off_p = offline_h[ids], offline_pred[ids]
        parity_max = float(np.max(np.abs(served_h - off_h)))
        pred_agree = float(
            np.mean([done[i].scenario == int(off_p[k]) for k, i in enumerate(ids)])
        )
        pow_ = float(np.sum(h_perf[ids] ** 2))
        nmse_served = nmse_db(float(np.sum((served_h - h_perf[ids]) ** 2)) / pow_)
        nmse_offline = nmse_db(float(np.sum((off_h - h_perf[ids]) ** 2)) / pow_)

    import jax

    if external_pool:
        # this RUN's window only: the pool's collectors span its whole
        # lifetime (other runs, controller probes), so replay the results
        # into a fresh collector — latency/SLO/scenario stats exact, batch
        # fill/queue depth unknowable here and reported null
        metrics_all = ServeMetrics(sink=sink, log_requests=False)
        metrics_all._t0 = t0
        for r in results:
            if isinstance(r, Prediction):
                metrics_all.observe_prediction(r)
            else:
                metrics_all.observe_shed(r, had_deadline=deadline_ms is not None)
        metrics_all.completed = len(done)
        # goodput is exact from results alone (observe_prediction counted the
        # useful rows); the executable-side row ledger is not — rows_dispatched
        # stays 0, so padding_waste reports None, never a fabricated perfect
        # fill
    else:
        # aggregate across every replica's every worker (== the single loop's
        # metrics when replicas=workers=1); any one collector alone would
        # undercount the pool
        metrics_all = pool.merged_metrics(sink=sink)
    summary = metrics_all.summary(
        compile_cache=cache_after,
        # labels the record for report's platform-mismatch disarm: a CPU
        # loadgen diffed against a TPU baseline compares hardware, not code
        platform=jax.default_backend(),
        offered_rps=round(n / offered_elapsed, 2),
        target_rps=rate,
        n_requests=n,
        n_shed=len(shed),
        # resilience accounting (docs/RESILIENCE.md): a stranded future is a
        # client hung forever — the always-armed report gate requires 0;
        # failed_requests resolved WITH a typed error (clients saw closure)
        stranded_futures=stranded,
        failed_requests=failed,
        breaker=None if pool.breaker is None else pool.breaker.summary(),
        arrival={"process": process, "burstiness": cfg.serve.burstiness},
        deadline_ms=deadline_ms,
        parity_max_abs_err=parity_max,
        pred_agreement=pred_agree,
        nmse_db_served=nmse_served,
        nmse_db_offline=nmse_offline,
        # fleet facts for the report gate: aggregate rps is the `rps` field
        # above; topology makes "scaled out" vs "sped up" attributable
        replicas=pool.n_replicas,
        workers=pool.workers,
        mesh=engine.mesh_topology(),
        # scenario scale-out facts: how many expert families this fleet
        # serves, which routing dispatch the race baked into the buckets,
        # and the observed sparse overflow-fallback rate (the report gates
        # a rate regression — a capacity factor sized for yesterday's
        # traffic skew is a silent O(S) compute leak)
        n_scenarios=cfg.data.n_scenarios,
        dispatch=engine.dispatch_summary(),
        # batching facts for the report gate and the bucket-vs-ragged dryrun:
        # which mode each capacity tier serves (measured or forced) and
        # whether the feed admitted continuously — serve_summary.fleet's
        # batching_mode per tier
        batching=engine.batching_summary(),
        bucket_sharding=engine.bucket_sharding or None,
        warmup=warm,
        server_metrics=live_slim,
    )
    if drifting:
        summary["drift"] = {
            "at": int(drift_at),
            "step": drift_step,
            "scenario": drift_scen,
        }
        # chunked sub-windows ride along so a controller harness can replay
        # the run as a SEQUENCE of windowed measurements (the nmse_parity
        # drift detector consumes windows, not one aggregate)
        chunk = max(24, n // 12)
        chunks = []
        for lo in range(0, n, chunk):
            st = _window_stats(
                list(range(lo, min(lo + chunk, n))), done, offline_h,
                offline_pred, h_perf, samples["indicator"],
                drift_scenario=drift_scen,
            )
            if st is not None:
                st["start"] = lo
                st["pre_drift"] = lo + chunk <= int(drift_at)
                chunks.append(st)
        summary["windows"] = {
            "pre_drift": _window_stats(
                list(range(int(drift_at))), done, offline_h, offline_pred,
                h_perf, samples["indicator"], drift_scenario=drift_scen,
            ),
            "post_drift": _window_stats(
                list(range(int(drift_at), n)), done, offline_h, offline_pred,
                h_perf, samples["indicator"], drift_scenario=drift_scen,
            ),
            "chunks": chunks,
        }
    if summary.get("rps") is not None and pool.n_replicas:
        summary["rps_per_replica"] = round(summary["rps"] / pool.n_replicas, 2)
    if summary.get("trace"):
        # phase sums vs the same requests' end-to-end latencies (both on the
        # batcher clock here — the in-process path is single-clock by
        # construction): the dryrun's reconciliation gate reads this
        summary["trace"]["reconciliation"] = _trace_reconciliation(
            [
                (r.latency_s, r.trace.phase_sum_s())
                for r in results
                if isinstance(r, Prediction) and r.trace is not None
            ]
        )
    metrics_all.flush(
        compile_cache=cache_after, workers=pool.workers, replicas=pool.n_replicas
    )
    if logger is not None:
        logger.telemetry.write_raw(summary)
    return summary


def run_loadgen_socket(
    cfg: ExperimentConfig,
    address: tuple[str, int],
    rate: float = 200.0,
    n: int = 256,
    seed: int = 0,
    deadline_ms: float | None = None,
    logger=None,
    process: str | None = None,
    clients: int = 8,
    timeout_s: float = 30.0,
    retries: int = 3,
    x: np.ndarray | None = None,
) -> dict:
    """Open-loop traffic over the SOCKET protocol against a running server.

    The wire twin of :func:`run_loadgen` (which drives an in-process pool):
    a pool of ``clients`` :class:`~qdml_tpu.serve.client.ServeClient`
    connections offers requests on the arrival-process clock, each exchange
    carrying the full retry discipline — per-request timeouts, deadline
    propagation, reconnect-with-jittered-backoff on transient resets — so a
    mid-run ``ECONNRESET``/``BrokenPipeError`` (a restarting server, a
    chaos-injected drop) is RECORDED (``reconnects``/``retries`` in the
    summary) instead of aborting the measurement, and a retried id never
    double-dispatches (server-side dedup).

    Writes a ``serve_summary``-shaped record (latency measured client-side,
    wire-to-wire; sheds from typed replies; SLO from the offered deadline;
    ``server_metrics`` from an end-of-run ``{"op": "metrics"}`` poll, which
    also carries the server's compile gate, faults/restarts and breaker
    state). ``x`` overrides the request samples (the chaos harness reuses
    one set across phases so per-phase NMSE windows are comparable).

    Pointed at a fleet ROUTER (docs/FLEET.md) the endpoint's metrics verb
    returns the aggregated fleet view, and the summary reports per-backend
    AND merged rows instead of one blended blob: the merged counters are the
    router's exact sums (the per-replica merge discipline, one tier up), and
    ``server_metrics.per_backend`` / the top-level ``router`` block keep
    every host's own completed/latency/compile-gate row attributable."""
    process = process or cfg.serve.arrival
    if process not in ARRIVAL_PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r} (have {ARRIVAL_PROCESSES})"
        )
    from concurrent.futures import ThreadPoolExecutor

    from qdml_tpu.serve.client import ServeClient, ServeClientError

    if x is None:
        x = make_request_samples(cfg, n)["x"]
    host, port = address
    pool = [
        ServeClient(
            host, port, timeout_s=timeout_s, retries=retries, seed=seed + i
        )
        for i in range(max(1, int(clients)))
    ]
    rng = np.random.default_rng(seed)
    arrivals = arrival_times(
        n, rate, rng, process=process, burstiness=cfg.serve.burstiness
    )
    metrics = ServeMetrics(
        sink=None if logger is None else logger.telemetry, log_requests=False
    )
    # ONE collector shared by every client thread: ServeMetrics is
    # single-thread by contract (the serve loop gives each worker its own),
    # so the harness serializes its bookkeeping — read-modify-write counter
    # interleavings would silently undercount the very numbers the chaos
    # gates read (SLO rows, sheds)
    mlock = lockdep.Lock("loadgen:mlock")
    shed_counts: dict[str, int] = {}
    give_ups = 0
    replies: list[dict | None] = [None] * n
    # (client wall, reported phase-duration sum) per traced reply — the
    # reconciliation input; wall is THIS clock, phases are durations, no
    # cross-host timestamp ever differenced
    trace_pairs: list[tuple[float, float]] = []

    def _one(i: int) -> None:
        client = pool[i % len(pool)]
        t_req = time.perf_counter()
        try:
            rep = client.request(
                x[i], rid=f"lg{seed}-{i}", deadline_ms=deadline_ms
            )
        except ServeClientError:
            # counted via the client's give_ups ledger; a give-up under an
            # offered deadline is an SLO miss (the client never got a usable
            # answer within its budget)
            if deadline_ms is not None:
                with mlock:
                    metrics.slo_total += 1
            return
        replies[i] = rep
        wall = time.perf_counter() - t_req
        if rep.get("ok"):
            # a traced reply's phase spans fold into the client-side phase
            # histograms RAW (exact quantiles live harness-side), and its
            # wall/phase-sum pair feeds the reconciliation fact
            tr = TraceContext.from_wire(rep.get("trace"))
            p = Prediction(
                rid=rep.get("id"),
                h=np.asarray(rep.get("h", ()), np.float32),
                scenario=int(rep.get("pred", -1)),
                latency_s=wall,
                bucket=int(rep.get("bucket", 0)),
                batch_n=0,
                deadline_met=(
                    None if deadline_ms is None else wall * 1e3 <= deadline_ms
                ),
                confidence=None,
                trace=tr,
            )
            with mlock:
                metrics.observe_prediction(p)
                if tr is not None:
                    trace_pairs.append((wall, tr.phase_sum_s()))
        else:
            reason = str(rep.get("reason", "error"))
            with mlock:
                shed_counts[reason] = shed_counts.get(reason, 0) + 1
                if deadline_ms is not None:
                    metrics.slo_total += 1  # typed rejection under an SLO = a miss

    t0 = time.perf_counter()
    with span("loadgen_socket_traffic", rate_rps=rate, n=n, process=process):
        with ThreadPoolExecutor(max_workers=len(pool)) as ex:
            jobs = []
            for i in range(n):
                lag = t0 + arrivals[i] - time.perf_counter()
                if lag > 0:
                    time.sleep(lag)
                jobs.append(ex.submit(_one, i))
            offered_elapsed = time.perf_counter() - t0
            stranded = 0
            for j in jobs:
                try:
                    j.result(timeout=timeout_s * (retries + 2))
                except FuturesTimeout:
                    stranded += 1  # a client call that never returned at all
    give_ups = sum(c.give_ups for c in pool)
    server_metrics = None
    try:
        server_metrics = pool[0].metrics().get("metrics")
    except (ServeClientError, ConnectionError, OSError):
        pass  # end-of-run observability poll is best-effort
    for c in pool:
        c.close_connection()

    import jax

    metrics.completed = sum(1 for r in replies if r is not None and r.get("ok"))
    metrics.shed = dict(shed_counts)
    metrics._t0 = t0
    summary = metrics.summary(
        compile_cache=(server_metrics or {}).get("compile_cache_after_warmup"),
        platform=jax.default_backend(),
        transport="socket",
        offered_rps=round(n / offered_elapsed, 2),
        target_rps=rate,
        n_requests=n,
        n_shed=sum(shed_counts.values()),
        stranded_futures=stranded,
        give_ups=give_ups,
        # deadline-exhausted give-ups are typed SLO misses (the client
        # honored its budget); the DIFFERENCE — retries exhausted against a
        # live server — is the resilience signal the chaos checks gate on
        deadline_give_ups=sum(c.deadline_give_ups for c in pool),
        # the resilience ledger the reconnect-instead-of-abort bugfix exists
        # to report: transient resets during the window, retries spent
        reconnects=sum(c.reconnects for c in pool),
        retries=sum(c.retries_used for c in pool),
        clients=len(pool),
        arrival={"process": process, "burstiness": cfg.serve.burstiness},
        deadline_ms=deadline_ms,
        # lifted from the server poll so the report's breaker gate reads
        # socket summaries exactly like in-process ones
        breaker=(server_metrics or {}).get("breaker"),
        server_metrics=(
            None
            if server_metrics is None
            else {
                k: server_metrics.get(k)
                for k in (
                    "workers", "replicas", "replica_completed", "queue_depth_now",
                    "buckets", "completed", "swap_epoch", "faults", "restarts",
                    "breaker",
                    # the server/fleet-side trace decomposition rides the
                    # SAME end-of-run poll — no second verb round-trip per
                    # committed window (docs/TELEMETRY.md)
                    "phases", "trace",
                )
                # fleet-router poll: the per-host rows and the router's own
                # ledger ride along with the merged counters — never a
                # blended blob (docs/FLEET.md)
            } | (
                {
                    k: server_metrics.get(k)
                    for k in ("fleet", "backends_polled", "per_backend")
                }
                if server_metrics.get("fleet")
                else {}
            )
        ),
        **(
            {"router": (server_metrics or {}).get("router")}
            if (server_metrics or {}).get("router")
            else {}
        ),
    )
    if summary.get("trace"):
        summary["trace"]["reconciliation"] = _trace_reconciliation(trace_pairs)
    if logger is not None:
        logger.telemetry.write_raw(summary)
    return summary
