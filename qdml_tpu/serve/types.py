"""Typed request/result records for the serving engine.

A request is one pilot observation (``x`` of shape ``(n_sub, n_beam, 2)``)
asking for its channel estimate. It resolves to exactly one of two typed
results: a :class:`Prediction` (the routed HDCE estimate plus the predicted
scenario) or an :class:`Overloaded` (the engine shed it — bounded queue full
or deadline passed). Overload is a *result*, not an exception: under open-loop
traffic the callers that must react to shedding are the very ones that would
lose an exception raised on the server's worker thread.
"""

from __future__ import annotations

from concurrent.futures import Future
from dataclasses import dataclass

import numpy as np

from qdml_tpu.telemetry.tracing import TraceContext

# Overload reasons (the complete set; reasons are part of the wire contract)
QUEUE_FULL = "queue_full"          # bounded queue at capacity on submit
DEADLINE_AT_SUBMIT = "deadline_at_submit"    # deadline already past on admission
DEADLINE_AT_DEQUEUE = "deadline_at_dequeue"  # expired while queued
SHUTDOWN = "shutdown"              # server stopping (or its worker died)
BREAKER_OPEN = "breaker_open"      # circuit breaker browning out new submits


@dataclass
class Request:
    """One in-flight inference request."""

    rid: int | str
    x: np.ndarray                     # (n_sub, n_beam, 2) float32 pilot image
    enqueue_ts: float = 0.0           # monotonic seconds, stamped on submit
    deadline: float | None = None     # absolute monotonic seconds; None = no deadline
    future: Future | None = None      # resolved with Prediction | Overloaded
    # Sampled phase-trace context (telemetry/tracing.py): None for the
    # untraced default — the overhead-free contract is that no stamp, no
    # clock call and no allocation happens for a request with trace=None.
    # ``enqueue_ts`` above doubles as the trace's batcher-enqueue boundary.
    trace: TraceContext | None = None


@dataclass
class DispatchInfo:
    """Accounting for one :meth:`ServeEngine.infer` call — what was actually
    dispatched to XLA, not just what the caller asked for. ``rows`` is the
    total STATIC rows across every executable launch the call made (one per
    chunk for oversize batches), so ``n / rows`` is the honest fill and
    ``rows - n`` the honest pad waste even when an oversize batch is served
    in largest-bucket chunks whose final chunk is near-empty (the PR-2..10
    accounting recorded ``n / largest_bucket`` there, inflating fill past
    1.0). ``ServeMetrics.observe_batch`` consumes this record directly."""

    bucket: int          # static batch shape dispatched (largest tier, if chunked)
    n: int               # valid (real) rows served
    rows: int            # total static rows dispatched across all chunks
    chunks: int = 1      # executable launches this call made
    mode: str = "bucket"  # tier batching mode ("bucket"|"ragged"; "mixed" across chunks)
    # Host-measured phase durations for TRACED batches (summed over chunks):
    # compute = executable call + device fence, fetch = device->host reply
    # copy. None on the untraced fast path — infer stamps no clock unless the
    # serve loop asked for a traced dispatch (docs/TELEMETRY.md).
    compute_s: float | None = None
    fetch_s: float | None = None

    @property
    def fill(self) -> float:
        return self.n / self.rows if self.rows else 0.0

    @property
    def padded(self) -> int:
        return self.rows - self.n


@dataclass
class Prediction:
    """Successful result: routed channel estimate + predicted scenario."""

    rid: int | str
    h: np.ndarray                     # (2 * h_dim,) float32 packed re/im estimate
    scenario: int                     # predicted expert id (argmax of classifier)
    latency_s: float                  # enqueue -> result, monotonic
    bucket: int                       # padded batch bucket that served it
    batch_n: int                      # real (unpadded) requests in that batch
    # SLO accounting: True/False when the request carried a deadline
    # (completed before/after it), None when it had none. Feeds the
    # serve_summary slo-attainment figure (ServeMetrics).
    deadline_met: bool | None = None
    # Classifier confidence: probability of the routed class (exp of the max
    # log-prob), None when the serving path predates the stat. Feeds the
    # per-scenario confidence histogram the drift detectors watch
    # (docs/CONTROL.md).
    confidence: float | None = None
    # The request's sampled phase trace (telemetry/tracing.py), closed at
    # future resolution — ServeMetrics folds its phases into the per-phase
    # histograms and the socket reply carries it as the optional ``trace``
    # field. None for untraced requests (the overwhelming default).
    trace: TraceContext | None = None

    @property
    def ok(self) -> bool:
        return True


@dataclass
class Overloaded:
    """Typed load-shedding result (bounded queue / deadline admission)."""

    rid: int | str
    reason: str                       # QUEUE_FULL | DEADLINE_* | SHUTDOWN | BREAKER_OPEN
    latency_s: float = 0.0            # time spent queued before shedding

    @property
    def ok(self) -> bool:
        return False
