"""Serving engine: fused HDCE inference, bucketed AOT warmup, zero request-path
compiles — sharded over the mesh, hot-swappable under live traffic.

The online pipeline is the eval sweep's forward (``eval/sweep.py``) stripped
to its serving core: scenario classifier -> argmax -> expert trunks + shared
``FCP128`` head -> top-1 route, one jitted function end to end with no host
round trip. HOW the experts run is the measured dispatcher's per-bucket
choice (``ops/dispatch_autotune.py``): dense (all trunks on the batch +
:func:`~qdml_tpu.ops.routing.select_expert` gather — the S=3 winner) or
capacity-bucketed sparse (:func:`~qdml_tpu.ops.routing.sparse_dispatch` —
only the chosen trunk per bucket, the S≫3 winner; overflow rows fall back to
the dense gather in-program, never dropped).

Compilation is amortized entirely into :meth:`ServeEngine.warmup` (the
Qandle gate-matrix-caching argument applied to XLA executables): every batch
bucket is AOT-compiled via ``jit(...).lower(...).compile()`` and executed
once, then the compile-cache counters are SNAPSHOT — a request-path compile
would advance ``compile_cache_stats()`` past the snapshot, and
:meth:`request_path_compiles` exposes exactly that delta as the "warmup
actually covered the request path" gate (a snapshot, not a global reset:
the counters are process-wide and other telemetry consumers — StepClock,
bench — must keep seeing the run's true totals). The request path itself
calls pre-compiled executables only; an un-warmed shape raises instead of
silently tracing.

Sharding (``parallel/mesh.serve_mesh``): with a mesh, every bucket executable
is lowered with explicit ``NamedSharding`` in_shardings — the batch axis
data-parallel over ``data`` (buckets the data-axis size does not divide stay
replicated; the executable is still one SPMD program), params replicated,
and with ``serve.expert_sharding`` the stacked per-scenario trunks sharded
over ``fed`` (the federated placement rules, ``parallel/federated.py``, so
serve- and eval-time expert layouts cannot drift). The sharding is BAKED
into each compiled executable exactly like the autotuned circuit impl, and
the zero-request-path-compiles pin is unchanged.

Hot-swap (:meth:`swap_params`): checkpoints restore eval-only and shapes are
fixed, so new params ``device_put`` with the LIVE shardings slot into the
existing executables with zero recompiles (pinned via the compile-cache
counters). The live param tuple swaps atomically under ``_swap_lock``
between batches; in-flight batches keep the old committed arrays (XLA holds
the buffers until their dispatches retire), so no request ever sees a torn
checkpoint.

Padding & batching modes: batches pad with zeros up to the tier's static
shape and outputs are sliced back to the real count. HOW the tier's program
treats the pad tail is the third measured-dispatch choice
(``serve.batching``, ``serve/batching_autotune.py``):

- **bucket** (the PR-2..10 incumbent): the plain program — pad rows are inert
  because every per-sample op in the pipeline (convs, eval-mode BatchNorm
  over running stats, dense heads, the routing gather) is row-independent;
  the "mask" is the valid-count slice, and the batcher coalesces to bucket
  edges (full batch or max_wait).
- **ragged**: the program takes the valid-row count as a TRACED scalar and
  masks the pad tail inert INSIDE the executable (garbage in pad rows
  provably cannot reach valid outputs — pinned), so one AOT program serves
  every fill level of its capacity tier, and the batcher switches to
  continuous admission (dispatch whenever the engine is free, never sleep on
  a non-empty queue). Goodput/padding-waste accounting rides every dispatch
  as a :class:`~qdml_tpu.serve.types.DispatchInfo`.
- **auto**: raced at warmup per (platform, capacity, route) exactly like the
  routing and circuit-impl autotuners; the race's jits land inside the
  warmup compile window, so the zero-request-path-compile pin holds in both
  modes.
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.models.cnn import SCP128
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.ops import dispatch_autotune
from qdml_tpu.ops.routing import select_expert, sparse_dispatch
from qdml_tpu.serve import batching_autotune
from qdml_tpu.serve.batcher import pick_bucket, power_of_two_buckets
from qdml_tpu.serve.types import DispatchInfo
from qdml_tpu.telemetry import span
from qdml_tpu.telemetry import cost as _cost
from qdml_tpu.telemetry.spans import get_sink
from qdml_tpu.train.hdce import HDCE
from qdml_tpu.utils.compile_cache import compile_cache_stats, enable_compile_cache


def _restore_family(workdir: str, prefix: str, tags: dict | None):
    """One family's eval-only restore: the EXPLICIT tag when ``tags`` pins
    one (must exist — a typo'd pin must fail loudly, not fall back to a
    different checkpoint), else newest-tag discovery. Shared by engine
    construction and the live hot-swap, so a deployer's tag semantics are
    identical across restart and swap."""
    from qdml_tpu.train.checkpoint import (
        has_checkpoint,
        restore_latest_params,
        restore_params,
    )

    tag = (tags or {}).get(prefix)
    if tag is None:
        return restore_latest_params(workdir, prefix)
    if not has_checkpoint(workdir, tag):
        raise FileNotFoundError(
            f"pinned tag {tag!r} does not exist under {workdir!r}"
        )
    vars_, meta = restore_params(workdir, tag)
    return vars_, meta, tag


class ServeEngine:
    """Checkpoint-restored HDCE pipeline behind per-bucket AOT executables."""

    def __init__(
        self,
        cfg: ExperimentConfig,
        hdce_vars: dict,
        clf_vars: dict,
        quantum: bool = False,
        buckets: tuple[int, ...] | None = None,
        mesh: Any | None = None,
    ):
        self.cfg = cfg
        self.quantum = quantum
        self.mesh = mesh
        self.buckets = tuple(
            sorted(buckets or cfg.serve.buckets or power_of_two_buckets(cfg.serve.max_batch))
        )
        self.hdce = HDCE(
            n_scenarios=cfg.data.n_scenarios,
            features=cfg.model.features,
            out_dim=cfg.h_out_dim,
        )
        if quantum:
            self.clf: Any = QSCP128(
                n_qubits=cfg.quantum.n_qubits,
                n_layers=cfg.quantum.n_layers,
                n_classes=cfg.quantum.n_classes,
                backend=cfg.quantum.backend,
                impl=cfg.quantum.impl,
                mps_chi=cfg.quantum.mps_chi,
                input_norm=cfg.quantum.input_norm,
            )
        else:
            self.clf = SCP128(n_classes=cfg.quantum.n_classes)
        # Param placement: commit vars to device once (checkpoints restore as
        # host numpy, and re-transferring on every request batch would make
        # serving host-bandwidth-bound). With a mesh the placement carries
        # the NamedShardings every bucket executable is lowered against —
        # swap_params re-places new checkpoints with these SAME shardings,
        # which is what makes the swap recompile-free.
        self._var_shardings = self._build_var_shardings(hdce_vars, clf_vars)
        self._swap_lock = lockdep.Lock("ServeEngine._swap_lock")
        # serializes whole swaps (resolve -> restore -> validate -> place ->
        # flip): two concurrent {"op": "swap"}s racing check-then-act could
        # land in reverse completion order and leave the OLDER checkpoint
        # live — so swap_from_workdir holds it across the workdir resolve and
        # restore too, not just the flip (reentrant: swap_params re-acquires
        # on the same thread). Never held on the request path — infer only
        # takes the inner _swap_lock.
        self._swap_gate = lockdep.RLock("ServeEngine._swap_gate")
        self._swap_epoch = 0
        self._live = (
            self._place(hdce_vars, self._var_shardings[0]),
            self._place(clf_vars, self._var_shardings[1]),
        )
        self._compiled: dict[int, Any] = {}
        # serve.checkify: the buckets hold checkified executables returning
        # (err, (h, pred)); infer() raises typed DivergenceError on a trip
        self._checkify = bool(cfg.serve.checkify)
        self._warm = False
        self._stats0: dict = {}
        # per-bucket XLA cost records (flops/bytes/peak memory/roofline),
        # filled by warmup from each AOT-compiled executable
        self.bucket_cost: dict[str, dict] = {}
        # per-bucket batch-axis partitioning actually baked into the
        # executable ("data" or "replicated") — warmup fills it, the
        # serve_summary fleet block reports it
        self.bucket_sharding: dict[str, str] = {}
        # quantum classifier only: the circuit implementation each bucket's
        # AOT executable dispatches (autotuned at warmup — docs/QUANTUM.md),
        # plus the candidate timings when the tuner actually ran
        self.quantum_impl: dict[str, Any] = {}
        # expert-routing dispatch per bucket ("dense" | "sparse") and the
        # measured race entry behind each choice — warmup fills them exactly
        # like quantum_impl (serve.dispatch "auto" -> dispatch_autotune race;
        # an explicit mode is forced into every bucket, race skipped)
        if cfg.serve.dispatch not in ("auto", "dense", "sparse"):
            raise ValueError(
                f"serve.dispatch must be auto|dense|sparse, got {cfg.serve.dispatch!r}"
            )
        self.dispatch_mode: dict[str, str] = {}
        self.dispatch_race: dict[str, Any] = {}
        # batch-admission/executable mode per capacity tier ("bucket" |
        # "ragged") and the measured race entry behind each choice — warmup
        # fills them exactly like dispatch_mode (serve.batching "auto" ->
        # batching_autotune race; an explicit mode is forced into every tier,
        # race skipped — the committed dryrun drives both forced modes)
        if cfg.serve.batching not in ("auto", "bucket", "ragged"):
            raise ValueError(
                f"serve.batching must be auto|bucket|ragged, got {cfg.serve.batching!r}"
            )
        self.batching_mode: dict[str, str] = {}
        self.batching_race: dict[str, Any] = {}
        # sparse-overflow accounting across worker threads (overflow rows are
        # served by the dense fallback, never dropped — the RATE is the
        # capacity_factor health signal serve_summary reports and the report
        # gate watches)
        self._dispatch_lock = lockdep.Lock("ServeEngine._dispatch_lock")
        self._overflow_rows = 0
        self._routed_rows = 0

    # -- placement / sharding ------------------------------------------------

    def _build_var_shardings(self, hdce_vars: dict, clf_vars: dict):
        """(hdce, clf) NamedSharding trees, or (None, None) single-device."""
        if self.mesh is None:
            return (None, None)
        if self.cfg.serve.expert_sharding:
            from qdml_tpu.parallel.federated import hdce_state_shardings

            hdce_sh = hdce_state_shardings(
                hdce_vars, self.mesh, n_scenarios=self.cfg.data.n_scenarios
            )
        else:
            hdce_sh = jax.tree.map(lambda _: NamedSharding(self.mesh, P()), hdce_vars)
        clf_sh = jax.tree.map(lambda _: NamedSharding(self.mesh, P()), clf_vars)
        return (hdce_sh, clf_sh)

    def _place(self, tree: Any, shardings: Any) -> Any:
        if shardings is None:
            return jax.tree.map(jnp.asarray, tree)
        # one placement choke point for the whole repo: the federated
        # placer device_puts single-controller and routes multi-controller
        # placement through its jitted identity — so a fleet of multihost
        # backends places warmup params and every fan-out hot-swap exactly
        # like multihost training placement (docs/FLEET.md)
        from qdml_tpu.parallel.federated import place_tree

        return place_tree(tree, shardings)

    def _x_sharding(self, b: int) -> NamedSharding | None:
        """Batch-axis sharding for bucket ``b``: data-parallel when the data
        axis divides it, replicated otherwise (tiny buckets below the device
        count run everywhere rather than compiling an uneven layout)."""
        if self.mesh is None:
            return None
        dp = self.mesh.shape[self.cfg.mesh.data_axis_name]
        return NamedSharding(self.mesh, P("data") if b % dp == 0 else P())

    def mesh_topology(self) -> dict | None:
        """Fleet-facing mesh facts for serve_summary / the report gate."""
        if self.mesh is None:
            return None
        return {
            "devices": int(np.prod(list(self.mesh.shape.values()))),
            "axes": {k: int(v) for k, v in self.mesh.shape.items()},
            "expert_sharding": bool(self.cfg.serve.expert_sharding),
        }

    # -- construction -------------------------------------------------------

    @classmethod
    def from_workdir(
        cls,
        cfg: ExperimentConfig,
        workdir: str,
        buckets: tuple[int, ...] | None = None,
        mesh: Any | None = None,
        tags: dict | None = None,
    ) -> "ServeEngine":
        """Restore the newest trained HDCE + classifier from ``workdir``.

        Tag discovery goes through
        :func:`~qdml_tpu.train.checkpoint.restore_latest_params`
        (best > last > resume); the quantum classifier is preferred when one
        was trained (its checkpoint meta reconciles the circuit config via
        ``reconcile_quantum_cfg``, exactly like the eval CLI), falling back to
        the classical ``SCP128``. ``tags`` pins explicit per-family tags
        exactly like :meth:`swap_from_workdir` — how a RESTARTED server comes
        up on a continually fine-tuned ``hdce_last`` that a stale earlier
        ``hdce_best`` would otherwise shadow (docs/CONTROL.md).
        """
        from qdml_tpu.train.checkpoint import (
            CheckpointNotFoundError,
            reconcile_quantum_cfg,
        )

        hdce_vars, _, _ = _restore_family(workdir, "hdce", tags)
        try:
            # one resolve-and-restore per family: a separate existence check
            # would scan the directory twice and race checkpoint promotion.
            # Only the typed never-trained miss falls through to the
            # classical classifier — a failed restore of an EXISTING qsc tag
            # (partial/corrupt checkpoint) propagates; silently downgrading a
            # quantum deployment to SCP128 would serve the wrong model.
            clf_vars, clf_meta, _ = _restore_family(workdir, "qsc", tags)
        except CheckpointNotFoundError:
            pass
        else:
            cfg = reconcile_quantum_cfg(cfg, clf_meta)
            return cls(cfg, hdce_vars, clf_vars, quantum=True, buckets=buckets, mesh=mesh)
        try:
            clf_vars, _, _ = _restore_family(workdir, "sc", tags)
        except CheckpointNotFoundError:
            raise FileNotFoundError(
                f"no scenario-classifier checkpoint (qsc/sc) under {workdir!r} "
                "— run `qdml-tpu train-sc` (or train-qsc) first"
            ) from None
        return cls(cfg, hdce_vars, clf_vars, quantum=False, buckets=buckets, mesh=mesh)

    # -- live params (hot-swap) ---------------------------------------------

    def live_vars(self) -> tuple[dict, dict]:
        """One atomic read of the live ``(hdce_vars, clf_vars)`` pair. The
        only sanctioned way to look at the serving params from outside:
        reading the halves in two separate lock acquisitions could pair hdce
        params from one checkpoint with clf params from the next if a swap
        lands in between — mismatched model halves that swap_params' shape
        validation cannot catch."""
        with self._swap_lock:
            return self._live

    @property
    def swap_epoch(self) -> int:
        """Number of successful hot-swaps since construction (0 = the params
        the engine was built with)."""
        with self._swap_lock:
            return self._swap_epoch

    def swap_params(self, hdce_vars: dict, clf_vars: dict) -> dict:
        """Zero-downtime checkpoint hot-swap: place new params with the LIVE
        shardings and flip the live tuple between batches.

        Shapes/dtypes/tree structure must match the serving params exactly —
        that is the invariant that lets the existing AOT executables accept
        the new arrays with zero compiles (validated up front; a mismatched
        checkpoint raises ``ValueError`` and the old params keep serving).
        In-flight batches already dispatched against the old committed arrays
        resolve against them (XLA pins the buffers); every batch dequeued
        after the flip sees the new checkpoint. Returns ``{"epoch", "compile"
        <cache-counter deltas over the swap — all-zero is the gate>}``.
        """
        if not self._warm:
            raise RuntimeError("swap_params before warmup() — nothing is serving yet")

        def _sig(tree):
            # shape/dtype without materializing device arrays (np.asarray on
            # a committed sharded param would be a full device->host copy)
            return jax.tree.map(
                lambda a: (tuple(np.shape(a)), str(getattr(a, "dtype", "?"))), tree
            )

        # one swap at a time, end to end: validation against the live tree
        # and the flip must not interleave with another swap's
        with self._swap_gate:
            with self._swap_lock:
                live_h, live_c = self._live
            for name, new, old in (("hdce", hdce_vars, live_h), ("clf", clf_vars, live_c)):
                # dict equality recurses containers, so a structure mismatch
                # compares unequal rather than raising
                if _sig(new) != _sig(old):
                    raise ValueError(
                        f"hot-swap {name} params do not match the serving tree "
                        "(structure/shape/dtype) — a shape-changing checkpoint "
                        "needs a fresh engine + warmup, not a swap"
                    )
            pre = compile_cache_stats()
            new_h = self._place(hdce_vars, self._var_shardings[0])
            new_c = self._place(clf_vars, self._var_shardings[1])
            # fault the transfers in OFF the request path: the first
            # post-swap batch must not pay the host->device copy
            jax.block_until_ready((new_h, new_c))  # lint: disable=blocking-under-lock(sanctioned off-request-path sync: the fence keeps half-copied params off replicas; _swap_gate is only ever held by swap/control calls, never the request path)
            post = compile_cache_stats()
            with self._swap_lock:
                self._swap_epoch += 1
                self._live = (new_h, new_c)
                epoch = self._swap_epoch
        rec = {
            "epoch": epoch,
            "compile": {k: post[k] - pre.get(k, 0) for k in post},
        }
        sink = get_sink()
        if sink is not None and getattr(sink, "active", False):
            sink.emit("counters", name="serve_swap", **rec)
        return rec

    def swap_from_workdir(self, workdir: str, tags: dict | None = None) -> dict:
        """Re-resolve the newest checkpoints under ``workdir`` (best > last >
        resume, per family) and hot-swap to them — the ``{"op": "swap"}``
        serve verb's engine half. A training run that just promoted a new
        ``*_best`` is deployed without restarting the server.

        ``tags`` pins an EXPLICIT checkpoint tag per family prefix (e.g.
        ``{"hdce": "hdce_last"}``; families not named keep the newest-tag
        resolution). The deployer (control/deploy.py) always passes the tag
        it just promoted: ``latest_tag``'s best > last preference is right
        for "deploy the newest training run", but after continual fine-tuning
        — which writes ``hdce_last`` — a STALE earlier ``hdce_best`` from the
        original training run would shadow the freshly promoted checkpoint
        and silently re-deploy yesterday's params."""
        from qdml_tpu.train.checkpoint import reconcile_quantum_cfg

        # the gate spans resolve+restore+flip: restoring OUTSIDE it would let
        # two concurrent swap verbs resolve different tags (slow orbax IO)
        # and flip in reverse completion order — the stale checkpoint would
        # pass swap_params' shape validation and end up live
        with self._swap_gate:
            hdce_vars, _, hdce_tag = _restore_family(workdir, "hdce", tags)
            clf_prefix = "qsc" if self.quantum else "sc"
            clf_vars, clf_meta, clf_tag = _restore_family(workdir, clf_prefix, tags)
            if self.quantum:
                # from_workdir RECONCILES the circuit config from checkpoint
                # meta; a live engine cannot (the model is baked into every
                # AOT executable), so the checkpoint must already match.
                # Shape-free flags (input_norm above all) matter here:
                # shapes/dtypes would pass swap_params validation while the
                # serving forward silently diverged from what the new
                # checkpoint was trained for.
                reconciled = reconcile_quantum_cfg(self.cfg, clf_meta)
                if reconciled.quantum != self.cfg.quantum:
                    raise ValueError(
                        f"hot-swap checkpoint {clf_tag!r} was trained for a "
                        "different quantum config than this engine serves "
                        "(see the reconcile note above) — deploy it with a "
                        "fresh engine + warmup, not a swap"
                    )
            rec = self.swap_params(hdce_vars, clf_vars)  # lint: disable=blocking-under-lock(sanctioned off-request-path sync: swap_from_workdir is a control verb; _swap_gate re-entry serializes it with swap_params by design)
        rec["tags"] = {"hdce": hdce_tag, clf_prefix: clf_tag}
        return rec

    # -- forward ------------------------------------------------------------

    def _forward(
        self, hdce_vars: dict, clf_vars: dict, x: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Fused classify -> all-trunks -> top-1 route. ``x``: (B, n_sub,
        n_beam, 2) f32 -> ``(h (B, 2*h_dim), pred (B,), conf (B,))``.
        ``conf`` is the routed class's probability (``exp(max log-prob)``) —
        the per-request classifier-confidence stat ServeMetrics histograms
        and the drift detectors consume (docs/CONTROL.md); it rides the
        existing result fetch, no extra dispatch."""
        logp = self.clf.apply(clf_vars, x, train=False)
        pred = jnp.argmax(logp, -1)
        conf = jnp.exp(jnp.max(logp, -1))
        xs = jnp.broadcast_to(x[None], (self.cfg.data.n_scenarios,) + x.shape)
        est_all = self.hdce.apply(hdce_vars, xs, train=False)  # (S, B, D)
        return select_expert(est_all, pred), pred, conf

    def _apply_trunks(self, hdce_vars: dict, xs: jnp.ndarray) -> jnp.ndarray:
        """Stacked trunks+head on per-scenario inputs ``(S, B', ...) ->
        (S, B', D)`` — the one sub-forward both dispatch modes share. With
        expert sharding the leading axis pins to ``fed`` exactly like the
        eval sweep's placement, so capacity buckets compose with the PR-7
        mesh layout (bucket s's rows live with trunk s's weights)."""
        if self.mesh is not None and self.cfg.serve.expert_sharding:
            s = self.cfg.data.n_scenarios
            fed = "fed" if self.mesh.shape.get("fed", 1) == s else None
            xs = jax.lax.with_sharding_constraint(
                xs,
                NamedSharding(self.mesh, P(fed, *(None,) * (xs.ndim - 1))),
            )
        return self.hdce.apply(hdce_vars, xs, train=False)

    def _forward_sparse(
        self, hdce_vars: dict, clf_vars: dict, x: jnp.ndarray, n_valid: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Capacity-bucketed twin of :meth:`_forward`: classify -> pack rows
        into per-expert buckets -> run ONLY the chosen trunk per bucket ->
        unsort (``routing.sparse_dispatch``). ``n_valid`` masks the zero-pad
        tail out of bucket capacity (padding must not inflate overflow).
        Returns ``(h, pred, conf, overflow)`` — overflow rows were served by
        the dense fallback inside the same program, never dropped."""
        s = self.cfg.data.n_scenarios
        logp = self.clf.apply(clf_vars, x, train=False)
        pred = jnp.argmax(logp, -1)
        conf = jnp.exp(jnp.max(logp, -1))
        valid = jnp.arange(x.shape[0]) < n_valid

        def dense_fb(xb, predb):
            xs = jnp.broadcast_to(xb[None], (s,) + xb.shape)
            return select_expert(self._apply_trunks(hdce_vars, xs), predb)

        h, overflow = sparse_dispatch(
            lambda buckets: self._apply_trunks(hdce_vars, buckets),
            dense_fb,
            x,
            pred,
            s,
            self.cfg.serve.capacity_factor,
            valid=valid,
        )
        return h, pred, conf, overflow

    def _mask_padding(self, x: jnp.ndarray, n_valid: jnp.ndarray) -> jnp.ndarray:
        """Zero the pad tail INSIDE the traced program: rows at or past the
        traced ``n_valid`` become exact zeros before any compute, so garbage
        in pad rows (NaN/Inf included) provably cannot reach valid outputs —
        stronger than the bucket mode's row-independence argument, and what
        lets one ragged executable serve every fill level of its tier."""
        valid = jnp.arange(x.shape[0]) < n_valid
        return jnp.where(
            valid.reshape((x.shape[0],) + (1,) * (x.ndim - 1)), x, jnp.zeros_like(x)
        )

    def _forward_ragged(
        self, hdce_vars: dict, clf_vars: dict, x: jnp.ndarray, n_valid: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Ragged twin of :meth:`_forward`: identical pipeline at the tier's
        static shape, pad tail masked inert from the traced valid count."""
        return self._forward(hdce_vars, clf_vars, self._mask_padding(x, n_valid))

    def _forward_sparse_ragged(
        self, hdce_vars: dict, clf_vars: dict, x: jnp.ndarray, n_valid: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        """Ragged twin of :meth:`_forward_sparse`: the valid count already
        feeds capacity accounting there; ragged additionally masks the pad
        INPUT rows so their garbage is inert before the classifier runs."""
        return self._forward_sparse(
            hdce_vars, clf_vars, self._mask_padding(x, n_valid), n_valid
        )

    def _tier_batching(self, b: int, route_mode: str) -> str:
        """Resolve tier ``b``'s batching mode at warmup time: a forced
        ``serve.batching`` wins outright; ``auto`` is the measured race
        (``batching_autotune.ensure_batching`` — table-cached per (platform,
        capacity, route), so repeat warmups read, not re-time). The race's
        candidate jits land inside the warmup compile window, keeping the
        zero-request-path-compile pin intact in both modes."""
        mode = self.cfg.serve.batching
        if mode != "auto":
            self.batching_race[str(b)] = {"forced": mode}
            return mode
        hdce_live, clf_live = self.live_vars()
        sparse = route_mode == "sparse"
        base = self._forward_sparse if sparse else self._forward
        ragged = self._forward_sparse_ragged if sparse else self._forward_ragged
        if self._checkify:
            # race the programs that actually deploy: with serve.checkify the
            # tier executables are the CHECKIFIED forwards, and a winner
            # timed on the unchecked twins could pick the loser of the real
            # pair (the functionalized error plumbing is not mask-free)
            from jax.experimental import checkify as _checkify

            from qdml_tpu.telemetry.sanitizer import checks

            base = _checkify.checkify(base, errors=checks())
            ragged = _checkify.checkify(ragged, errors=checks())
        # VARIED race inputs (not zeros): the candidates run the full forward
        # through the LIVE classifier, so identical rows would collapse every
        # prediction onto one expert and — on sparse tiers — time the
        # overflow-fallback branch instead of the steady state (the PR-9
        # degenerate-argmax lesson). Both candidates still consume the SAME
        # rows, so whatever the classifier routes, they execute the same
        # branch and the race's DELTA stays the mask cost it exists to
        # measure; varied rows keep the absolute path realistic too.
        x = (
            np.random.default_rng(0)
            .standard_normal((b, *self.cfg.image_hw, 2))
            .astype(np.float32)
        )
        args_b: tuple = (hdce_live, clf_live, x) + ((np.int32(b),) if sparse else ())
        args_r: tuple = (hdce_live, clf_live, x, np.int32(b))
        entry = batching_autotune.ensure_batching(
            {"bucket": (jax.jit(base), args_b), "ragged": (jax.jit(ragged), args_r)},
            capacity=b,
            route=route_mode,
            # program-variant dimensions of the raced shape: a winner timed
            # on the f32 unchecked pair must not decide for a bf16 or
            # checkified deployment (each variant gets its own table entry)
            dtype=self.cfg.model.dtype,
            checkify=self._checkify,
        )
        self.batching_race[str(b)] = entry
        return entry.get("best_infer") or "bucket"

    def _bucket_dispatch(self, b: int) -> str:
        """Resolve bucket ``b``'s routing dispatch at warmup time: a forced
        ``serve.dispatch`` wins outright; ``auto`` is the measured race
        (``dispatch_autotune.ensure_route`` — table-cached per (platform, S,
        bucket), so repeat warmups read, not re-time). With only one eligible
        mode (S below the sparse window) nothing is timed and the reference
        grid keeps its zero-extra-compile warmup."""
        mode = self.cfg.serve.dispatch
        if mode != "auto":
            self.dispatch_race[str(b)] = {"forced": mode}
            return mode
        hdce_live, _ = self.live_vars()
        entry = dispatch_autotune.ensure_route(
            lambda xs: self._apply_trunks(hdce_live, xs),
            jnp.zeros((b, *self.cfg.image_hw, 2), jnp.float32),
            self.cfg.data.n_scenarios,
            capacity_factor=self.cfg.serve.capacity_factor,
        )
        self.dispatch_race[str(b)] = entry
        return entry.get("best_infer") or "dense"

    def offline_forward(
        self, x: np.ndarray
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """The parity reference: the same fused forward jitted at the natural
        (unpadded, unbucketed) batch shape — numerically the offline eval
        path. Returns ``(h, pred, conf)``. Loadgen/tests call this BEFORE
        :meth:`warmup` so its compile never pollutes the request-path compile
        gate; the canary gate (control/deploy.py) calls it on throwaway
        candidate engines — control-plane compiles, never serving-window
        ones."""
        hdce_live, clf_live = self.live_vars()
        h, pred, conf = jax.jit(self._forward)(hdce_live, clf_live, jnp.asarray(x))
        return (
            np.asarray(jax.device_get(h)),
            np.asarray(jax.device_get(pred)),
            np.asarray(jax.device_get(conf)),
        )

    # -- warmup -------------------------------------------------------------

    def warmup(self) -> dict:
        """AOT-compile and first-execute every bucket; arm the compile gate.

        Returns ``{"buckets": ..., "compile": <cache-stat deltas over
        warmup>}``. After this returns, :meth:`request_path_compiles` starts
        from zero — any later compile in this process is, by definition, one
        the warmup failed to cover.
        """
        enable_compile_cache()
        pre = compile_cache_stats()
        # serve.checkify: AOT-compile the checkified forward instead — same
        # buckets, same gate; the error value is functionalized into the
        # program, so the request path still never compiles. OFF compiles
        # exactly the unwrapped program (byte-identical to the unflagged
        # build; pinned in tests/test_analysis.py).
        _checkify = checks = None
        if self._checkify:
            from jax.experimental import checkify as _checkify

            from qdml_tpu.telemetry.sanitizer import checks
        hdce_live, clf_live = self.live_vars()
        var_specs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (hdce_live, clf_live),
        )
        hw = self.cfg.image_hw
        for b in self.buckets:
            with span("serve_warmup_bucket", bucket=b):
                if self.quantum:
                    # Autotune at AOT-bucket compile time, NEVER on the
                    # request path: the tuner's own jits land inside the
                    # warmup window (the compile-gate snapshot is taken after
                    # this loop), and the lower() below bakes the measured
                    # winner into the bucket's executable.
                    from qdml_tpu.quantum import autotune
                    from qdml_tpu.quantum.circuits import resolve_impl

                    q = self.cfg.quantum
                    entry = autotune.prewarm(self.cfg, batch=b)
                    rec_impl: dict[str, Any] = {
                        "impl": resolve_impl(
                            q.impl, q.backend, q.n_qubits, q.n_layers, b, mode="infer"
                        )
                    }
                    if rec_impl["impl"] == "mps":
                        rec_impl["mps_chi"] = int(q.mps_chi)
                    if entry is not None:
                        rec_impl["autotuned"] = True
                        rec_impl["candidates"] = entry["candidates"]
                    self.quantum_impl[str(b)] = rec_impl
                # the routing dispatch AND the batching mode are decided here
                # — measured (auto) or forced — and BAKED into the bucket's
                # executable exactly like the sharding and the autotuned
                # circuit impl; both races' own jits land inside the warmup
                # compile window
                mode = self._bucket_dispatch(b)
                self.dispatch_mode[str(b)] = mode
                bmode = self._tier_batching(b, mode)
                self.batching_mode[str(b)] = bmode
                if bmode == "ragged":
                    base_fwd = (
                        self._forward_sparse_ragged
                        if mode == "sparse"
                        else self._forward_ragged
                    )
                else:
                    base_fwd = (
                        self._forward_sparse if mode == "sparse" else self._forward
                    )
                # both the sparse route and the ragged batching thread the
                # valid-row count through as a traced scalar, so one
                # executable serves every fill level of the bucket/tier
                takes_valid = mode == "sparse" or bmode == "ragged"
                fwd = (
                    _checkify.checkify(base_fwd, errors=checks())
                    if self._checkify
                    else base_fwd
                )
                x_spec = jax.ShapeDtypeStruct((b, *hw, 2), jnp.float32)
                specs: list[Any] = [*var_specs, x_spec]
                args: list[Any] = [hdce_live, clf_live, np.zeros((b, *hw, 2), np.float32)]
                if takes_valid:
                    specs.append(jax.ShapeDtypeStruct((), jnp.int32))
                    args.append(np.int32(b))
                jit_kwargs: dict[str, Any] = {}
                x_sh = self._x_sharding(b)
                if x_sh is not None:
                    # the sharding is baked into the executable exactly like
                    # the autotuned impl: batch over `data` when it divides,
                    # params per the placement trees — one SPMD program per
                    # bucket, collectives on ICI, nothing decided per request
                    shardings: tuple = (*self._var_shardings, x_sh)
                    if takes_valid:
                        shardings = (*shardings, NamedSharding(self.mesh, P()))
                    jit_kwargs["in_shardings"] = shardings
                    self.bucket_sharding[str(b)] = (
                        "data" if x_sh.spec else "replicated"
                    )
                compiled = jax.jit(fwd, **jit_kwargs).lower(*specs).compile()
                # first execute outside the request path (XLA may lazily
                # finalize; also faults in the params transfer)
                out = compiled(*args)
                res = out[1] if self._checkify else out
                h, pred = res[0], res[1]
                jax.block_until_ready((h, pred))
                self._compiled[b] = compiled
                # XLA cost accounting straight off the AOT executable (the
                # one place a COMPILED analysis is free — no extra compile,
                # we are holding the executable anyway): flops, bytes, peak
                # temp memory, roofline class per bucket
                rec = _cost.analyze(compiled)
                self.bucket_cost[str(b)] = rec
                sink = get_sink()
                if sink is not None and getattr(sink, "active", False):
                    sink.emit("cost", name="serve_bucket", bucket=b, **rec)
        post = compile_cache_stats()
        # SNAPSHOT the post-warmup totals (never reset the process-global
        # counters: StepClock/bench records in the same process must keep
        # their true run totals). request_path_compiles() diffs against this.
        self._stats0 = post
        self._warm = True
        out = {
            "buckets": self.buckets,
            "compile": {k: post[k] - pre.get(k, 0) for k in post},
            "cost": self.bucket_cost,
            "dispatch": {
                "mode": dict(self.dispatch_mode),
                "capacity_factor": float(self.cfg.serve.capacity_factor),
                "race": self.dispatch_race,
            },
            "batching": {
                "mode": dict(self.batching_mode),
                "continuous_admission": self.continuous_admission,
                "race": self.batching_race,
            },
        }
        if self.mesh is not None:
            out["mesh"] = self.mesh_topology()
            out["sharding"] = dict(self.bucket_sharding)
        if self.quantum_impl:
            out["quantum_impl"] = self.quantum_impl
        return out

    @property
    def continuous_admission(self) -> bool:
        """True when the engine's batching mode calls for continuous
        admission (the largest tier — the capacity production fills live in —
        resolved to ragged at warmup). ServeLoop/ReplicaPool sync their
        self-created batcher's admission policy from this after warmup."""
        return self.batching_mode.get(str(self.buckets[-1])) == "ragged"

    def batching_summary(self) -> dict:
        """The serve_summary/fleet ``batching`` block: per-capacity-tier
        batching modes (collapsed to one word when uniform) and whether the
        batcher admits continuously — how a fleet reader tells a ragged
        deployment from a bucket one per tier."""
        modes = set(self.batching_mode.values())
        mode = modes.pop() if len(modes) == 1 else ("mixed" if modes else "bucket")
        return {
            "mode": mode,
            "per_tier": dict(self.batching_mode),
            "continuous_admission": self.continuous_admission,
        }

    def dispatch_summary(self) -> dict:
        """The serve_summary ``dispatch`` block: per-bucket routing modes
        (collapsed to one word when uniform), the capacity factor, and the
        observed sparse overflow-fallback rate over everything served so far
        (``None`` until a sparse batch has been routed — a rate over zero
        rows would read as perfect health that was never measured)."""
        modes = set(self.dispatch_mode.values())
        mode = modes.pop() if len(modes) == 1 else ("mixed" if modes else "dense")
        with self._dispatch_lock:
            routed, overflow = self._routed_rows, self._overflow_rows
        return {
            "mode": mode,
            "per_bucket": dict(self.dispatch_mode),
            "capacity_factor": float(self.cfg.serve.capacity_factor),
            "overflow_rows": overflow,
            "routed_rows": routed,
            "overflow_rate": round(overflow / routed, 6) if routed else None,
        }

    def request_path_compiles(self) -> dict:
        """Compile-cache counter deltas since warmup ended — all-zero iff
        nothing compiled on the request path (the acceptance gate loadgen
        reports). Clamped at zero: an external ``reset_stats()`` between
        warmup and now can only lower the totals, never fake a compile."""
        now = compile_cache_stats()
        return {k: max(0, now[k] - self._stats0.get(k, 0)) for k in now}

    # -- request path -------------------------------------------------------

    def infer(
        self, x: np.ndarray, traced: bool = False
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, DispatchInfo]:
        """Serve one coalesced batch: pad to its bucket/capacity tier, run
        the pre-compiled executable (ragged tiers additionally thread the
        valid count as a traced scalar), slice back. ``x``: (n, n_sub,
        n_beam, 2). Returns ``(h (n, 2*h_dim), pred (n,), conf (n,), info)``
        — ``conf`` is the routed class's probability, the per-request
        confidence stat the serve metrics histogram and the drift detectors
        consume; ``info`` is the :class:`~qdml_tpu.serve.types.DispatchInfo`
        the goodput/padding-waste accounting consumes.

        ``traced`` stamps the host-side compute/fetch phase boundaries onto
        the DispatchInfo for the request-tracing decomposition
        (docs/TELEMETRY.md): the executable call plus a device fence is the
        ``compute`` phase, the device->host reply copy the ``fetch`` phase.
        The fence adds nothing material — the very next statements fetch the
        same buffers — and the untraced path (default) stamps NO clock: the
        ``serve.trace_sample=0`` overhead-free pin. The executables are
        identical either way; tracing never touches jitted code.

        Oversized batches (n > largest bucket — only reachable by direct
        callers; the micro-batcher caps at ``max_batch``) fall back to
        largest-bucket chunks rather than compiling a fresh shape; ``info``
        sums the STATIC rows of every chunk (the final chunk picks its own
        smallest-fitting tier), so chunked fill/pad stats stay honest
        instead of reporting n/largest_bucket fills past 1.0.
        """
        if not self._warm:
            raise RuntimeError("ServeEngine.infer before warmup() — request path would compile")
        n = int(x.shape[0])
        if n == 0:
            raise ValueError("empty batch")
        largest = self.buckets[-1]
        if n > largest:
            hs, preds, confs, infos = [], [], [], []
            for lo in range(0, n, largest):
                h, p, c, sub = self.infer(x[lo : lo + largest], traced=traced)
                hs.append(h)
                preds.append(p)
                confs.append(c)
                infos.append(sub)
            modes = {i.mode for i in infos}
            return (
                np.concatenate(hs),
                np.concatenate(preds),
                np.concatenate(confs),
                # the aggregate labels the LARGEST tier dispatched (the final
                # chunk may have dropped to a smaller one) and collapses the
                # per-chunk batching modes honestly — with batching=auto,
                # tiers can resolve to different race winners
                DispatchInfo(
                    bucket=max(i.bucket for i in infos),
                    n=n,
                    rows=sum(i.rows for i in infos),
                    chunks=sum(i.chunks for i in infos),
                    mode=modes.pop() if len(modes) == 1 else "mixed",
                    # traced chunked dispatch: phase durations SUM across
                    # chunks (the request paid every launch sequentially)
                    compute_s=(
                        sum(i.compute_s or 0.0 for i in infos) if traced else None
                    ),
                    fetch_s=(
                        sum(i.fetch_s or 0.0 for i in infos) if traced else None
                    ),
                ),
            )
        b = pick_bucket(n, self.buckets)  # lint: disable=pad-to-bucket-in-serve(THE sanctioned pad site: every request batch reaches XLA through this one tier pick + pad, where DispatchInfo accounts the waste)
        xp = np.zeros((b, *x.shape[1:]), np.float32)
        xp[:n] = x
        # one atomic read of the live checkpoint per batch: a swap that lands
        # mid-batch applies to the NEXT dequeue, never tears this one
        hdce_live, clf_live = self.live_vars()
        mode = self.dispatch_mode.get(str(b), "dense")
        bmode = self.batching_mode.get(str(b), "bucket")
        t_dispatch = time.perf_counter() if traced else None
        if mode == "sparse" or bmode == "ragged":
            out = self._compiled[b](hdce_live, clf_live, xp, np.int32(n))
        else:
            out = self._compiled[b](hdce_live, clf_live, xp)
        t_fetch = None
        if traced:
            # compute/fetch boundary for the trace: fence the dispatch so the
            # fetch segment below is the pure device->host copy, not "device
            # still executing". Traced batches only — the next statements
            # fetch these same buffers anyway, so the fence adds no stall,
            # and the untraced path never syncs here.
            jax.block_until_ready(out)  # lint: disable=host-sync-hot-path(traced-batch-only phase fence: the reply fetch on the next lines waits on the same buffers — same dispatch, no extra stall; serve.trace_sample=0 never reaches this branch)
            t_fetch = time.perf_counter()
        overflow = None
        if self._checkify:
            err, res = out
            # per-batch device->host error fetch: the sanitizer's contract
            # (out of host-sync-hot-path's sight — `.get` is far too generic
            # an attribute to track; the rule audits the unconditional syncs)
            msg = err.get()
            if msg:
                from qdml_tpu.telemetry import DivergenceError

                # typed failure into the serve loop's batch guard: every
                # affected request future gets the exception, nothing hangs
                raise DivergenceError(
                    f"serve checkify tripped on bucket {b}: {msg.splitlines()[0]}",
                    None,
                    "checkify",
                )
        else:
            res = out
        if mode == "sparse":
            h, pred, conf, overflow = res
        else:
            h, pred, conf = res
        if overflow is not None:
            # overflow rides the same result fetch cadence (a 4-byte scalar
            # next to the reply arrays) — the capacity-factor health signal
            # serve_summary reports per window
            ovf = int(np.asarray(jax.device_get(overflow)))  # lint: disable=host-sync-hot-path(4-byte overflow counter fetched with the reply it annotates — same dispatch, no extra stall)
            with self._dispatch_lock:
                self._overflow_rows += ovf
                self._routed_rows += n
        out_h = np.asarray(jax.device_get(h))[:n]  # lint: disable=host-sync-hot-path(the one result fetch per served batch — this transfer IS the reply)
        out_pred = np.asarray(jax.device_get(pred))[:n]  # lint: disable=host-sync-hot-path(the one result fetch per served batch — this transfer IS the reply)
        out_conf = np.asarray(jax.device_get(conf))[:n]  # lint: disable=host-sync-hot-path(per-request confidence fetched with the reply it annotates — same dispatch, no extra stall)
        info = DispatchInfo(bucket=b, n=n, rows=b, chunks=1, mode=bmode)
        if traced:
            t_end = time.perf_counter()
            info.compute_s = t_fetch - t_dispatch
            info.fetch_s = t_end - t_fetch
        return (out_h, out_pred, out_conf, info)
