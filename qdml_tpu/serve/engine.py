"""Serving engine: fused HDCE inference, bucketed AOT warmup, zero request-path compiles.

The online pipeline is the eval sweep's forward (``eval/sweep.py``) stripped
to its serving core: scenario classifier -> argmax -> run ALL stacked
``ConvP128`` trunks + shared ``FCP128`` head on the batch ->
:func:`~qdml_tpu.ops.routing.select_expert` gather — MoE-style top-1 dispatch
with no host round trip, one jitted function end to end.

Compilation is amortized entirely into :meth:`ServeEngine.warmup` (the
Qandle gate-matrix-caching argument applied to XLA executables): every batch
bucket is AOT-compiled via ``jit(...).lower(...).compile()`` and executed
once, then the compile-cache counters are SNAPSHOT — a request-path compile
would advance ``compile_cache_stats()`` past the snapshot, and
:meth:`request_path_compiles` exposes exactly that delta as the "warmup
actually covered the request path" gate (a snapshot, not a global reset:
the counters are process-wide and other telemetry consumers — StepClock,
bench — must keep seeing the run's true totals). The request path itself
calls pre-compiled executables only; an un-warmed shape raises instead of
silently tracing.

Padding: batches pad with zeros up to the bucket size and outputs are sliced
back to the real count. Every per-sample op in the pipeline (convs, eval-mode
BatchNorm over running stats, dense heads, the routing gather) is
row-independent, so padding rows cannot perturb real rows — the "mask" is the
valid-count slice.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.models.cnn import SCP128
from qdml_tpu.models.qsc import QSCP128
from qdml_tpu.ops.routing import select_expert
from qdml_tpu.serve.batcher import pick_bucket, power_of_two_buckets
from qdml_tpu.telemetry import span
from qdml_tpu.telemetry import cost as _cost
from qdml_tpu.telemetry.spans import get_sink
from qdml_tpu.train.hdce import HDCE
from qdml_tpu.utils.compile_cache import compile_cache_stats, enable_compile_cache


class ServeEngine:
    """Checkpoint-restored HDCE pipeline behind per-bucket AOT executables."""

    def __init__(
        self,
        cfg: ExperimentConfig,
        hdce_vars: dict,
        clf_vars: dict,
        quantum: bool = False,
        buckets: tuple[int, ...] | None = None,
    ):
        self.cfg = cfg
        self.quantum = quantum
        self.buckets = tuple(
            sorted(buckets or cfg.serve.buckets or power_of_two_buckets(cfg.serve.max_batch))
        )
        self.hdce = HDCE(
            n_scenarios=cfg.data.n_scenarios,
            features=cfg.model.features,
            out_dim=cfg.h_out_dim,
        )
        if quantum:
            self.clf: Any = QSCP128(
                n_qubits=cfg.quantum.n_qubits,
                n_layers=cfg.quantum.n_layers,
                n_classes=cfg.quantum.n_classes,
                backend=cfg.quantum.backend,
                impl=cfg.quantum.impl,
                input_norm=cfg.quantum.input_norm,
            )
        else:
            self.clf = SCP128(n_classes=cfg.quantum.n_classes)
        # Commit vars to device once: checkpoints restore as host numpy, and
        # re-transferring the params on every request batch would make the
        # serving path host-bandwidth-bound.
        self._hdce_vars = jax.tree.map(jnp.asarray, hdce_vars)
        self._clf_vars = jax.tree.map(jnp.asarray, clf_vars)
        self._compiled: dict[int, Any] = {}
        # serve.checkify: the buckets hold checkified executables returning
        # (err, (h, pred)); infer() raises typed DivergenceError on a trip
        self._checkify = bool(cfg.serve.checkify)
        self._warm = False
        self._stats0: dict = {}
        # per-bucket XLA cost records (flops/bytes/peak memory/roofline),
        # filled by warmup from each AOT-compiled executable
        self.bucket_cost: dict[str, dict] = {}
        # quantum classifier only: the circuit implementation each bucket's
        # AOT executable dispatches (autotuned at warmup — docs/QUANTUM.md),
        # plus the candidate timings when the tuner actually ran
        self.quantum_impl: dict[str, Any] = {}

    # -- construction -------------------------------------------------------

    @classmethod
    def from_workdir(
        cls,
        cfg: ExperimentConfig,
        workdir: str,
        buckets: tuple[int, ...] | None = None,
    ) -> "ServeEngine":
        """Restore the newest trained HDCE + classifier from ``workdir``.

        Tag discovery goes through :func:`~qdml_tpu.train.checkpoint.latest_tag`
        (best > last > resume); the quantum classifier is preferred when one
        was trained (its checkpoint meta reconciles the circuit config via
        ``reconcile_quantum_cfg``, exactly like the eval CLI), falling back to
        the classical ``SCP128``.
        """
        from qdml_tpu.train.checkpoint import (
            latest_tag,
            reconcile_quantum_cfg,
            restore_params,
        )

        hdce_tag = latest_tag(workdir, "hdce")
        if hdce_tag is None:
            raise FileNotFoundError(
                f"no hdce checkpoint (best/last/resume) under {workdir!r} — "
                "run `qdml-tpu train-hdce` first"
            )
        hdce_vars, _ = restore_params(workdir, hdce_tag)
        qsc_tag = latest_tag(workdir, "qsc")
        if qsc_tag is not None:
            clf_vars, clf_meta = restore_params(workdir, qsc_tag)
            cfg = reconcile_quantum_cfg(cfg, clf_meta)
            return cls(cfg, hdce_vars, clf_vars, quantum=True, buckets=buckets)
        sc_tag = latest_tag(workdir, "sc")
        if sc_tag is None:
            raise FileNotFoundError(
                f"no scenario-classifier checkpoint (qsc/sc) under {workdir!r} "
                "— run `qdml-tpu train-sc` (or train-qsc) first"
            )
        clf_vars, _ = restore_params(workdir, sc_tag)
        return cls(cfg, hdce_vars, clf_vars, quantum=False, buckets=buckets)

    # -- forward ------------------------------------------------------------

    def _forward(
        self, hdce_vars: dict, clf_vars: dict, x: jnp.ndarray
    ) -> tuple[jnp.ndarray, jnp.ndarray]:
        """Fused classify -> all-trunks -> top-1 route. ``x``: (B, n_sub,
        n_beam, 2) f32 -> ``(h (B, 2*h_dim), pred (B,))``."""
        logp = self.clf.apply(clf_vars, x, train=False)
        pred = jnp.argmax(logp, -1)
        xs = jnp.broadcast_to(x[None], (self.cfg.data.n_scenarios,) + x.shape)
        est_all = self.hdce.apply(hdce_vars, xs, train=False)  # (S, B, D)
        return select_expert(est_all, pred), pred

    def offline_forward(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """The parity reference: the same fused forward jitted at the natural
        (unpadded, unbucketed) batch shape — numerically the offline eval
        path. Loadgen/tests call this BEFORE :meth:`warmup` so its compile
        never pollutes the request-path compile gate."""
        h, pred = jax.jit(self._forward)(self._hdce_vars, self._clf_vars, jnp.asarray(x))
        return np.asarray(jax.device_get(h)), np.asarray(jax.device_get(pred))

    # -- warmup -------------------------------------------------------------

    def warmup(self) -> dict:
        """AOT-compile and first-execute every bucket; arm the compile gate.

        Returns ``{"buckets": ..., "compile": <cache-stat deltas over
        warmup>}``. After this returns, :meth:`request_path_compiles` starts
        from zero — any later compile in this process is, by definition, one
        the warmup failed to cover.
        """
        enable_compile_cache()
        pre = compile_cache_stats()
        # serve.checkify: AOT-compile the checkified forward instead — same
        # buckets, same gate; the error value is functionalized into the
        # program, so the request path still never compiles. OFF compiles
        # exactly the unwrapped program (byte-identical to the unflagged
        # build; pinned in tests/test_analysis.py).
        fwd = self._forward
        if self._checkify:
            from jax.experimental import checkify as _checkify

            from qdml_tpu.telemetry.sanitizer import checks

            fwd = _checkify.checkify(self._forward, errors=checks())
        var_specs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            (self._hdce_vars, self._clf_vars),
        )
        hw = self.cfg.image_hw
        for b in self.buckets:
            with span("serve_warmup_bucket", bucket=b):
                if self.quantum:
                    # Autotune at AOT-bucket compile time, NEVER on the
                    # request path: the tuner's own jits land inside the
                    # warmup window (the compile-gate snapshot is taken after
                    # this loop), and the lower() below bakes the measured
                    # winner into the bucket's executable.
                    from qdml_tpu.quantum import autotune
                    from qdml_tpu.quantum.circuits import resolve_impl

                    q = self.cfg.quantum
                    entry = autotune.prewarm(self.cfg, batch=b)
                    rec_impl: dict[str, Any] = {
                        "impl": resolve_impl(
                            q.impl, q.backend, q.n_qubits, q.n_layers, b, mode="infer"
                        )
                    }
                    if entry is not None:
                        rec_impl["autotuned"] = True
                        rec_impl["candidates"] = entry["candidates"]
                    self.quantum_impl[str(b)] = rec_impl
                x_spec = jax.ShapeDtypeStruct((b, *hw, 2), jnp.float32)
                compiled = jax.jit(fwd).lower(*var_specs, x_spec).compile()
                # first execute outside the request path (XLA may lazily
                # finalize; also faults in the params transfer)
                out = compiled(
                    self._hdce_vars, self._clf_vars, np.zeros((b, *hw, 2), np.float32)
                )
                h, pred = out[1] if self._checkify else out
                jax.block_until_ready((h, pred))
                self._compiled[b] = compiled
                # XLA cost accounting straight off the AOT executable (the
                # one place a COMPILED analysis is free — no extra compile,
                # we are holding the executable anyway): flops, bytes, peak
                # temp memory, roofline class per bucket
                rec = _cost.analyze(compiled)
                self.bucket_cost[str(b)] = rec
                sink = get_sink()
                if sink is not None and getattr(sink, "active", False):
                    sink.emit("cost", name="serve_bucket", bucket=b, **rec)
        post = compile_cache_stats()
        # SNAPSHOT the post-warmup totals (never reset the process-global
        # counters: StepClock/bench records in the same process must keep
        # their true run totals). request_path_compiles() diffs against this.
        self._stats0 = post
        self._warm = True
        out = {
            "buckets": self.buckets,
            "compile": {k: post[k] - pre.get(k, 0) for k in post},
            "cost": self.bucket_cost,
        }
        if self.quantum_impl:
            out["quantum_impl"] = self.quantum_impl
        return out

    def request_path_compiles(self) -> dict:
        """Compile-cache counter deltas since warmup ended — all-zero iff
        nothing compiled on the request path (the acceptance gate loadgen
        reports). Clamped at zero: an external ``reset_stats()`` between
        warmup and now can only lower the totals, never fake a compile."""
        now = compile_cache_stats()
        return {k: max(0, now[k] - self._stats0.get(k, 0)) for k in now}

    # -- request path -------------------------------------------------------

    def infer(self, x: np.ndarray) -> tuple[np.ndarray, np.ndarray, int]:
        """Serve one coalesced batch: pad to its bucket, run the pre-compiled
        executable, slice back. ``x``: (n, n_sub, n_beam, 2). Returns
        ``(h (n, 2*h_dim), pred (n,), bucket)``.

        Oversized batches (n > largest bucket — only reachable by direct
        callers; the micro-batcher caps at ``max_batch``) fall back to
        largest-bucket chunks rather than compiling a fresh shape.
        """
        if not self._warm:
            raise RuntimeError("ServeEngine.infer before warmup() — request path would compile")
        n = int(x.shape[0])
        if n == 0:
            raise ValueError("empty batch")
        largest = self.buckets[-1]
        if n > largest:
            hs, preds = [], []
            for lo in range(0, n, largest):
                h, p, _ = self.infer(x[lo : lo + largest])
                hs.append(h)
                preds.append(p)
            return np.concatenate(hs), np.concatenate(preds), largest
        b = pick_bucket(n, self.buckets)
        xp = np.zeros((b, *x.shape[1:]), np.float32)
        xp[:n] = x
        out = self._compiled[b](self._hdce_vars, self._clf_vars, xp)
        if self._checkify:
            err, (h, pred) = out
            # per-batch device->host error fetch: the sanitizer's contract
            # (out of host-sync-hot-path's sight — `.get` is far too generic
            # an attribute to track; the rule audits the unconditional syncs)
            msg = err.get()
            if msg:
                from qdml_tpu.telemetry import DivergenceError

                # typed failure into the serve loop's batch guard: every
                # affected request future gets the exception, nothing hangs
                raise DivergenceError(
                    f"serve checkify tripped on bucket {b}: {msg.splitlines()[0]}",
                    None,
                    "checkify",
                )
        else:
            h, pred = out
        return (
            np.asarray(jax.device_get(h))[:n],  # lint: disable=host-sync-hot-path(the one result fetch per served batch — this transfer IS the reply)
            np.asarray(jax.device_get(pred))[:n],  # lint: disable=host-sync-hot-path(the one result fetch per served batch — this transfer IS the reply)
            b,
        )
