"""Circuit-breaker brownout for the serving front door.

When the shared queue crosses a high watermark the breaker OPENS and new
submits fast-fail with a typed ``Overloaded("breaker_open")`` BEFORE they
enter the queue — brownout instead of collapse: requests already queued keep
their place and their deadlines, and the client's retry/backoff discipline
(docs/RESILIENCE.md) gets an immediate, cheap signal instead of a queue-full
timeout at the end of a doomed wait. The bounded queue alone sheds at
``max_queue``; the breaker sheds EARLIER (at ``high_frac * max_queue``) and
keeps shedding until the backlog has actually drained (hysteresis), so the
system spends the overload serving the queue it has instead of churning
admission at the rim.

States (the textbook three, clock injected for deterministic tests):

- **closed** — everything admits; depth >= high watermark opens it.
- **open** — every submit fast-fails for ``open_s`` seconds.
- **half-open** — up to ``probes`` submits admit; the next transition check
  closes (depth <= low watermark) or re-opens (still >= high). Probe counts
  reset on every open -> half-open edge.

One breaker fronts the whole pool (submits funnel through replica 0), so the
state machine is a single small critical section on the submit path —
counters ride the same lock.
"""

from __future__ import annotations

import threading

from qdml_tpu.utils import lockdep
import time
from typing import Callable

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Queue-depth-watermark breaker with half-open probe recovery."""

    def __init__(
        self,
        max_queue: int,
        high_frac: float = 0.8,
        low_frac: float = 0.3,
        open_s: float = 0.25,
        probes: int = 4,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not (0.0 < low_frac <= high_frac <= 1.0):
            raise ValueError(
                f"need 0 < low_frac <= high_frac <= 1, got {low_frac}/{high_frac}"
            )
        self.high = max(1, int(max_queue * high_frac))
        self.low = max(0, int(max_queue * low_frac))
        self.open_s = float(open_s)
        self.probes = max(1, int(probes))
        self.clock = clock
        self._lock = lockdep.Lock("CircuitBreaker._lock")
        self._state = CLOSED
        self._opened_at = 0.0
        self._probes_left = 0
        self._opens = 0        # closed/half-open -> open transitions
        self._fast_fails = 0   # submits rejected while open
        self._admitted = 0     # submits allowed through (all states)

    def allow(self, depth: int, now: float | None = None) -> bool:
        """One submit's admission decision at current queue ``depth``.
        Runs the whole state machine: False means fast-fail with the typed
        ``breaker_open`` result, BEFORE the request touches the queue."""
        now = self.clock() if now is None else now
        with self._lock:
            if self._state == CLOSED:
                if depth >= self.high:
                    self._state = OPEN
                    self._opened_at = now
                    self._opens += 1
                    self._fast_fails += 1
                    return False
                self._admitted += 1
                return True
            if self._state == OPEN:
                if now - self._opened_at >= self.open_s:
                    self._state = HALF_OPEN
                    self._probes_left = self.probes
                else:
                    self._fast_fails += 1
                    return False
            # half-open: transition on the watermarks, else spend a probe
            if depth <= self.low:
                self._state = CLOSED
                self._admitted += 1
                return True
            if depth >= self.high or self._probes_left <= 0:
                self._state = OPEN
                self._opened_at = now
                self._opens += 1
                self._fast_fails += 1
                return False
            self._probes_left -= 1
            self._admitted += 1
            return True

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def summary(self) -> dict:
        """The ``serve_summary.breaker`` block (and the health verb's view):
        state + transition/shed counters + the open fraction the report gate
        compares absolutely (``serve.breaker_open_fraction``, slack-gated
        like the sparse overflow rate — healthy runs sit at 0.0)."""
        with self._lock:
            total = self._admitted + self._fast_fails
            return {
                "state": self._state,
                "opens": self._opens,
                "fast_fails": self._fast_fails,
                "admitted": self._admitted,
                "open_fraction": (
                    round(self._fast_fails / total, 6) if total else 0.0
                ),
                "high_watermark": self.high,
                "low_watermark": self.low,
            }
