"""Serve loop + local socket front-end.

:class:`ServeLoop` is the in-process serving core: a worker thread that
drains the micro-batcher — shed results resolve immediately, ready batches go
through the engine's pre-compiled executables, and every request's future
resolves with a typed :class:`~qdml_tpu.serve.types.Prediction` or
:class:`~qdml_tpu.serve.types.Overloaded`. The loadgen harness and the smoke
tests drive this object directly; the socket server below is a thin framing
layer over it.

``qdml-tpu serve`` runs :func:`run_server`: an asyncio loop accepting
newline-delimited JSON over a local TCP socket (``{"id", "x", [deadline_ms]}``
-> ``{"id", "ok", "pred", "h", "latency_ms"}`` or
``{"id", "ok": false, "reason"}``). One engine, one batcher: concurrent
connections coalesce into the same buckets, which is the entire point of
dynamic micro-batching.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import Future

import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.serve.batcher import MicroBatcher
from qdml_tpu.serve.engine import ServeEngine
from qdml_tpu.serve.metrics import ServeMetrics
from qdml_tpu.serve.types import SHUTDOWN, Overloaded, Prediction, Request


class ServeLoop:
    """Worker thread(s) pumping batcher -> engine -> futures.

    ``workers`` (default ``cfg.serve.workers``) threads share the one
    batcher and engine; each records into its OWN :class:`ServeMetrics`
    (no cross-thread contention on the hot path) and
    :meth:`merged_metrics`/:meth:`live_metrics` aggregate them exactly via
    ``Histogram.merge``. ``self.metrics`` is worker 0's collector — the
    single-worker default keeps the PR-2 behavior and tests unchanged.
    """

    def __init__(
        self,
        engine: ServeEngine,
        batcher: MicroBatcher | None = None,
        metrics: ServeMetrics | None = None,
        workers: int | None = None,
    ):
        serve_cfg = engine.cfg.serve
        self.engine = engine
        self.batcher = batcher or MicroBatcher(
            max_batch=serve_cfg.max_batch,
            max_wait_s=serve_cfg.max_wait_ms / 1e3,
            max_queue=serve_cfg.max_queue,
        )
        self.metrics = metrics or ServeMetrics()
        self.workers = max(1, int(workers if workers is not None else serve_cfg.workers))
        self._worker_metrics = [self.metrics] + [
            ServeMetrics(
                sink=self.metrics._sink, log_requests=self.metrics.log_requests
            )
            for _ in range(self.workers - 1)
        ]
        self._default_deadline_s = (
            serve_cfg.deadline_ms / 1e3 if serve_cfg.deadline_ms > 0 else None
        )
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._threads: list[threading.Thread] = []
        self._exit_lock = threading.Lock()
        self._live_workers = 0
        self._started = False  # stays True after stop(): a finished loop rejects
        self._rid = 0

    # -- client side --------------------------------------------------------

    def submit(
        self,
        x: np.ndarray,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
    ) -> Future:
        """Enqueue one request; the returned future resolves with a
        Prediction or Overloaded (never raises for overload). A malformed
        payload raises ``ValueError`` HERE, synchronously — client errors
        must never reach the worker, where one bad shape would crash the
        batch it was coalesced into."""
        x = np.asarray(x, np.float32)
        expect = (*self.engine.cfg.image_hw, 2)
        if x.shape != expect:
            raise ValueError(f"request x has shape {x.shape}, expected {expect}")
        if rid is None:
            self._rid += 1
            rid = self._rid
        if self._started and not any(t.is_alive() for t in self._threads):
            # a stopped or CRASHED worker must not accept work: the queue
            # would grow with futures nobody will ever resolve (clients hung
            # forever behind a server that still accepts connections).
            # Submits before start() are fine — start() will drain them.
            fut: Future = Future()
            fut.set_result(Overloaded(rid, SHUTDOWN))
            return fut
        now = self.batcher.clock()
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None else self._default_deadline_s
        )
        req = Request(
            rid=rid,
            x=x,
            deadline=None if deadline_s is None else now + deadline_s,
            future=Future(),
        )
        rejected = self.batcher.submit(req, now=now)
        if rejected is not None:
            self.metrics.observe_shed(rejected)
            req.future.set_result(rejected)
        else:
            self._wake.set()
        return req.future

    # -- worker side --------------------------------------------------------

    def start(self) -> "ServeLoop":
        if not self.engine._compiled:
            self.engine.warmup()
        self._stop.clear()
        self._threads = [
            threading.Thread(
                target=self._run,
                args=(self._worker_metrics[i],),
                daemon=True,
                name=f"serve-loop-{i}",
            )
            for i in range(self.workers)
        ]
        self._started = True
        with self._exit_lock:  # workers read this under the same lock on exit
            self._live_workers = len(self._threads)
        for t in self._threads:
            t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the workers; with ``drain`` (default) only after the queue
        has emptied, so every submitted future resolves."""
        if not self._threads:
            return
        if drain:
            while self.batcher.depth > 0 and any(t.is_alive() for t in self._threads):
                self._wake.set()
                time.sleep(0.001)
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []

    def merged_metrics(self, sink=None) -> ServeMetrics:
        """All workers' collectors folded into one fresh ServeMetrics (exact
        quantile aggregation — ``Histogram.merge`` keeps raw samples).
        ``sink`` binds the aggregate's flush target (loadgen passes its
        logger's telemetry stream)."""
        agg = ServeMetrics(sink=sink, log_requests=False)
        for m in self._worker_metrics:
            agg.merge(m)
        return agg

    def live_metrics(self) -> dict:
        """The ``{"op": "metrics"}`` serve-verb payload: merged per-worker
        counters/histograms, current queue depth, bucket layout, and the
        request-path compile-cache snapshot — a running server is observable
        without restarting it. Safe to call any time (also after stop)."""
        return self.merged_metrics().snapshot(
            compile_cache=self.engine.request_path_compiles(),
            workers=self.workers,
            queue_depth_now=self.batcher.depth,
            buckets=list(self.engine.buckets),
        )

    def _serve_one(self, metrics: ServeMetrics | None = None) -> bool:
        """Single batcher pump: resolve sheds, serve at most one batch.
        Returns True when any work happened (the loop's idle detector).
        ``metrics`` is the calling worker's collector (worker 0's when
        driven directly, e.g. by the fake-clock tests)."""
        metrics = metrics if metrics is not None else self.metrics
        depth = self.batcher.depth
        batch, shed = self.batcher.next_batch()
        for r, o in shed:
            metrics.observe_shed(o)
            if r.future is not None:
                r.future.set_result(o)
        if not batch:
            return bool(shed)
        t0 = time.perf_counter()
        try:
            # stack INSIDE the guard: a shape-mismatched request failing the
            # stack must strand nobody, exactly like an engine failure
            x = np.stack([r.x for r in batch])
            h, pred, bucket = self.engine.infer(x)
        except BaseException as e:
            # a dying batch must not strand its clients: forward the failure
            # into every future, then let the loop's finally drain the rest
            for r in batch:
                if r.future is not None and not r.future.done():
                    r.future.set_exception(e)
            raise
        dur = time.perf_counter() - t0
        now = self.batcher.clock()
        preds = []
        for i, r in enumerate(batch):
            p = Prediction(
                rid=r.rid,
                h=h[i],
                scenario=int(pred[i]),
                latency_s=now - r.enqueue_ts,
                bucket=bucket,
                batch_n=len(batch),
            )
            preds.append(p)
        # metrics before resolution: a client awaiting the future must be able
        # to read a consistent histogram the moment its result arrives
        metrics.observe_batch(preds, bucket, depth, dur)
        for r, p in zip(batch, preds):
            if r.future is not None:
                r.future.set_result(p)
        return True

    def _run(self, metrics: ServeMetrics) -> None:
        try:
            while not self._stop.is_set():
                if not self._serve_one(metrics):
                    # idle: sleep until the oldest request ages out or a submit wakes us
                    self._wake.wait(timeout=max(self.batcher.wait_hint(), 1e-4))
                    self._wake.clear()
        finally:
            # shutdown OR crash: resolve EVERYTHING still queued (no silent
            # hangs) — but only once no OTHER worker can still serve it. A
            # single crashed worker must not shed a queue its surviving
            # peers are actively draining; the LAST worker out (crash or
            # stop) always drains, so nothing strands either way.
            with self._exit_lock:
                self._live_workers -= 1
                last_out = self._live_workers <= 0
            while self._stop.is_set() or last_out:
                batch, shed = self.batcher.next_batch(now=float("inf"))
                if not batch and not shed:
                    break
                for r, o in shed:
                    metrics.observe_shed(o)
                    if r.future is not None:
                        r.future.set_result(o)
                for r in batch:
                    if r.future is not None:
                        r.future.set_result(Overloaded(r.rid, SHUTDOWN))


# ---------------------------------------------------------------------------
# Socket front-end (newline-delimited JSON over local TCP)
# ---------------------------------------------------------------------------


def _encode(res) -> dict:
    if isinstance(res, Prediction):
        return {
            "id": res.rid,
            "ok": True,
            "pred": res.scenario,
            "h": np.asarray(res.h, np.float32).tolist(),
            "latency_ms": round(res.latency_s * 1e3, 3),
            "bucket": res.bucket,
        }
    return {"id": res.rid, "ok": False, "reason": res.reason}


async def _handle(reader, writer, loop_: ServeLoop) -> None:
    while True:
        line = await reader.readline()
        if not line:
            break
        try:
            msg = json.loads(line)
        except json.JSONDecodeError:
            writer.write(b'{"ok": false, "reason": "bad_json"}\n')
            await writer.drain()
            continue
        if isinstance(msg, dict) and msg.get("op") == "metrics":
            # live observability verb: counters/histograms/compile-cache of
            # the RUNNING server, no restart, no inference submitted. Off the
            # event loop: the merge copies+sorts every raw histogram sample,
            # which is O(requests served) on a long-lived server — it must
            # not stall every connected client's reply path while it runs.
            metrics_view = await asyncio.get_running_loop().run_in_executor(
                None, loop_.live_metrics
            )
            reply = {"id": msg.get("id"), "ok": True, "metrics": metrics_view}
            writer.write((json.dumps(reply) + "\n").encode())
            await writer.drain()
            continue
        try:
            # every well-formed line gets a typed reply — a missing/ragged
            # "x", a non-object message, a bad deadline are client errors,
            # not reasons to drop the connection (or touch the worker)
            fut = loop_.submit(
                np.asarray(msg["x"], np.float32),
                rid=msg.get("id"),
                deadline_ms=msg.get("deadline_ms"),
            )
        except (KeyError, TypeError, ValueError) as e:
            rid = msg.get("id") if isinstance(msg, dict) else None
            writer.write(
                (json.dumps({"id": rid, "ok": False, "reason": f"bad_request: {e}"}) + "\n").encode()
            )
            await writer.drain()
            continue
        res = await asyncio.wrap_future(fut)
        writer.write((json.dumps(_encode(res)) + "\n").encode())
        await writer.drain()
    writer.close()


async def serve_async(
    loop_: ServeLoop,
    host: str,
    port: int,
    ready: "asyncio.Future | None" = None,
) -> None:
    """Accept connections until cancelled; resolves ``ready`` with the bound
    port (port=0 binds an ephemeral port — how the tests avoid collisions)."""
    server = await asyncio.start_server(
        lambda r, w: _handle(r, w, loop_), host=host, port=port
    )
    bound = server.sockets[0].getsockname()[1]
    if ready is not None and not ready.done():
        ready.set_result(bound)
    async with server:
        await server.serve_forever()


def run_server(cfg: ExperimentConfig, engine: ServeEngine, logger=None) -> None:
    """Blocking entry for ``qdml-tpu serve``: warm, announce, serve until
    interrupted; flush serving counters on the way out."""
    metrics = ServeMetrics()
    loop_ = ServeLoop(engine, metrics=metrics, workers=cfg.serve.workers).start()
    print(
        json.dumps(
            {
                "serving": f"{cfg.serve.host}:{cfg.serve.port}",
                "buckets": list(engine.buckets),
                "workers": loop_.workers,
                # post-warmup counters: anything non-zero here (or later)
                # is a compile the warmup failed to cover
                "compile_cache_after_warmup": engine.request_path_compiles(),
                # per-bucket XLA cost accounting from the AOT warmup
                "cost": engine.bucket_cost,
            }
        ),
        flush=True,
    )
    try:
        asyncio.run(serve_async(loop_, cfg.serve.host, cfg.serve.port))
    except KeyboardInterrupt:
        pass
    finally:
        loop_.stop(drain=False)
        # merged across workers: the same aggregate the metrics verb serves
        loop_.merged_metrics().flush(
            compile_cache=engine.request_path_compiles(), workers=loop_.workers
        )
