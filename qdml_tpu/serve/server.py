"""Serve loop, replica pool + local socket front-end.

:class:`ServeLoop` is the in-process serving core: worker thread(s) that
drain the micro-batcher — shed results resolve immediately, ready batches go
through the engine's pre-compiled executables, and every request's future
resolves with a typed :class:`~qdml_tpu.serve.types.Prediction` or
:class:`~qdml_tpu.serve.types.Overloaded`. :class:`ReplicaPool` runs N of
them over ONE shared micro-batcher against ONE warmed engine (one warmup,
one autotune table, one set of AOT executables), with per-replica
:class:`~qdml_tpu.serve.metrics.ServeMetrics` merged exactly via
``Histogram.merge`` — the fleet story of docs/SERVING.md. The loadgen
harness and the smoke tests drive these objects directly; the socket server
below is a thin framing layer over either.

Exit discipline: every worker of every replica registers with one
:class:`ExitCoordinator`. A crashed (or stopped) worker must never shed the
shared queue while ANY peer — same replica or not — can still serve it; the
LAST worker out pool-wide always drains, so nothing strands either way (the
PR-3 hazard, generalized from one loop's threads to the whole pool).

Fault tolerance (docs/RESILIENCE.md): the pool SUPERVISES its replicas — a
supervisor thread detects dead worker threads (and, with
``serve.stall_timeout_s``, stale heartbeats while work is queued), restarts
the crashed replica with jittered exponential backoff under a restart
budget, and QUARANTINES a crash-looping slot (structured
``replica_quarantined`` event) while the peers keep serving. A
:class:`~qdml_tpu.serve.breaker.CircuitBreaker` (``serve.breaker``) fronts
``submit``: past the queue-depth high watermark new requests fast-fail with
typed ``Overloaded("breaker_open")`` BEFORE they enqueue, and half-open
probes recover it. Chaos faults inject through the explicit
:class:`~qdml_tpu.serve.faults.FaultPlan` hooks (``faults=``; inert and free
when absent — the default).

``qdml-tpu serve`` runs :func:`run_server`: an asyncio loop accepting
newline-delimited JSON over a local TCP socket (``{"id", "x", [deadline_ms]}``
-> ``{"id", "ok", "pred", "h", "latency_ms"}`` or
``{"id", "ok": false, "reason"}``), plus the ``{"op": "metrics"}`` live
observability verb, the ``{"op": "health"}`` liveness verb (warmup state,
live/quarantined replicas, queue depth, last-dispatch age, swap epoch,
breaker state — cheap enough to poll every second) and the ``{"op": "swap"}``
zero-downtime checkpoint hot-swap verb. Connections are hardened: a
per-connection idle/read timeout (``serve.conn_timeout_s``) reaps dead or
slow-loris peers with a typed reply, an oversized line
(``serve.max_line_bytes``) gets a typed ``bad_request`` and the connection
closes, and explicit request ids are DEDUPED for ``serve.dedup_ttl_s``
seconds — a client retrying an idempotent id re-attaches to the in-flight
(or just-completed) result instead of double-dispatching, which is what
makes the client-side retry/backoff discipline (serve/client.py) safe.
"""

from __future__ import annotations

import asyncio
import json
import os
import random
import socket
import threading

from qdml_tpu.utils import lockdep
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.serve.batcher import MicroBatcher
from qdml_tpu.serve.breaker import CircuitBreaker
from qdml_tpu.serve.engine import ServeEngine
from qdml_tpu.serve.faults import FaultInjected, FaultPlan, RestartPolicy
from qdml_tpu.serve.metrics import ServeMetrics
from qdml_tpu.serve.types import (
    BREAKER_OPEN,
    SHUTDOWN,
    Overloaded,
    Prediction,
    Request,
)
from qdml_tpu.telemetry.events import ensure_bus
from qdml_tpu.telemetry.events import publish as publish_event
from qdml_tpu.telemetry.spans import get_sink
from qdml_tpu.telemetry.tracing import TraceContext, trace_sampled


def _emit_event(name: str, **fields) -> None:
    """Structured fleet event (replica_restarted / replica_quarantined /
    supervisor_error) into the run's telemetry stream, if one is active —
    and onto the process-global event spine always (the ``{"op": "events"}``
    tail works sink or no sink)."""
    sink = get_sink()
    if sink is not None and getattr(sink, "active", False):
        sink.emit("counters", name=name, **fields)
    publish_event(name, tier="serve", **fields)


class ExitCoordinator:
    """Worker-liveness accounting shared by every loop draining one batcher.

    One instance per ServeLoop by default; a :class:`ReplicaPool` injects a
    single shared instance into all its replicas, so "am I the last worker
    out" (the drain trigger) and "is anyone still serving" (the submit
    liveness check) are pool-wide facts, not per-loop guesses.
    """

    def __init__(self):
        self._lock = lockdep.Lock("ExitCoordinator._lock")
        self._live = 0

    def enter(self, n: int) -> None:
        with self._lock:
            self._live += n

    def leave(self) -> bool:
        """Deregister one worker; True iff it was the last one pool-wide."""
        with self._lock:
            self._live -= 1
            return self._live <= 0

    def live(self) -> int:
        with self._lock:
            return self._live


class ServeLoop:
    """Worker thread(s) pumping batcher -> engine -> futures.

    ``workers`` (default ``cfg.serve.workers``) threads share the one
    batcher and engine; each records into its OWN :class:`ServeMetrics`
    (no cross-thread contention on the hot path) and
    :meth:`merged_metrics`/:meth:`live_metrics` aggregate them exactly via
    ``Histogram.merge``. ``self.metrics`` is worker 0's collector — the
    single-worker default keeps the PR-2 behavior and tests unchanged.
    ``exit_coord`` shares worker-exit accounting across loops (the replica
    pool passes one coordinator to all replicas); ``name`` labels the
    threads. ``faults`` opts into the chaos hooks (None = inert, free);
    ``breaker`` fronts submit with the brownout state machine (the pool
    passes one breaker to all replicas so the front's decisions cover the
    shared queue).
    """

    def __init__(
        self,
        engine: ServeEngine,
        batcher: MicroBatcher | None = None,
        metrics: ServeMetrics | None = None,
        workers: int | None = None,
        exit_coord: ExitCoordinator | None = None,
        name: str = "serve-loop",
        faults: FaultPlan | None = None,
        breaker: CircuitBreaker | None = None,
        trace_sample: float | None = None,
    ):
        serve_cfg = engine.cfg.serve
        self.engine = engine
        self.name = name
        self.faults = faults
        # remember whether the batcher is loop-owned: start() syncs an owned
        # batcher's admission policy (coalesce vs continuous) from the warmed
        # engine's measured batching mode; an injected batcher is the
        # caller's to configure (the replica pool injects its shared one and
        # syncs it itself; fake-clock tests pin the policy they test)
        self._own_batcher = batcher is None
        self.batcher = batcher or MicroBatcher(
            max_batch=serve_cfg.max_batch,
            max_wait_s=serve_cfg.max_wait_ms / 1e3,
            max_queue=serve_cfg.max_queue,
            continuous=engine.continuous_admission,
        )
        self._breaker = breaker if breaker is not None else (
            CircuitBreaker(
                max_queue=self.batcher.max_queue,
                high_frac=serve_cfg.breaker_high_frac,
                low_frac=serve_cfg.breaker_low_frac,
                open_s=serve_cfg.breaker_open_s,
                probes=serve_cfg.breaker_probes,
            )
            if serve_cfg.breaker
            else None
        )
        self.metrics = metrics or ServeMetrics()
        self.workers = max(1, int(workers if workers is not None else serve_cfg.workers))
        self._worker_metrics = [self.metrics] + [
            ServeMetrics(
                sink=self.metrics._sink, log_requests=self.metrics.log_requests
            )
            for _ in range(self.workers - 1)
        ]
        self._default_deadline_s = (
            serve_cfg.deadline_ms / 1e3 if serve_cfg.deadline_ms > 0 else None
        )
        # Phase-trace sampling rate (telemetry/tracing.py): deterministic on
        # the request id, so a retried id stays traced across tiers. The
        # override parameter exists for harnesses that vary the rate against
        # ONE warmed engine (the engine's executables are identical either
        # way — tracing is host-side only).
        self._trace_sample = float(
            serve_cfg.trace_sample if trace_sample is None else trace_sample
        )
        self._stop = threading.Event()
        # wake rides on the BATCHER (its owner): pool replicas share the
        # queue, so a submit must reach whichever loop's worker is idle
        self._wake = self.batcher.wake
        self._threads: list[threading.Thread] = []
        self._exit = exit_coord or ExitCoordinator()
        self._started = False  # stays True after stop(): a finished loop rejects
        self._rid = 0
        # supervision signals (advisory, single-writer-newest-wins floats:
        # any worker stamps them; the supervisor/health verb only AGE them)
        self._heartbeat = 0.0          # newest worker pump iteration
        self._last_dispatch_ts = 0.0   # newest served batch
        # restart-visibility epoch (docs/TELEMETRY.md "monitoring"): a
        # monitor differencing cumulative counters across polls must detect
        # a restart BETWEEN two scrapes — uptime_s alone can miss one when
        # the poll gap exceeds the new uptime, so start_seq stamps the
        # construction instant as an identity the restart resets
        self._monitor_t0 = time.monotonic()
        self._start_seq = int(time.time() * 1000)

    # -- client side --------------------------------------------------------

    def submit(
        self,
        x: np.ndarray,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
        trace: bool | None = None,
    ) -> Future:
        """Enqueue one request; the returned future resolves with a
        Prediction or Overloaded (never raises for overload). A malformed
        payload raises ``ValueError`` HERE, synchronously — client errors
        must never reach the worker, where one bad shape would crash the
        batch it was coalesced into. ``trace`` forces (True) or suppresses
        (False) the phase trace; None (default) samples by the id hash at
        ``serve.trace_sample`` — 0 creates nothing, the overhead-free pin."""
        x = np.asarray(x, np.float32)
        expect = (*self.engine.cfg.image_hw, 2)
        if x.shape != expect:
            raise ValueError(f"request x has shape {x.shape}, expected {expect}")
        if rid is None:
            self._rid += 1
            rid = self._rid
        if self._started and self._exit.live() <= 0:
            # no worker anywhere in the pool can serve this: the queue would
            # grow with futures nobody will ever resolve (clients hung
            # forever behind a server that still accepts connections).
            # Submits before start() are fine — start() will drain them; a
            # crashed worker with live peers is fine too — the coordinator
            # counts pool-wide, and the peers drain the shared queue.
            fut: Future = Future()
            fut.set_result(Overloaded(rid, SHUTDOWN))
            return fut
        had_deadline = deadline_ms is not None or self._default_deadline_s is not None
        if self._breaker is not None and not self._breaker.allow(self.batcher.depth):
            # brownout: fast-fail BEFORE the queue — requests already queued
            # keep their place, and the retrying client gets an immediate
            # typed signal instead of a doomed queue wait (docs/RESILIENCE.md)
            res = Overloaded(rid, BREAKER_OPEN)
            self.metrics.observe_shed(res, had_deadline=had_deadline)
            fut = Future()
            fut.set_result(res)
            return fut
        now = self.batcher.clock()
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None else self._default_deadline_s
        )
        want_trace = (
            trace
            if trace is not None
            else self._trace_sample > 0.0 and trace_sampled(rid, self._trace_sample)
        )
        req = Request(
            rid=rid,
            x=x,
            deadline=None if deadline_s is None else now + deadline_s,
            future=Future(),
            trace=TraceContext(rid) if want_trace else None,
        )
        rejected = self.batcher.submit(req, now=now)
        if rejected is not None:
            self.metrics.observe_shed(rejected, had_deadline=req.deadline is not None)
            req.future.set_result(rejected)
        return req.future

    # -- worker side --------------------------------------------------------

    def start(self) -> "ServeLoop":
        if not self.engine._compiled:
            self.engine.warmup()
        if self._own_batcher:
            # the "auto" batching race resolves at warmup, after the batcher
            # exists: sync the admission policy to the measured mode (ragged
            # -> continuous dispatch, bucket -> coalesce to bucket edges)
            self.batcher.continuous = self.engine.continuous_admission
        self._stop.clear()
        self._threads = [
            threading.Thread(
                target=self._run,
                args=(self._worker_metrics[i],),
                daemon=True,
                name=f"{self.name}-{i}",
            )
            for i in range(self.workers)
        ]
        self._started = True
        # register BEFORE the threads run: a submit racing start() must see
        # the pool as live (the coordinator is the liveness source of truth)
        self._exit.enter(len(self._threads))
        for t in self._threads:
            t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the workers; with ``drain`` (default) only after the queue
        has emptied, so every submitted future resolves. When pool PEERS
        share the batcher, draining is their job — a scaled-down replica
        must not block on a feed that live peers keep refilling (and they,
        or the pool-wide last-worker-out drain, resolve every future)."""
        if not self._threads:
            return
        if drain:
            while (
                self.batcher.depth > 0
                and 0 < self._exit.live() <= sum(t.is_alive() for t in self._threads)
            ):
                self._wake.set()
                time.sleep(0.001)
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []

    def merged_metrics(self, sink=None) -> ServeMetrics:
        """All workers' collectors folded into one fresh ServeMetrics (exact
        quantile aggregation — ``Histogram.merge`` keeps raw samples).
        ``sink`` binds the aggregate's flush target (loadgen passes its
        logger's telemetry stream)."""
        agg = ServeMetrics(sink=sink, log_requests=False)
        for m in self._worker_metrics:
            agg.merge(m)
        return agg

    def live_metrics(self) -> dict:
        """The ``{"op": "metrics"}`` serve-verb payload: merged per-worker
        counters/histograms, current queue depth, bucket layout, swap epoch,
        and the request-path compile-cache snapshot — a running server is
        observable without restarting it. Safe to call any time (also after
        stop)."""
        return self.merged_metrics().snapshot(
            compile_cache=self.engine.request_path_compiles(),
            workers=self.workers,
            queue_depth_now=self.batcher.depth,
            buckets=list(self.engine.buckets),
            swap_epoch=self.engine.swap_epoch,
            dispatch=self.engine.dispatch_summary(),
            batching=self.engine.batching_summary(),
            breaker=None if self._breaker is None else self._breaker.summary(),
        )

    def health(self) -> dict:
        """The ``{"op": "health"}`` verb's per-loop view: is anything able to
        serve, and how stale is it. Cheap (no histogram merges — this is the
        1 Hz poll a front-door router or the fleet controller makes)."""
        now = time.monotonic()
        alive = sum(t.is_alive() for t in self._threads)
        return {
            "warm": bool(getattr(self.engine, "_warm", False)),
            "started": self._started,
            "workers": self.workers,
            "workers_alive": alive,
            "queue_depth": self.batcher.depth,
            "heartbeat_age_s": (
                None if not self._heartbeat else round(now - self._heartbeat, 4)
            ),
            "last_dispatch_age_s": (
                None
                if not self._last_dispatch_ts
                else round(now - self._last_dispatch_ts, 4)
            ),
            "swap_epoch": self.engine.swap_epoch,
            "uptime_s": round(now - self._monitor_t0, 3),
            "start_seq": self._start_seq,
            "breaker": None if self._breaker is None else self._breaker.summary(),
        }

    def _serve_one(self, metrics: ServeMetrics | None = None) -> bool:
        """Single batcher pump: resolve sheds, serve at most one batch.
        Returns True when any work happened (the loop's idle detector).
        ``metrics`` is the calling worker's collector (worker 0's when
        driven directly, e.g. by the fake-clock tests)."""
        metrics = metrics if metrics is not None else self.metrics
        depth = self.batcher.depth
        batch, shed = self.batcher.next_batch()
        for r, o in shed:
            # dequeue sheds are deadline expiries by construction
            metrics.observe_shed(o, had_deadline=True)
            if r.future is not None:
                r.future.set_result(o)
        if not batch:
            return bool(shed)
        # dequeue/dispatch trace boundary: stamped ONLY when the batch holds
        # a traced request (trace_sample=0 adds zero clock calls here — the
        # fake-clock tests and the overhead-free pin both count on it)
        traced = any(r.trace is not None for r in batch)
        t_dequeue = self.batcher.clock() if traced else None
        t0 = time.perf_counter()
        try:
            # stack INSIDE the guard: a shape-mismatched request failing the
            # stack must strand nobody, exactly like an engine failure
            x = np.stack([r.x for r in batch])
            if self.faults is not None:
                # worker_exception site: the batch is dequeued and its
                # futures are in hand — an injected raise here must resolve
                # every one of them with the failure, exactly like a real
                # engine error (that equivalence is what the chaos proves)
                self.faults.check_worker_batch(self.name)
            h, pred, conf, info = self.engine.infer(x, traced=traced)
        except BaseException as e:
            # a dying batch must not strand its clients: forward the failure
            # into every future, then let the loop's finally drain the rest
            for r in batch:
                if r.future is not None and not r.future.done():
                    r.future.set_exception(e)
            raise
        dur = time.perf_counter() - t0
        self._last_dispatch_ts = time.monotonic()
        now = self.batcher.clock()
        if traced:
            # batch_wait vs queue_wait split (docs/TELEMETRY.md): the batch's
            # NEWEST member's enqueue time partitions each request's wait —
            # everything before it is coalescing (waiting for later arrivals
            # to batch with), everything after is the formed batch waiting
            # for a free engine. Both from the one batcher clock that also
            # stamps enqueue_ts and latency_s — never mixed with perf_counter.
            newest = max(r.enqueue_ts for r in batch)
            for r in batch:
                if r.trace is None:
                    continue
                r.trace.add_phase("batch_wait", newest - r.enqueue_ts)
                r.trace.add_phase("queue_wait", t_dequeue - newest)
                if info.compute_s is not None:
                    r.trace.add_phase("compute", info.compute_s)
                if info.fetch_s is not None:
                    r.trace.add_phase("fetch", info.fetch_s)
                # future-resolution boundary closes the trace: the total IS
                # the latency the reply reports, so phase sums reconcile
                # against the same number the latency histogram sees
                r.trace.total_s = now - r.enqueue_ts
        preds = []
        for i, r in enumerate(batch):
            p = Prediction(
                rid=r.rid,
                h=h[i],
                scenario=int(pred[i]),
                latency_s=now - r.enqueue_ts,
                bucket=info.bucket,
                batch_n=len(batch),
                deadline_met=None if r.deadline is None else now <= r.deadline,
                confidence=float(conf[i]),
                trace=r.trace,
            )
            preds.append(p)
        # metrics before resolution: a client awaiting the future must be able
        # to read a consistent histogram the moment its result arrives
        metrics.observe_batch(preds, info, depth, dur)
        for r, p in zip(batch, preds):
            if r.future is not None:
                r.future.set_result(p)
        return True

    def _run(self, metrics: ServeMetrics) -> None:
        try:
            while not self._stop.is_set():
                self._heartbeat = time.monotonic()
                if self.faults is not None and self.batcher.depth > 0:
                    # replica_crash site: BEFORE any dequeue and only when
                    # work is pending (so the schedule's `at` counts
                    # observed-work occasions) — an injected crash leaves the
                    # queue untouched, the killed-process shape supervision
                    # must recover from
                    self.faults.check_worker_loop(self.name)
                if not self._serve_one(metrics):
                    # idle: sleep until the oldest request ages out or a submit wakes us
                    self._wake.wait(timeout=max(self.batcher.wait_hint(), 1e-4))
                    self._wake.clear()
        except FaultInjected as e:
            # an injected chaos fault kills the worker — that IS the
            # experiment — quietly: the expected crash must not bury the
            # run's stderr under tracebacks (real failures re-raise below)
            metrics.observe_fault(e.kind)
        except BaseException as e:
            metrics.observe_fault(type(e).__name__)
            raise
        finally:
            # shutdown OR crash: resolve EVERYTHING still queued (no silent
            # hangs) — but only once no OTHER worker, in THIS loop or any
            # pool peer sharing the batcher, can still serve it. A single
            # crashed worker (or a stopped replica) must not shed a queue
            # its surviving peers are actively draining; the LAST worker out
            # pool-wide always drains, so nothing strands either way.
            last_out = self._exit.leave()
            while last_out:
                batch, shed = self.batcher.next_batch(now=float("inf"))
                if not batch and not shed:
                    break
                for r, o in shed:
                    metrics.observe_shed(o, had_deadline=True)
                    if r.future is not None:
                        r.future.set_result(o)
                for r in batch:
                    if r.future is not None:
                        r.future.set_result(
                            Overloaded(r.rid, SHUTDOWN)
                        )


class ReplicaPool:
    """N ServeLoops over one shared batcher, one engine, one warmup.

    The fleet unit of docs/SERVING.md: every replica pumps the SAME
    :class:`MicroBatcher` feed through the SAME warmed engine (one set of
    AOT executables, one autotune table — warmup runs exactly once however
    many replicas serve), with per-replica/per-worker :class:`ServeMetrics`
    merged exactly via ``Histogram.merge`` on demand. One
    :class:`ExitCoordinator` spans the pool, so submit-liveness and
    last-worker-out draining are pool-wide facts. A checkpoint hot-swap on
    the shared engine (``engine.swap_params``) lands on every replica at
    once — each batch reads the live param tuple at dequeue.

    The pool is ELASTIC: :meth:`add_replica` / :meth:`remove_replica` /
    :meth:`scale_to` resize it under live traffic (the autoscaler's levers,
    docs/CONTROL.md). Removal is drain-safe by construction: the departing
    replica's workers deregister from the SHARED coordinator, and because
    live peers remain, the last-worker-out drain never fires — the shared
    queue keeps being pumped by the survivors and no submitted future is
    ever shed by a scale-down. Replica 0 is the permanent submit front and
    is never removed. Removed replicas land in a retired list so their
    histograms stay in :meth:`merged_metrics` (a scale-down must not vanish
    the requests it already served).

    The pool is also SUPERVISED (``serve.supervise``, docs/RESILIENCE.md): a
    supervisor thread restarts replicas whose workers died (thread liveness;
    plus heartbeat age under ``serve.stall_timeout_s``) with jittered
    exponential backoff, and quarantines a slot that exhausts
    ``serve.restart_budget`` — structured ``replica_restarted`` /
    ``replica_quarantined`` events, peers serving throughout, the
    zero-stranded-futures invariant intact across every restart (the crashed
    worker's own exit path resolves what it held; the restarted workers —
    or live peers — drain the shared queue).
    """

    def __init__(
        self,
        engine: ServeEngine,
        replicas: int | None = None,
        batcher: MicroBatcher | None = None,
        workers: int | None = None,
        sink=None,
        log_requests: bool = True,
        faults: FaultPlan | None = None,
        trace_sample: float | None = None,
    ):
        serve_cfg = engine.cfg.serve
        self.engine = engine
        n_replicas = max(
            1, int(replicas if replicas is not None else serve_cfg.replicas)
        )
        self._own_batcher = batcher is None
        self.batcher = batcher or MicroBatcher(
            max_batch=serve_cfg.max_batch,
            max_wait_s=serve_cfg.max_wait_ms / 1e3,
            max_queue=serve_cfg.max_queue,
            continuous=engine.continuous_admission,
        )
        self._exit = ExitCoordinator()
        self._sink = sink
        self._log_requests = log_requests
        self._workers_per = workers
        self._faults = faults
        self._trace_sample = trace_sample  # None = each loop reads cfg
        # ONE breaker fronts the pool: every replica's submit consults it,
        # and since submits funnel through replica 0 the state machine sees
        # every admission decision for the shared queue
        self.breaker = (
            CircuitBreaker(
                max_queue=self.batcher.max_queue,
                high_frac=serve_cfg.breaker_high_frac,
                low_frac=serve_cfg.breaker_low_frac,
                open_s=serve_cfg.breaker_open_s,
                probes=serve_cfg.breaker_probes,
            )
            if serve_cfg.breaker
            else None
        )
        self._pool_lock = lockdep.Lock("ReplicaPool._pool_lock")
        self._started = False
        self._next_id = n_replicas
        self._replicas = [
            self._make_replica(i) for i in range(n_replicas)
        ]
        # the permanent submit front: replica 0 validates/enqueues into the
        # shared feed without taking the pool lock per request (it is created
        # here and never removed — though supervision may REPLACE the object,
        # atomically repointing this reference)
        self._front = self._replicas[0]
        self._retired: list[ServeLoop] = []
        self._quarantined: list[ServeLoop] = []
        # supervision state (docs/RESILIENCE.md): per-slot restart counts,
        # the jittered-backoff policy, and the seeded rng (the FaultPlan's
        # under chaos, so runs replay; fresh otherwise)
        self._supervise = bool(serve_cfg.supervise)
        self._sup_interval_s = float(serve_cfg.supervise_interval_s)
        self._stall_timeout_s = float(serve_cfg.stall_timeout_s)
        self._policy = RestartPolicy(
            base_s=serve_cfg.restart_backoff_s, budget=serve_cfg.restart_budget
        )
        self._rng = faults.rng if faults is not None else random.Random(0)
        self._restart_counts: dict[str, int] = {}
        self._restart_ts: dict[str, float] = {}
        self._restart_total = 0
        self._sup_stop = threading.Event()
        self._sup_thread: threading.Thread | None = None
        # restart-visibility epoch, pool-level (the pool survives replica
        # restarts; only a PROCESS restart resets these — exactly the event
        # the monitor's counter differencing must re-anchor on)
        self._monitor_t0 = time.monotonic()
        self._start_seq = int(time.time() * 1000)

    def _make_replica(self, i: int) -> ServeLoop:
        return self._new_loop(f"serve-replica-{i}")

    def _new_loop(self, name: str) -> ServeLoop:
        return ServeLoop(
            self.engine,
            batcher=self.batcher,
            metrics=ServeMetrics(sink=self._sink, log_requests=self._log_requests),
            workers=self._workers_per,
            exit_coord=self._exit,
            name=name,
            faults=self._faults,
            breaker=self.breaker,
            trace_sample=self._trace_sample,
        )

    @property
    def replicas(self) -> list[ServeLoop]:
        """Snapshot of the live replica list (copy — the pool can be resized
        by the autoscaler thread while a caller iterates)."""
        with self._pool_lock:
            return list(self._replicas)

    @property
    def n_replicas(self) -> int:
        with self._pool_lock:
            return len(self._replicas)

    @property
    def workers(self) -> int:
        """Total worker threads across the live pool."""
        return sum(r.workers for r in self.replicas)

    def start(self) -> "ReplicaPool":
        if not self.engine._compiled:
            self.engine.warmup()  # ONE warmup, shared by every replica
        if self._own_batcher:
            # post-warmup sync, same as ServeLoop: the measured batching mode
            # decides whether the SHARED feed coalesces or admits continuously
            self.batcher.continuous = self.engine.continuous_admission
        for r in self.replicas:
            r.start()
        self._started = True
        if self._supervise and self._sup_thread is None:
            self._sup_stop.clear()
            self._sup_thread = threading.Thread(
                target=self._supervise_loop, daemon=True, name="serve-supervisor"
            )
            self._sup_thread.start()
        return self

    def stop(self, drain: bool = True) -> None:
        # supervisor first: it must not resurrect the replicas being stopped
        if self._sup_thread is not None:
            self._sup_stop.set()
            self._sup_thread.join(timeout=10.0)
            self._sup_thread = None
        if drain:
            while self.batcher.depth > 0 and self._exit.live() > 0:
                self.batcher.wake.set()
                time.sleep(0.001)
        self._started = False
        with self._pool_lock:
            loops = list(self._replicas) + list(self._quarantined)
        for r in loops:
            r.stop(drain=False)

    # -- supervision (docs/RESILIENCE.md) -----------------------------------

    def _supervise_loop(self) -> None:
        while not self._sup_stop.wait(self._sup_interval_s):
            try:
                self._check_replicas()
            except Exception as e:  # lint: disable=broad-except(the supervisor is the last line of defense — a transient restart failure must be reported and survived, not kill supervision and strand the pool unsupervised; typed errors have nowhere better to go from this thread)
                _emit_event(
                    "supervisor_error", error=f"{type(e).__name__}: {e}"
                )

    def _check_replicas(self) -> None:
        """One supervision sweep: restart (or quarantine) every replica whose
        workers died — or, with ``serve.stall_timeout_s``, whose newest
        heartbeat is stale while work is queued (a hung worker pins requests
        exactly like a crashed one). Deliberately SKIPS replicas that were
        stopped on purpose (``_stop`` set — scale-downs and shutdowns are not
        crashes)."""
        with self._pool_lock:
            snapshot = list(self._replicas)
        now = time.monotonic()
        for loop in snapshot:
            if not loop._started or loop._stop.is_set() or not loop._threads:
                continue
            dead = any(not t.is_alive() for t in loop._threads)
            # progress = the freshest of loop-top heartbeat and last served
            # batch: a worker deep in a LONG (but progressing) dispatch has
            # a stale heartbeat yet a recent dispatch stamp, and must not be
            # restarted as hung. stall_timeout_s must still exceed the
            # worst-case batch service time — docs/RESILIENCE.md (default 0
            # = disabled for exactly this reason).
            progress = max(loop._heartbeat, loop._last_dispatch_ts)
            stalled = (
                self._stall_timeout_s > 0
                and self.batcher.depth > 0
                and progress > 0
                and now - progress > self._stall_timeout_s
            )
            if dead or stalled:
                self._restart_replica(
                    loop, "worker_death" if dead else "worker_stall"
                )

    def _restart_replica(self, loop: ServeLoop, reason: str) -> None:
        slot = loop.name
        now = time.monotonic()
        n = self._restart_counts.get(slot, 0)
        # the budget counts crash LOOPS, not lifetime totals: sustained
        # healthy serving since the last restart forgets the slot's history
        # (a transient fault a day apart must never inch toward quarantine)
        last = self._restart_ts.get(slot)
        if n and last is not None and self._policy.stale(now - last):
            n = 0
            self._restart_counts[slot] = 0
        if self._policy.exhausted(n):
            # crash-looping slot: QUARANTINE — peers keep serving, the event
            # is structured, and the slot stays visible in health() so an
            # operator (or the fleet controller) can act on it
            with self._pool_lock:
                if loop not in self._replicas:
                    return  # scaled away between the sweep and now
                self._replicas.remove(loop)
                self._quarantined.append(loop)
                survivors = list(self._replicas)
            loop.stop(drain=False)
            if self._front is loop and survivors:
                self._front = survivors[0]
            _emit_event(
                "replica_quarantined", replica=slot, reason=reason, restarts=n
            )
            return
        # jittered exponential backoff BEFORE the restart: a crash-looping
        # replica must not hot-spin warm-start cycles (budget bounds the
        # total), and the jitter decorrelates a fleet restarting at once.
        # The wait rides the supervisor's stop event, so pool.stop() can
        # interrupt a long backoff instead of racing a sleeping sweep that
        # would restart a replica into an already-stopped pool
        delay = self._policy.delay(n, self._rng)
        if self._sup_stop.wait(delay):
            return  # the pool is stopping: abort the restart
        loop.stop(drain=False)
        fresh = self._new_loop(slot)
        with self._pool_lock:
            if loop not in self._replicas:
                return  # scaled away while backing off
            self._replicas[self._replicas.index(loop)] = fresh
            self._retired.append(loop)
        self._restart_counts[slot] = n + 1
        self._restart_ts[slot] = time.monotonic()
        self._restart_total += 1
        fresh.metrics.restarts += 1
        if self._front is loop:
            self._front = fresh
        fresh.start()
        _emit_event(
            "replica_restarted",
            replica=slot,
            reason=reason,
            restart=n + 1,
            backoff_s=round(delay, 4),
        )

    # -- elastic scaling (the autoscaler's levers) --------------------------

    def add_replica(self) -> ServeLoop:
        """Grow the pool by one replica under live traffic: the new loop
        shares the batcher, engine (already-warmed executables — zero new
        compiles) and exit coordinator, and starts serving the shared queue
        immediately."""
        with self._pool_lock:
            loop = self._make_replica(self._next_id)
            self._next_id += 1
            self._replicas.append(loop)
            started = self._started
        if started:
            loop.start()
        return loop

    def remove_replica(self) -> ServeLoop | None:
        """Shrink the pool by one replica (never below one; replica 0, the
        submit front, is never the victim). Drain-safe: ``stop(drain=False)``
        only stops THIS replica's workers — they deregister from the shared
        :class:`ExitCoordinator`, and because peers remain live the
        last-worker-out drain cannot fire, so every queued future is drained
        by the survivors (pinned in tests/test_control.py). Returns the
        removed loop (its metrics are retained in :meth:`merged_metrics`),
        or ``None`` when the pool is already at one replica."""
        with self._pool_lock:
            if len(self._replicas) <= 1:
                return None
            loop = self._replicas.pop()
            self._retired.append(loop)
        loop.stop(drain=False)
        return loop

    def scale_to(self, n: int) -> dict:
        """Resize to ``n`` replicas (clamped to >= 1); returns the action
        record the ``{"op": "scale"}`` verb replies with."""
        n = max(1, int(n))
        before = self.n_replicas
        while self.n_replicas < n:
            self.add_replica()
        while self.n_replicas > n:
            if self.remove_replica() is None:
                break
        return {"replicas_before": before, "replicas": self.n_replicas}

    def submit(
        self,
        x: np.ndarray,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
        trace: bool | None = None,
    ) -> Future:
        """Validated enqueue into the SHARED feed (replica 0 fronts it; the
        liveness check is pool-wide through the coordinator, so work is
        accepted as long as ANY replica can serve it)."""
        return self._front.submit(x, rid=rid, deadline_ms=deadline_ms, trace=trace)

    def merged_metrics(self, sink=None) -> ServeMetrics:
        """Every replica's every worker folded into one collector — exact
        quantiles across the whole pool (``Histogram.merge``), retired
        (scaled-down) and quarantined replicas included: the requests they
        served happened."""
        agg = ServeMetrics(sink=sink, log_requests=False)
        with self._pool_lock:
            loops = (
                list(self._replicas) + list(self._retired) + list(self._quarantined)
            )
        for r in loops:
            for m in r._worker_metrics:
                agg.merge(m)
        return agg

    def live_metrics(self) -> dict:
        """Pool-wide ``{"op": "metrics"}`` payload: the merged counters plus
        replica topology and per-replica completion split (the fleet-balance
        view), the shared queue depth, the swap epoch and the routing
        dispatch block — everything the fleet controller's poll consumes."""
        replicas = self.replicas
        return self.merged_metrics().snapshot(
            compile_cache=self.engine.request_path_compiles(),
            workers=self.workers,
            replicas=len(replicas),
            # plain counter sums — a per-replica merged_metrics() here would
            # copy every raw histogram sample once per replica per poll
            replica_completed=[
                sum(m.completed for m in r._worker_metrics) for r in replicas
            ],
            queue_depth_now=self.batcher.depth,
            buckets=list(self.engine.buckets),
            swap_epoch=self.engine.swap_epoch,
            dispatch=self.engine.dispatch_summary(),
            batching=self.engine.batching_summary(),
            breaker=None if self.breaker is None else self.breaker.summary(),
        )

    def health(self) -> dict:
        """The ``{"op": "health"}`` verb: liveness/readiness without touching
        a histogram — warmup state, live vs quarantined replicas, queue
        depth, last-dispatch age, swap epoch, restart count, breaker state.
        This is what a front-door router's health check (and the fleet
        controller) polls at 1 Hz; :meth:`live_metrics` is the heavier
        counters view."""
        with self._pool_lock:
            replicas = list(self._replicas)
            quarantined = [q.name for q in self._quarantined]
        now = time.monotonic()
        live = sum(
            1
            for r in replicas
            if r._threads and all(t.is_alive() for t in r._threads)
        )
        last_ts = max((r._last_dispatch_ts for r in replicas), default=0.0)
        return {
            "warm": bool(getattr(self.engine, "_warm", False)),
            "replicas": len(replicas),
            "replicas_live": live,
            "quarantined": quarantined,
            "workers": sum(r.workers for r in replicas),
            "queue_depth": self.batcher.depth,
            "last_dispatch_age_s": (
                None if last_ts == 0.0 else round(now - last_ts, 4)
            ),
            "swap_epoch": self.engine.swap_epoch,
            "uptime_s": round(now - self._monitor_t0, 3),
            "start_seq": self._start_seq,
            "restarts": self._restart_total,
            "supervised": (
                self._sup_thread is not None and self._sup_thread.is_alive()
            ),
            "breaker": None if self.breaker is None else self.breaker.summary(),
        }


# ---------------------------------------------------------------------------
# Socket front-end (newline-delimited JSON over local TCP)
# ---------------------------------------------------------------------------


def _encode(res) -> dict:
    if isinstance(res, Prediction):
        out = {
            "id": res.rid,
            "ok": True,
            "pred": res.scenario,
            "h": np.asarray(res.h, np.float32).tolist(),
            "latency_ms": round(res.latency_s * 1e3, 3),
            "bucket": res.bucket,
        }
        if res.trace is not None:
            # the optional trace wire field (docs/SERVING.md): phase spans in
            # ms — a fleet router PREPENDS its own pick/wire spans to these
            out["trace"] = res.trace.to_wire()
        return out
    return {"id": res.rid, "ok": False, "reason": res.reason}


class DedupCache:
    """Server-side idempotent-request dedup: explicit request ids map to
    their in-flight (or recently completed) futures for ``ttl_s`` seconds,
    so a client RETRYING an id — after a dropped connection, a timeout, a
    jittered backoff — re-attaches to the original dispatch instead of
    running the request twice (docs/RESILIENCE.md, "retry contract"). The id
    is the idempotency key: reusing one within the TTL intentionally returns
    the original result. Thread-safe (futures resolve on worker threads
    while the event loop inserts)."""

    def __init__(self, ttl_s: float, clock: Callable[[], float] = time.monotonic):
        self.ttl_s = float(ttl_s)
        self.clock = clock
        self._lock = lockdep.Lock("DedupCache._lock")
        self._entries: dict = {}  # rid -> (future, inserted_at)
        self.hits = 0

    def get_or_submit(self, rid, submit: Callable[[], Future]) -> tuple[Future, bool]:
        """The cached future for ``rid`` (hit=True), or ``submit()``'s fresh
        one, recorded. Validation errors from ``submit`` propagate and cache
        nothing — a malformed retry must re-report, not pin the error."""
        now = self.clock()
        with self._lock:
            # amortized O(1) eviction: entries insert in time order (always
            # stamped with the current clock), so expired ones cluster at
            # the head of the insertion-ordered dict — pop until fresh. A
            # full-map rebuild here would be O(live entries) per request ON
            # THE EVENT LOOP (≈ rate · ttl entries), stalling every
            # connected client's reply path under sustained load.
            while self._entries:
                head = next(iter(self._entries))
                if now - self._entries[head][1] < self.ttl_s:
                    break
                del self._entries[head]
            ent = self._entries.get(rid)
            if ent is not None:
                self.hits += 1
                return ent[0], True
        fut = submit()
        with self._lock:
            self._entries[rid] = (fut, now)

        def _forget_unless_served(f, rid=rid):
            # only SERVED results stay pinned: a shed (breaker_open,
            # queue_full, deadline) never dispatched, and a failed dispatch
            # may succeed on retry — caching either would turn one brownout
            # rejection into a TTL-long outage for that id. (f is done here;
            # exception() inspects without re-raising into this callback.)
            keep = f.exception() is None and isinstance(f.result(), Prediction)
            if not keep:
                with self._lock:
                    cur = self._entries.get(rid)
                    if cur is not None and cur[0] is f:
                        del self._entries[rid]

        fut.add_done_callback(_forget_unless_served)
        return fut, False


async def _read_line(reader, timeout_s: float) -> bytes:
    """One framed line with the idle/read timeout applied (``timeout_s <= 0``
    waits forever). Always goes through ``wait_for`` — the unbounded-readline
    lint rule exists because a bare await here is how one dead peer pins a
    connection slot."""
    return await asyncio.wait_for(
        reader.readline(), timeout_s if timeout_s > 0 else None
    )


async def _handle(
    reader,
    writer,
    loop_,
    swap_fn: "Callable[..., dict] | None",
    conn_timeout_s: float = 0.0,
    dedup: DedupCache | None = None,
    ident: dict | None = None,
) -> None:
    try:
        while True:
            try:
                line = await _read_line(reader, conn_timeout_s)
            except asyncio.TimeoutError:
                # dead/stalled peer (or a slow-loris): reap the connection
                # with a typed reply — one silent client must never pin a
                # connection slot forever
                writer.write(b'{"ok": false, "reason": "idle_timeout"}\n')
                await writer.drain()
                break
            except (asyncio.LimitOverrunError, ValueError):
                # a line past serve.max_line_bytes: framing is lost mid-line,
                # so reply typed and CLOSE — resyncing would misparse the
                # oversized tail as fresh requests
                writer.write(
                    b'{"ok": false, "reason": '
                    b'"bad_request: line exceeds serve.max_line_bytes"}\n'
                )
                await writer.drain()
                break
            if not line:
                break
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                # garbage or a partial line (a client that died mid-write):
                # typed reply, connection survives — the NEXT line is framed
                writer.write(b'{"ok": false, "reason": "bad_json"}\n')
                await writer.drain()
                continue
            if isinstance(msg, dict) and msg.get("op") == "health":
                # liveness/readiness verb: cheap by construction (no
                # histogram merge — see ReplicaPool.health), safe to poll at
                # 1 Hz from a router health check or the fleet controller
                reply = {"id": msg.get("id"), "ok": True, "health": loop_.health()}
                if dedup is not None:
                    reply["health"]["dedup_hits"] = dedup.hits
                if ident is not None:
                    # backend identity block (docs/FLEET.md): a front-door
                    # router keys its ejection bookkeeping and per-backend
                    # fleet rows on a STABLE host_id + listen address —
                    # anonymous replies cannot be attributed after a failover
                    reply["health"].update(ident)
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                continue
            if isinstance(msg, dict) and msg.get("op") == "metrics":
                # live observability verb: counters/histograms/compile-cache of
                # the RUNNING server, no restart, no inference submitted. Off the
                # event loop: the merge copies+sorts every raw histogram sample,
                # which is O(requests served) on a long-lived server — it must
                # not stall every connected client's reply path while it runs.
                metrics_view = await asyncio.get_running_loop().run_in_executor(
                    None, loop_.live_metrics
                )
                if ident is not None:
                    metrics_view.update(ident)  # same identity block as health
                reply = {"id": msg.get("id"), "ok": True, "metrics": metrics_view}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                continue
            if isinstance(msg, dict) and msg.get("op") == "events":
                # event-spine tail verb (docs/TELEMETRY.md "event spine"):
                # everything this process published since the caller's
                # cursor, with the explicit loss ledger. Cheap by
                # construction (bounded ring copy under one lock), so it
                # answers inline like health — the monitor's third verb.
                try:
                    cur = msg.get("cursor")
                    if cur is not None and not isinstance(cur, dict):
                        raise ValueError(
                            f"events cursor must be an object, got {cur!r}"
                        )
                    tail = ensure_bus().tail(
                        cur, limit=int(msg.get("limit") or 512)
                    )
                    reply = {"id": msg.get("id"), "ok": True, "events": tail}
                except (TypeError, ValueError) as e:
                    reply = {"id": msg.get("id"), "ok": False,
                             "reason": f"bad_request: {e}"}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                continue
            if isinstance(msg, dict) and msg.get("op") == "swap":
                # zero-downtime deploy verb: re-restore the newest checkpoints
                # (or the EXPLICIT per-family "tags" the client pins — the
                # deployer's path, so a stale *_best can never shadow a freshly
                # fine-tuned *_last) and hot-swap them under live traffic
                # (engine.swap_params — zero recompiles, in-flight batches keep
                # the old params). Off the event loop: the orbax restore +
                # device_put is host work that must not stall connected clients'
                # reply paths.
                if swap_fn is None:
                    reply = {"id": msg.get("id"), "ok": False,
                             "reason": "swap_unavailable: server has no checkpoint workdir"}
                else:
                    try:
                        tags = msg.get("tags")
                        if tags is not None and not (
                            isinstance(tags, dict)
                            and all(
                                isinstance(k, str) and isinstance(v, str)
                                for k, v in tags.items()
                            )
                        ):
                            raise ValueError(f"swap tags must be a str->str map, got {tags!r}")
                        rec = await asyncio.get_running_loop().run_in_executor(
                            None, swap_fn, tags
                        )
                        reply = {"id": msg.get("id"), "ok": True, "swap": rec}
                    except (FileNotFoundError, ValueError, RuntimeError) as e:
                        # a missing/mismatched/CORRUPT checkpoint is a
                        # client-visible deploy failure (CheckpointRestoreError
                        # lands here too), not a reason to kill the server —
                        # the old params keep serving (swap validated first)
                        reply = {"id": msg.get("id"), "ok": False,
                                 "reason": f"swap_failed: {e}"}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                continue
            if isinstance(msg, dict) and msg.get("op") == "scale":
                # replica autoscaling verb: resize the pool under live traffic
                # (drain-safe — ReplicaPool.remove_replica never sheds a queue
                # peers still drain). The fleet controller's remote lever.
                if not hasattr(loop_, "scale_to"):
                    reply = {"id": msg.get("id"), "ok": False,
                             "reason": "scale_unavailable: server is not a replica pool"}
                else:
                    try:
                        n = int(msg["replicas"])
                        rec = await asyncio.get_running_loop().run_in_executor(
                            None, loop_.scale_to, n
                        )
                        reply = {"id": msg.get("id"), "ok": True, "scale": rec}
                    except (KeyError, TypeError, ValueError) as e:
                        reply = {"id": msg.get("id"), "ok": False,
                                 "reason": f"bad_request: {e}"}
                writer.write((json.dumps(reply) + "\n").encode())
                await writer.drain()
                continue
            try:
                # every well-formed line gets a typed reply — a missing/ragged
                # "x", a non-object message, a bad deadline are client errors,
                # not reasons to drop the connection (or touch the worker).
                # Explicit ids are IDEMPOTENCY KEYS: a retried id within the
                # dedup TTL re-attaches to the original dispatch (never
                # double-dispatches) and gets the identical reply.
                rid = msg.get("id") if isinstance(msg, dict) else None

                def _submit(m=msg):
                    return loop_.submit(
                        np.asarray(m["x"], np.float32),
                        rid=m.get("id"),
                        deadline_ms=m.get("deadline_ms"),
                        # optional wire field: "trace": true forces a phase
                        # trace for THIS request (a router propagating its
                        # sampling decision downstream); absent = the
                        # server's own serve.trace_sample decides
                        trace=True if m.get("trace") else None,
                    )

                if dedup is not None and rid is not None:
                    fut, _ = dedup.get_or_submit(rid, _submit)
                else:
                    fut = _submit()
            except (KeyError, TypeError, ValueError) as e:
                rid = msg.get("id") if isinstance(msg, dict) else None
                writer.write(
                    (json.dumps({"id": rid, "ok": False, "reason": f"bad_request: {e}"}) + "\n").encode()
                )
                await writer.drain()
                continue
            try:
                res = await asyncio.wrap_future(fut)
            except Exception as e:  # lint: disable=broad-except(the serve loop forwards ANY dispatch failure — engine errors, injected chaos faults, DivergenceError from serve.checkify — into the future; the client must get a TYPED server_error reply it can retry (the dedup cache already forgot the failed id), not a dropped connection and an unretrieved-task warning)
                writer.write(
                    (json.dumps({
                        "id": rid, "ok": False,
                        "reason": f"server_error: {type(e).__name__}: {e}",
                    }) + "\n").encode()
                )
                await writer.drain()
                continue
            writer.write((json.dumps(_encode(res)) + "\n").encode())
            await writer.drain()
    except (ConnectionResetError, BrokenPipeError):
        # the peer vanished mid-exchange (socket_drop chaos class, a killed
        # client): nothing to tell them, nothing stranded — any in-flight
        # future resolved above (or resolves server-side and is dropped),
        # and the dedup cache keeps the result for the retry
        pass
    finally:
        try:
            writer.close()
        except RuntimeError:
            pass  # event loop already closed: test/server teardown path


async def serve_async(
    loop_,
    host: str,
    port: int,
    ready: "asyncio.Future | None" = None,
    swap_fn: "Callable[..., dict] | None" = None,
    conn_timeout_s: float | None = None,
    max_line_bytes: int | None = None,
    dedup_ttl_s: float | None = None,
    host_id: str | None = None,
) -> None:
    """Accept connections until cancelled; resolves ``ready`` with the bound
    port (port=0 binds an ephemeral port — how the tests avoid collisions).
    ``loop_`` is a :class:`ServeLoop` or :class:`ReplicaPool` (both expose
    ``submit``/``live_metrics``/``health``; a pool additionally serves the
    ``{"op": "scale"}`` autoscaling verb); ``swap_fn(tags=None)`` arms the
    ``{"op": "swap"}`` verb. The hardening knobs (per-connection idle/read
    timeout, max line bytes, dedup TTL) default to the serving config's
    values (``serve.conn_timeout_s`` / ``max_line_bytes`` / ``dedup_ttl_s``);
    pass explicit values to override. ``host_id`` is the stable backend
    identity stamped (with the listen address) into every ``health`` and
    ``metrics`` reply — the fleet router's ejection bookkeeping and
    per-backend rows key on it; the default is unique per process AND per
    listening endpoint, so in-process multi-server tests never collide."""
    serve_cfg = loop_.engine.cfg.serve
    conn_timeout_s = (
        serve_cfg.conn_timeout_s if conn_timeout_s is None else conn_timeout_s
    )
    max_line_bytes = (
        serve_cfg.max_line_bytes if max_line_bytes is None else max_line_bytes
    )
    dedup_ttl_s = serve_cfg.dedup_ttl_s if dedup_ttl_s is None else dedup_ttl_s
    dedup = DedupCache(dedup_ttl_s) if dedup_ttl_s > 0 else None
    ident_box: dict = {}
    server = await asyncio.start_server(
        lambda r, w: _handle(
            r, w, loop_, swap_fn, conn_timeout_s=conn_timeout_s, dedup=dedup,
            ident=ident_box,
        ),
        host=host,
        port=port,
        limit=max_line_bytes,
    )
    bound = server.sockets[0].getsockname()[1]
    if host_id is None:
        host_id = f"{socket.gethostname()}-{os.getpid()}-p{bound}"
    ident_box.update({"host_id": host_id, "listen": f"{host}:{bound}"})
    if ready is not None and not ready.done():
        ready.set_result(bound)
    async with server:
        await server.serve_forever()


def run_server(
    cfg: ExperimentConfig,
    engine: ServeEngine,
    logger=None,
    workdir: str | None = None,
) -> None:
    """Blocking entry for ``qdml-tpu serve``: warm, bind, announce, serve
    until interrupted; flush serving counters on the way out. ``workdir``
    arms the ``{"op": "swap"}`` hot-swap verb (re-restore newest checkpoints
    live). The startup banner prints AFTER the socket is bound with the
    ACTUAL port (``--serve.port=0`` binds an ephemeral one) plus the stable
    ``host_id`` — how a fleet-router spawner (fleet/spawn.py) learns where a
    backend it launched actually listens."""
    pool = ReplicaPool(engine, workers=cfg.serve.workers).start()
    host_id = f"{socket.gethostname()}-{os.getpid()}"
    swap_fn = (
        None
        if workdir is None
        else (lambda tags=None: engine.swap_from_workdir(workdir, tags=tags))
    )

    async def _serve_announced() -> None:
        aloop = asyncio.get_running_loop()
        ready: asyncio.Future = aloop.create_future()
        task = aloop.create_task(
            serve_async(
                pool, cfg.serve.host, cfg.serve.port, ready,
                swap_fn=swap_fn, host_id=host_id,
            )
        )
        # wait on BOTH: a bind failure must propagate, not hang on `ready`
        await asyncio.wait({task, ready}, return_when=asyncio.FIRST_COMPLETED)
        if task.done():
            return task.result()  # lint: disable=sync-io-in-async(task.done() was just checked: result() on a completed future returns immediately, it only propagates the bind failure)
        print(
            json.dumps(
                {
                    "serving": f"{cfg.serve.host}:{ready.result()}",  # lint: disable=sync-io-in-async(FIRST_COMPLETED with task not done means ready resolved: result() on a completed future returns immediately)
                    "host_id": host_id,
                    "buckets": list(engine.buckets),
                    "batching": engine.batching_summary(),
                    "replicas": pool.n_replicas,
                    "workers": pool.workers,
                    "supervised": cfg.serve.supervise,
                    "breaker": cfg.serve.breaker,
                    "mesh": engine.mesh_topology(),
                    "sharding": engine.bucket_sharding or None,
                    # post-warmup counters: anything non-zero here (or later)
                    # is a compile the warmup failed to cover
                    "compile_cache_after_warmup": engine.request_path_compiles(),
                    # per-bucket XLA cost accounting from the AOT warmup
                    "cost": engine.bucket_cost,
                }
            ),
            flush=True,
        )
        await task

    try:
        asyncio.run(_serve_announced())
    except KeyboardInterrupt:
        pass
    finally:
        pool.stop(drain=False)
        # merged across every replica's workers: the same aggregate the
        # metrics verb serves
        pool.merged_metrics().flush(
            compile_cache=engine.request_path_compiles(),
            workers=pool.workers,
            replicas=pool.n_replicas,
            breaker=None if pool.breaker is None else pool.breaker.summary(),
        )
