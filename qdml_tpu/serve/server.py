"""Serve loop, replica pool + local socket front-end.

:class:`ServeLoop` is the in-process serving core: worker thread(s) that
drain the micro-batcher — shed results resolve immediately, ready batches go
through the engine's pre-compiled executables, and every request's future
resolves with a typed :class:`~qdml_tpu.serve.types.Prediction` or
:class:`~qdml_tpu.serve.types.Overloaded`. :class:`ReplicaPool` runs N of
them over ONE shared micro-batcher against ONE warmed engine (one warmup,
one autotune table, one set of AOT executables), with per-replica
:class:`~qdml_tpu.serve.metrics.ServeMetrics` merged exactly via
``Histogram.merge`` — the fleet story of docs/SERVING.md. The loadgen
harness and the smoke tests drive these objects directly; the socket server
below is a thin framing layer over either.

Exit discipline: every worker of every replica registers with one
:class:`ExitCoordinator`. A crashed (or stopped) worker must never shed the
shared queue while ANY peer — same replica or not — can still serve it; the
LAST worker out pool-wide always drains, so nothing strands either way (the
PR-3 hazard, generalized from one loop's threads to the whole pool).

``qdml-tpu serve`` runs :func:`run_server`: an asyncio loop accepting
newline-delimited JSON over a local TCP socket (``{"id", "x", [deadline_ms]}``
-> ``{"id", "ok", "pred", "h", "latency_ms"}`` or
``{"id", "ok": false, "reason"}``), plus the ``{"op": "metrics"}`` live
observability verb and the ``{"op": "swap"}`` zero-downtime checkpoint
hot-swap verb (re-restores the newest checkpoints and swaps them under live
traffic with zero recompiles — docs/SERVING.md). One engine, one batcher:
concurrent connections coalesce into the same buckets, which is the entire
point of dynamic micro-batching.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from concurrent.futures import Future
from typing import Callable

import numpy as np

from qdml_tpu.config import ExperimentConfig
from qdml_tpu.serve.batcher import MicroBatcher
from qdml_tpu.serve.engine import ServeEngine
from qdml_tpu.serve.metrics import ServeMetrics
from qdml_tpu.serve.types import SHUTDOWN, Overloaded, Prediction, Request


class ExitCoordinator:
    """Worker-liveness accounting shared by every loop draining one batcher.

    One instance per ServeLoop by default; a :class:`ReplicaPool` injects a
    single shared instance into all its replicas, so "am I the last worker
    out" (the drain trigger) and "is anyone still serving" (the submit
    liveness check) are pool-wide facts, not per-loop guesses.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._live = 0

    def enter(self, n: int) -> None:
        with self._lock:
            self._live += n

    def leave(self) -> bool:
        """Deregister one worker; True iff it was the last one pool-wide."""
        with self._lock:
            self._live -= 1
            return self._live <= 0

    def live(self) -> int:
        with self._lock:
            return self._live


class ServeLoop:
    """Worker thread(s) pumping batcher -> engine -> futures.

    ``workers`` (default ``cfg.serve.workers``) threads share the one
    batcher and engine; each records into its OWN :class:`ServeMetrics`
    (no cross-thread contention on the hot path) and
    :meth:`merged_metrics`/:meth:`live_metrics` aggregate them exactly via
    ``Histogram.merge``. ``self.metrics`` is worker 0's collector — the
    single-worker default keeps the PR-2 behavior and tests unchanged.
    ``exit_coord`` shares worker-exit accounting across loops (the replica
    pool passes one coordinator to all replicas); ``name`` labels the
    threads.
    """

    def __init__(
        self,
        engine: ServeEngine,
        batcher: MicroBatcher | None = None,
        metrics: ServeMetrics | None = None,
        workers: int | None = None,
        exit_coord: ExitCoordinator | None = None,
        name: str = "serve-loop",
    ):
        serve_cfg = engine.cfg.serve
        self.engine = engine
        self.name = name
        # remember whether the batcher is loop-owned: start() syncs an owned
        # batcher's admission policy (coalesce vs continuous) from the warmed
        # engine's measured batching mode; an injected batcher is the
        # caller's to configure (the replica pool injects its shared one and
        # syncs it itself; fake-clock tests pin the policy they test)
        self._own_batcher = batcher is None
        self.batcher = batcher or MicroBatcher(
            max_batch=serve_cfg.max_batch,
            max_wait_s=serve_cfg.max_wait_ms / 1e3,
            max_queue=serve_cfg.max_queue,
            continuous=engine.continuous_admission,
        )
        self.metrics = metrics or ServeMetrics()
        self.workers = max(1, int(workers if workers is not None else serve_cfg.workers))
        self._worker_metrics = [self.metrics] + [
            ServeMetrics(
                sink=self.metrics._sink, log_requests=self.metrics.log_requests
            )
            for _ in range(self.workers - 1)
        ]
        self._default_deadline_s = (
            serve_cfg.deadline_ms / 1e3 if serve_cfg.deadline_ms > 0 else None
        )
        self._stop = threading.Event()
        # wake rides on the BATCHER (its owner): pool replicas share the
        # queue, so a submit must reach whichever loop's worker is idle
        self._wake = self.batcher.wake
        self._threads: list[threading.Thread] = []
        self._exit = exit_coord or ExitCoordinator()
        self._started = False  # stays True after stop(): a finished loop rejects
        self._rid = 0

    # -- client side --------------------------------------------------------

    def submit(
        self,
        x: np.ndarray,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
    ) -> Future:
        """Enqueue one request; the returned future resolves with a
        Prediction or Overloaded (never raises for overload). A malformed
        payload raises ``ValueError`` HERE, synchronously — client errors
        must never reach the worker, where one bad shape would crash the
        batch it was coalesced into."""
        x = np.asarray(x, np.float32)
        expect = (*self.engine.cfg.image_hw, 2)
        if x.shape != expect:
            raise ValueError(f"request x has shape {x.shape}, expected {expect}")
        if rid is None:
            self._rid += 1
            rid = self._rid
        if self._started and self._exit.live() <= 0:
            # no worker anywhere in the pool can serve this: the queue would
            # grow with futures nobody will ever resolve (clients hung
            # forever behind a server that still accepts connections).
            # Submits before start() are fine — start() will drain them; a
            # crashed worker with live peers is fine too — the coordinator
            # counts pool-wide, and the peers drain the shared queue.
            fut: Future = Future()
            fut.set_result(Overloaded(rid, SHUTDOWN))
            return fut
        now = self.batcher.clock()
        deadline_s = (
            deadline_ms / 1e3 if deadline_ms is not None else self._default_deadline_s
        )
        req = Request(
            rid=rid,
            x=x,
            deadline=None if deadline_s is None else now + deadline_s,
            future=Future(),
        )
        rejected = self.batcher.submit(req, now=now)
        if rejected is not None:
            self.metrics.observe_shed(rejected, had_deadline=req.deadline is not None)
            req.future.set_result(rejected)
        return req.future

    # -- worker side --------------------------------------------------------

    def start(self) -> "ServeLoop":
        if not self.engine._compiled:
            self.engine.warmup()
        if self._own_batcher:
            # the "auto" batching race resolves at warmup, after the batcher
            # exists: sync the admission policy to the measured mode (ragged
            # -> continuous dispatch, bucket -> coalesce to bucket edges)
            self.batcher.continuous = self.engine.continuous_admission
        self._stop.clear()
        self._threads = [
            threading.Thread(
                target=self._run,
                args=(self._worker_metrics[i],),
                daemon=True,
                name=f"{self.name}-{i}",
            )
            for i in range(self.workers)
        ]
        self._started = True
        # register BEFORE the threads run: a submit racing start() must see
        # the pool as live (the coordinator is the liveness source of truth)
        self._exit.enter(len(self._threads))
        for t in self._threads:
            t.start()
        return self

    def stop(self, drain: bool = True) -> None:
        """Stop the workers; with ``drain`` (default) only after the queue
        has emptied, so every submitted future resolves. When pool PEERS
        share the batcher, draining is their job — a scaled-down replica
        must not block on a feed that live peers keep refilling (and they,
        or the pool-wide last-worker-out drain, resolve every future)."""
        if not self._threads:
            return
        if drain:
            while (
                self.batcher.depth > 0
                and 0 < self._exit.live() <= sum(t.is_alive() for t in self._threads)
            ):
                self._wake.set()
                time.sleep(0.001)
        self._stop.set()
        self._wake.set()
        for t in self._threads:
            t.join(timeout=10.0)
        self._threads = []

    def merged_metrics(self, sink=None) -> ServeMetrics:
        """All workers' collectors folded into one fresh ServeMetrics (exact
        quantile aggregation — ``Histogram.merge`` keeps raw samples).
        ``sink`` binds the aggregate's flush target (loadgen passes its
        logger's telemetry stream)."""
        agg = ServeMetrics(sink=sink, log_requests=False)
        for m in self._worker_metrics:
            agg.merge(m)
        return agg

    def live_metrics(self) -> dict:
        """The ``{"op": "metrics"}`` serve-verb payload: merged per-worker
        counters/histograms, current queue depth, bucket layout, swap epoch,
        and the request-path compile-cache snapshot — a running server is
        observable without restarting it. Safe to call any time (also after
        stop)."""
        return self.merged_metrics().snapshot(
            compile_cache=self.engine.request_path_compiles(),
            workers=self.workers,
            queue_depth_now=self.batcher.depth,
            buckets=list(self.engine.buckets),
            swap_epoch=self.engine.swap_epoch,
            dispatch=self.engine.dispatch_summary(),
            batching=self.engine.batching_summary(),
        )

    def _serve_one(self, metrics: ServeMetrics | None = None) -> bool:
        """Single batcher pump: resolve sheds, serve at most one batch.
        Returns True when any work happened (the loop's idle detector).
        ``metrics`` is the calling worker's collector (worker 0's when
        driven directly, e.g. by the fake-clock tests)."""
        metrics = metrics if metrics is not None else self.metrics
        depth = self.batcher.depth
        batch, shed = self.batcher.next_batch()
        for r, o in shed:
            # dequeue sheds are deadline expiries by construction
            metrics.observe_shed(o, had_deadline=True)
            if r.future is not None:
                r.future.set_result(o)
        if not batch:
            return bool(shed)
        t0 = time.perf_counter()
        try:
            # stack INSIDE the guard: a shape-mismatched request failing the
            # stack must strand nobody, exactly like an engine failure
            x = np.stack([r.x for r in batch])
            h, pred, conf, info = self.engine.infer(x)
        except BaseException as e:
            # a dying batch must not strand its clients: forward the failure
            # into every future, then let the loop's finally drain the rest
            for r in batch:
                if r.future is not None and not r.future.done():
                    r.future.set_exception(e)
            raise
        dur = time.perf_counter() - t0
        now = self.batcher.clock()
        preds = []
        for i, r in enumerate(batch):
            p = Prediction(
                rid=r.rid,
                h=h[i],
                scenario=int(pred[i]),
                latency_s=now - r.enqueue_ts,
                bucket=info.bucket,
                batch_n=len(batch),
                deadline_met=None if r.deadline is None else now <= r.deadline,
                confidence=float(conf[i]),
            )
            preds.append(p)
        # metrics before resolution: a client awaiting the future must be able
        # to read a consistent histogram the moment its result arrives
        metrics.observe_batch(preds, info, depth, dur)
        for r, p in zip(batch, preds):
            if r.future is not None:
                r.future.set_result(p)
        return True

    def _run(self, metrics: ServeMetrics) -> None:
        try:
            while not self._stop.is_set():
                if not self._serve_one(metrics):
                    # idle: sleep until the oldest request ages out or a submit wakes us
                    self._wake.wait(timeout=max(self.batcher.wait_hint(), 1e-4))
                    self._wake.clear()
        finally:
            # shutdown OR crash: resolve EVERYTHING still queued (no silent
            # hangs) — but only once no OTHER worker, in THIS loop or any
            # pool peer sharing the batcher, can still serve it. A single
            # crashed worker (or a stopped replica) must not shed a queue
            # its surviving peers are actively draining; the LAST worker out
            # pool-wide always drains, so nothing strands either way.
            last_out = self._exit.leave()
            while last_out:
                batch, shed = self.batcher.next_batch(now=float("inf"))
                if not batch and not shed:
                    break
                for r, o in shed:
                    metrics.observe_shed(o, had_deadline=True)
                    if r.future is not None:
                        r.future.set_result(o)
                for r in batch:
                    if r.future is not None:
                        r.future.set_result(
                            Overloaded(r.rid, SHUTDOWN)
                        )


class ReplicaPool:
    """N ServeLoops over one shared batcher, one engine, one warmup.

    The fleet unit of docs/SERVING.md: every replica pumps the SAME
    :class:`MicroBatcher` feed through the SAME warmed engine (one set of
    AOT executables, one autotune table — warmup runs exactly once however
    many replicas serve), with per-replica/per-worker :class:`ServeMetrics`
    merged exactly via ``Histogram.merge`` on demand. One
    :class:`ExitCoordinator` spans the pool, so submit-liveness and
    last-worker-out draining are pool-wide facts. A checkpoint hot-swap on
    the shared engine (``engine.swap_params``) lands on every replica at
    once — each batch reads the live param tuple at dequeue.

    The pool is ELASTIC: :meth:`add_replica` / :meth:`remove_replica` /
    :meth:`scale_to` resize it under live traffic (the autoscaler's levers,
    docs/CONTROL.md). Removal is drain-safe by construction: the departing
    replica's workers deregister from the SHARED coordinator, and because
    live peers remain, the last-worker-out drain never fires — the shared
    queue keeps being pumped by the survivors and no submitted future is
    ever shed by a scale-down. Replica 0 is the permanent submit front and
    is never removed. Removed replicas land in a retired list so their
    histograms stay in :meth:`merged_metrics` (a scale-down must not vanish
    the requests it already served).
    """

    def __init__(
        self,
        engine: ServeEngine,
        replicas: int | None = None,
        batcher: MicroBatcher | None = None,
        workers: int | None = None,
        sink=None,
        log_requests: bool = True,
    ):
        serve_cfg = engine.cfg.serve
        self.engine = engine
        n_replicas = max(
            1, int(replicas if replicas is not None else serve_cfg.replicas)
        )
        self._own_batcher = batcher is None
        self.batcher = batcher or MicroBatcher(
            max_batch=serve_cfg.max_batch,
            max_wait_s=serve_cfg.max_wait_ms / 1e3,
            max_queue=serve_cfg.max_queue,
            continuous=engine.continuous_admission,
        )
        self._exit = ExitCoordinator()
        self._sink = sink
        self._log_requests = log_requests
        self._workers_per = workers
        self._pool_lock = threading.Lock()
        self._started = False
        self._next_id = n_replicas
        self._replicas = [
            self._make_replica(i) for i in range(n_replicas)
        ]
        # the permanent submit front: replica 0 validates/enqueues into the
        # shared feed without taking the pool lock per request (it is created
        # here and never removed, so the hot path needs no synchronization)
        self._front = self._replicas[0]
        self._retired: list[ServeLoop] = []

    def _make_replica(self, i: int) -> ServeLoop:
        return ServeLoop(
            self.engine,
            batcher=self.batcher,
            metrics=ServeMetrics(sink=self._sink, log_requests=self._log_requests),
            workers=self._workers_per,
            exit_coord=self._exit,
            name=f"serve-replica-{i}",
        )

    @property
    def replicas(self) -> list[ServeLoop]:
        """Snapshot of the live replica list (copy — the pool can be resized
        by the autoscaler thread while a caller iterates)."""
        with self._pool_lock:
            return list(self._replicas)

    @property
    def n_replicas(self) -> int:
        with self._pool_lock:
            return len(self._replicas)

    @property
    def workers(self) -> int:
        """Total worker threads across the live pool."""
        return sum(r.workers for r in self.replicas)

    def start(self) -> "ReplicaPool":
        if not self.engine._compiled:
            self.engine.warmup()  # ONE warmup, shared by every replica
        if self._own_batcher:
            # post-warmup sync, same as ServeLoop: the measured batching mode
            # decides whether the SHARED feed coalesces or admits continuously
            self.batcher.continuous = self.engine.continuous_admission
        for r in self.replicas:
            r.start()
        self._started = True
        return self

    def stop(self, drain: bool = True) -> None:
        if drain:
            while self.batcher.depth > 0 and self._exit.live() > 0:
                self.batcher.wake.set()
                time.sleep(0.001)
        self._started = False
        for r in self.replicas:
            r.stop(drain=False)

    # -- elastic scaling (the autoscaler's levers) --------------------------

    def add_replica(self) -> ServeLoop:
        """Grow the pool by one replica under live traffic: the new loop
        shares the batcher, engine (already-warmed executables — zero new
        compiles) and exit coordinator, and starts serving the shared queue
        immediately."""
        with self._pool_lock:
            loop = self._make_replica(self._next_id)
            self._next_id += 1
            self._replicas.append(loop)
            started = self._started
        if started:
            loop.start()
        return loop

    def remove_replica(self) -> ServeLoop | None:
        """Shrink the pool by one replica (never below one; replica 0, the
        submit front, is never the victim). Drain-safe: ``stop(drain=False)``
        only stops THIS replica's workers — they deregister from the shared
        :class:`ExitCoordinator`, and because peers remain live the
        last-worker-out drain cannot fire, so every queued future is drained
        by the survivors (pinned in tests/test_control.py). Returns the
        removed loop (its metrics are retained in :meth:`merged_metrics`),
        or ``None`` when the pool is already at one replica."""
        with self._pool_lock:
            if len(self._replicas) <= 1:
                return None
            loop = self._replicas.pop()
            self._retired.append(loop)
        loop.stop(drain=False)
        return loop

    def scale_to(self, n: int) -> dict:
        """Resize to ``n`` replicas (clamped to >= 1); returns the action
        record the ``{"op": "scale"}`` verb replies with."""
        n = max(1, int(n))
        before = self.n_replicas
        while self.n_replicas < n:
            self.add_replica()
        while self.n_replicas > n:
            if self.remove_replica() is None:
                break
        return {"replicas_before": before, "replicas": self.n_replicas}

    def submit(
        self,
        x: np.ndarray,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
    ) -> Future:
        """Validated enqueue into the SHARED feed (replica 0 fronts it; the
        liveness check is pool-wide through the coordinator, so work is
        accepted as long as ANY replica can serve it)."""
        return self._front.submit(x, rid=rid, deadline_ms=deadline_ms)

    def merged_metrics(self, sink=None) -> ServeMetrics:
        """Every replica's every worker folded into one collector — exact
        quantiles across the whole pool (``Histogram.merge``), retired
        (scaled-down) replicas included: the requests they served happened."""
        agg = ServeMetrics(sink=sink, log_requests=False)
        with self._pool_lock:
            loops = list(self._replicas) + list(self._retired)
        for r in loops:
            for m in r._worker_metrics:
                agg.merge(m)
        return agg

    def live_metrics(self) -> dict:
        """Pool-wide ``{"op": "metrics"}`` payload: the merged counters plus
        replica topology and per-replica completion split (the fleet-balance
        view), the shared queue depth, the swap epoch and the routing
        dispatch block — everything the fleet controller's poll consumes."""
        replicas = self.replicas
        return self.merged_metrics().snapshot(
            compile_cache=self.engine.request_path_compiles(),
            workers=self.workers,
            replicas=len(replicas),
            # plain counter sums — a per-replica merged_metrics() here would
            # copy every raw histogram sample once per replica per poll
            replica_completed=[
                sum(m.completed for m in r._worker_metrics) for r in replicas
            ],
            queue_depth_now=self.batcher.depth,
            buckets=list(self.engine.buckets),
            swap_epoch=self.engine.swap_epoch,
            dispatch=self.engine.dispatch_summary(),
            batching=self.engine.batching_summary(),
        )


# ---------------------------------------------------------------------------
# Socket front-end (newline-delimited JSON over local TCP)
# ---------------------------------------------------------------------------


def _encode(res) -> dict:
    if isinstance(res, Prediction):
        return {
            "id": res.rid,
            "ok": True,
            "pred": res.scenario,
            "h": np.asarray(res.h, np.float32).tolist(),
            "latency_ms": round(res.latency_s * 1e3, 3),
            "bucket": res.bucket,
        }
    return {"id": res.rid, "ok": False, "reason": res.reason}


async def _handle(reader, writer, loop_, swap_fn: "Callable[..., dict] | None") -> None:
    while True:
        line = await reader.readline()
        if not line:
            break
        try:
            msg = json.loads(line)
        except json.JSONDecodeError:
            writer.write(b'{"ok": false, "reason": "bad_json"}\n')
            await writer.drain()
            continue
        if isinstance(msg, dict) and msg.get("op") == "metrics":
            # live observability verb: counters/histograms/compile-cache of
            # the RUNNING server, no restart, no inference submitted. Off the
            # event loop: the merge copies+sorts every raw histogram sample,
            # which is O(requests served) on a long-lived server — it must
            # not stall every connected client's reply path while it runs.
            metrics_view = await asyncio.get_running_loop().run_in_executor(
                None, loop_.live_metrics
            )
            reply = {"id": msg.get("id"), "ok": True, "metrics": metrics_view}
            writer.write((json.dumps(reply) + "\n").encode())
            await writer.drain()
            continue
        if isinstance(msg, dict) and msg.get("op") == "swap":
            # zero-downtime deploy verb: re-restore the newest checkpoints
            # (or the EXPLICIT per-family "tags" the client pins — the
            # deployer's path, so a stale *_best can never shadow a freshly
            # fine-tuned *_last) and hot-swap them under live traffic
            # (engine.swap_params — zero recompiles, in-flight batches keep
            # the old params). Off the event loop: the orbax restore +
            # device_put is host work that must not stall connected clients'
            # reply paths.
            if swap_fn is None:
                reply = {"id": msg.get("id"), "ok": False,
                         "reason": "swap_unavailable: server has no checkpoint workdir"}
            else:
                try:
                    tags = msg.get("tags")
                    if tags is not None and not (
                        isinstance(tags, dict)
                        and all(
                            isinstance(k, str) and isinstance(v, str)
                            for k, v in tags.items()
                        )
                    ):
                        raise ValueError(f"swap tags must be a str->str map, got {tags!r}")
                    rec = await asyncio.get_running_loop().run_in_executor(
                        None, swap_fn, tags
                    )
                    reply = {"id": msg.get("id"), "ok": True, "swap": rec}
                except (FileNotFoundError, ValueError, RuntimeError) as e:
                    # a missing/mismatched checkpoint is a client-visible
                    # deploy failure, not a reason to kill the server — the
                    # old params keep serving (swap_params validated first)
                    reply = {"id": msg.get("id"), "ok": False,
                             "reason": f"swap_failed: {e}"}
            writer.write((json.dumps(reply) + "\n").encode())
            await writer.drain()
            continue
        if isinstance(msg, dict) and msg.get("op") == "scale":
            # replica autoscaling verb: resize the pool under live traffic
            # (drain-safe — ReplicaPool.remove_replica never sheds a queue
            # peers still drain). The fleet controller's remote lever.
            if not hasattr(loop_, "scale_to"):
                reply = {"id": msg.get("id"), "ok": False,
                         "reason": "scale_unavailable: server is not a replica pool"}
            else:
                try:
                    n = int(msg["replicas"])
                    rec = await asyncio.get_running_loop().run_in_executor(
                        None, loop_.scale_to, n
                    )
                    reply = {"id": msg.get("id"), "ok": True, "scale": rec}
                except (KeyError, TypeError, ValueError) as e:
                    reply = {"id": msg.get("id"), "ok": False,
                             "reason": f"bad_request: {e}"}
            writer.write((json.dumps(reply) + "\n").encode())
            await writer.drain()
            continue
        try:
            # every well-formed line gets a typed reply — a missing/ragged
            # "x", a non-object message, a bad deadline are client errors,
            # not reasons to drop the connection (or touch the worker)
            fut = loop_.submit(
                np.asarray(msg["x"], np.float32),
                rid=msg.get("id"),
                deadline_ms=msg.get("deadline_ms"),
            )
        except (KeyError, TypeError, ValueError) as e:
            rid = msg.get("id") if isinstance(msg, dict) else None
            writer.write(
                (json.dumps({"id": rid, "ok": False, "reason": f"bad_request: {e}"}) + "\n").encode()
            )
            await writer.drain()
            continue
        res = await asyncio.wrap_future(fut)
        writer.write((json.dumps(_encode(res)) + "\n").encode())
        await writer.drain()
    writer.close()


async def serve_async(
    loop_,
    host: str,
    port: int,
    ready: "asyncio.Future | None" = None,
    swap_fn: "Callable[..., dict] | None" = None,
) -> None:
    """Accept connections until cancelled; resolves ``ready`` with the bound
    port (port=0 binds an ephemeral port — how the tests avoid collisions).
    ``loop_`` is a :class:`ServeLoop` or :class:`ReplicaPool` (both expose
    ``submit``/``live_metrics``; a pool additionally serves the ``{"op":
    "scale"}`` autoscaling verb); ``swap_fn(tags=None)`` arms the ``{"op":
    "swap"}`` verb (``tags`` pins explicit checkpoint tags per family)."""
    server = await asyncio.start_server(
        lambda r, w: _handle(r, w, loop_, swap_fn), host=host, port=port
    )
    bound = server.sockets[0].getsockname()[1]
    if ready is not None and not ready.done():
        ready.set_result(bound)
    async with server:
        await server.serve_forever()


def run_server(
    cfg: ExperimentConfig,
    engine: ServeEngine,
    logger=None,
    workdir: str | None = None,
) -> None:
    """Blocking entry for ``qdml-tpu serve``: warm, announce, serve until
    interrupted; flush serving counters on the way out. ``workdir`` arms the
    ``{"op": "swap"}`` hot-swap verb (re-restore newest checkpoints live)."""
    pool = ReplicaPool(engine, workers=cfg.serve.workers).start()
    print(
        json.dumps(
            {
                "serving": f"{cfg.serve.host}:{cfg.serve.port}",
                "buckets": list(engine.buckets),
                "batching": engine.batching_summary(),
                "replicas": pool.n_replicas,
                "workers": pool.workers,
                "mesh": engine.mesh_topology(),
                "sharding": engine.bucket_sharding or None,
                # post-warmup counters: anything non-zero here (or later)
                # is a compile the warmup failed to cover
                "compile_cache_after_warmup": engine.request_path_compiles(),
                # per-bucket XLA cost accounting from the AOT warmup
                "cost": engine.bucket_cost,
            }
        ),
        flush=True,
    )
    swap_fn = (
        None
        if workdir is None
        else (lambda tags=None: engine.swap_from_workdir(workdir, tags=tags))
    )
    try:
        asyncio.run(serve_async(pool, cfg.serve.host, cfg.serve.port, swap_fn=swap_fn))
    except KeyboardInterrupt:
        pass
    finally:
        pool.stop(drain=False)
        # merged across every replica's workers: the same aggregate the
        # metrics verb serves
        pool.merged_metrics().flush(
            compile_cache=engine.request_path_compiles(),
            workers=pool.workers,
            replicas=pool.n_replicas,
        )
