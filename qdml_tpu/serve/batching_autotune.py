"""Autotuned bucket-vs-ragged batching dispatch for the serve request path.

Third instance of the repo's measured-dispatch pattern (quantum circuit impls
-> ``quantum/autotune.py``; dense-vs-sparse routing ->
``ops/dispatch_autotune.py``): the serving engine can compile each capacity
tier either as the classic **bucket** program (pad to the static shape, slice
back — pad rows are inert by row-independence) or as the **ragged** program
(same static shape plus a TRACED valid-row count that masks pad rows inert by
construction, one executable serving every fill level of the tier — PR 9's
``n_valid`` pattern generalized from sparse dispatch to the whole forward).

Per dispatch the two programs do identical FLOPs at identical shapes; the
only cost ragged can ADD is the input mask, and the only way to know whether
that mask is free on a given platform/shape is to time it — so the choice is
raced at warmup per ``(platform, capacity, route, dtype)`` and cached in a
table, never assumed. Where the mask measures free (every shape measured so
far), ragged wins the race and brings continuous admission with it — the
end-to-end p99/goodput win the committed ``results/serve_ragged/`` dryrun
measures under MMPP/diurnal load. Where masking is NOT free, bucket wins and
the engine keeps the coalescing batcher: the race is the guard that the
ragged mode can only ever be adopted where it measures at least as fast.

Contracts (identical to the routing dispatcher):

- ``ensure_batching()`` is HOST-side and eager: serve warmup calls it per
  capacity tier when ``serve.batching="auto"`` — never a traced function,
  never the serve request path; its candidate jits land inside the warmup
  compile window, so the zero-request-path-compile pin is intact in both
  modes.
- ``lookup()`` is read-only and cheap; any table pathology degrades to the
  ``bucket`` incumbent, never raises.
- Forced modes (``serve.batching="bucket"|"ragged"``) never race — the
  committed dryrun drives both modes explicitly through exactly that path.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable

from qdml_tpu.utils.tune_table import TableStore

SCHEMA = 1
DEFAULT_TABLE = os.path.join("results", "autotune", "serve_batching.json")
ENV_TABLE = "QDML_SERVE_BATCHING_TABLE"

_MODES = ("bucket", "ragged")

# Table persistence/caching lives in the shared store (utils/tune_table.py,
# the same machinery the routing dispatcher delegates to); the module-level
# functions stay as this dispatcher's public API.
_STORE = TableStore(DEFAULT_TABLE, ENV_TABLE, "serve_batching_table",
                    "serve.batching_autotune")


def set_table_path(path: str | None) -> None:
    """Install (or clear) the process-wide batching-table location."""
    _STORE.set_path(path)


def table_path(path: str | None = None) -> str:
    return _STORE.path(path)


def table_key(
    platform: str,
    capacity: int,
    route: str = "dense",
    dtype: str = "float32",
    checkify: bool = False,
) -> str:
    """Entry key. ``route`` (the tier's dense/sparse routing dispatch),
    ``dtype`` (the model's activation dtype) and ``checkify`` are part of the
    raced SHAPE, not metadata: the ragged mask rides a different program
    under sparse dispatch (the valid-count already feeds capacity accounting
    there), a bf16 forward is not the f32 one, and the checkified program
    carries functionalized error plumbing the unchecked twin does not — a
    winner raced on any one variant says nothing about the others, so each
    gets its own entry (the engine races the checkified pair when
    ``serve.checkify`` is on)."""
    return f"{platform}/cap{capacity}/{route}/{dtype}" + ("/ck" if checkify else "")


def load_table(path: str | None = None) -> dict:
    """entries dict; {} on missing/corrupt/alien — a broken table degrades to
    the bucket incumbent, never raises (same contract as the routing
    dispatcher)."""
    return _STORE.load(path)


def table_status(path: str | None = None) -> str:
    return _STORE.status(path)


def save_table(entries: dict, path: str | None = None) -> str:
    """Atomically persist the manifest-headed table; best-effort (serving
    must survive a read-only results dir)."""
    return _STORE.save(entries, path, schema=SCHEMA)


def invalidate_cache() -> None:
    _STORE.invalidate()


def lookup(
    capacity: int,
    route: str = "dense",
    dtype: str = "float32",
    path: str | None = None,
    checkify: bool = False,
) -> str | None:
    """The tuned batching mode for this shape, or ``None`` (caller falls back
    to the bucket incumbent). Never raises, never benchmarks — safe
    anywhere."""
    try:
        import jax

        entries = load_table(path)
        entry = entries.get(
            table_key(jax.default_backend(), int(capacity), route, dtype, checkify)
        )
        if not isinstance(entry, dict):
            return None
        sel = entry.get("best_infer")
        return sel if sel in _MODES else None
    except Exception:  # lint: disable=broad-except(batching lookup must degrade to the bucket incumbent on ANY table pathology — tuning can speed serving up, never crash it)
        return None


def ensure_batching(
    candidates: dict[str, tuple[Callable, tuple]],
    capacity: int,
    route: str = "dense",
    dtype: str = "float32",
    path: str | None = None,
    force: bool = False,
    budget_s: float = 0.2,
    checkify: bool = False,
) -> dict:
    """Return this capacity tier's table entry, racing and persisting it
    first if absent (or ``force``).

    ``candidates`` maps ``"bucket"``/``"ragged"`` to ``(callable, args)`` at
    the full-fill tier shape (the engine passes its two candidate forwards
    with jit applied but untraced — a table hit compiles NOTHING). Timing is
    :func:`qdml_tpu.ops.dispatch_autotune.measure` — median-of-reps wall ms,
    so the three dispatcher races in this repo are comparable measurements.
    """
    import jax

    platform = jax.default_backend()
    key = table_key(platform, int(capacity), route, dtype, checkify)
    entries = dict(load_table(path))
    entry = entries.get(key)
    if not force and isinstance(entry, dict) and entry.get("best_infer") in _MODES:
        return entry
    from qdml_tpu.ops.dispatch_autotune import measure

    cands = measure(candidates, budget_s=budget_s)
    timed = {
        m: v["infer_ms"]
        for m, v in cands.items()
        if isinstance(v.get("infer_ms"), (int, float))
    }
    best = min(timed, key=timed.get) if timed else "bucket"
    entry = {
        "key": key,
        "platform": platform,
        "capacity": int(capacity),
        "route": route,
        "dtype": dtype,
        "checkify": bool(checkify),
        "candidates": cands,
        "best_infer": best,
        "ts": round(time.time(), 3),
    }
    entries[key] = entry
    save_table(entries, path)
    return entry
