"""Seeded, deterministic fault injection for the serving stack.

The chaos half of docs/RESILIENCE.md: a :class:`FaultPlan` is an explicit
schedule of faults — WHICH failure class fires, at WHICH occurrence of its
hook site — consumed by the serving components that opted into a hook
(``ServeLoop``/``ReplicaPool`` take ``faults=``; the socket/file fault
classes are driven from the chaos client side, see
``scripts/chaos_dryrun.py``). Everything is deterministic: the plan is built
from explicit :class:`FaultSpec` entries plus a seed that only shapes the
supervision backoff jitter, never WHETHER a fault fires, so a chaos run
replays bit-identically.

Inert by default, and provably free: no plan (``faults=None``, the default
everywhere) means the hook sites reduce to one attribute check on the host
path — nothing touches a traced function, so the no-fault serve program is
byte-identical to the pre-chaos build (pinned via lowered-HLO equality and
the compile-cache counters in ``tests/test_faults.py``).

Fault classes (:data:`FAULT_CLASSES`; every class the chaos dryrun must
prove survivable):

- ``replica_crash`` — a worker thread dies BEFORE dequeuing (simulated
  process death: the queue is untouched; supervision must restart or peers
  must drain, nothing strands);
- ``worker_exception`` — the engine call for one batch raises (typed
  ``FaultInjected``): the batch's futures must resolve with the exception
  and the replica must come back;
- ``socket_drop`` / ``socket_garbage`` / ``partial_line`` /
  ``stalled_client`` — client-side protocol faults (disconnect mid-request,
  non-JSON line, a line fragment then disconnect, a connection that sends
  nothing): driven by the chaos client against the hardened server
  (``serve.conn_timeout_s`` / ``serve.max_line_bytes``);
- ``corrupt_swap`` — a ``{"op": "swap"}`` to a corrupted checkpoint tag:
  typed ``swap_failed`` reply, the old params keep serving;
- ``autotune_corrupt`` — an autotune table corrupted mid-run: the warmed
  engine never re-reads it (no effect on live serving), and the next warmup
  degrades to the documented fallback instead of crashing.
"""

from __future__ import annotations

import random
import threading

from qdml_tpu.utils import lockdep
from dataclasses import dataclass, field

FAULT_CLASSES = (
    "replica_crash",
    "worker_exception",
    "socket_drop",
    "socket_garbage",
    "partial_line",
    "stalled_client",
    "corrupt_swap",
    "autotune_corrupt",
)

# Hook sites the serving components expose. Worker-side sites fire inside
# ServeLoop (the spec's kind picks what happens); client/file sites are
# consumed by the chaos driver, which asks the plan "should this fault fire
# now?" the same way the workers do.
WORKER_SITES = ("worker_loop", "worker_batch")


class FaultInjected(RuntimeError):
    """A deliberately injected fault (chaos harness). Typed so tests and the
    serve loop's failure paths can tell an injected crash from a real one —
    and so nothing anywhere catches it by name to 'fix' the chaos."""

    def __init__(self, kind: str, site: str, seq: int):
        super().__init__(f"injected {kind} at {site}#{seq}")
        self.kind = kind
        self.site = site
        self.seq = seq


@dataclass
class FaultSpec:
    """One scheduled fault: ``kind`` (a :data:`FAULT_CLASSES` member) firing
    at the ``at``-th occurrence of its hook site (0-based), ``times``
    consecutive occurrences (a crash-looping replica is ``times`` large
    enough to exhaust the restart budget). ``replica`` targets one replica
    by name (``serve-replica-1``); ``None`` matches whichever worker reaches
    the site (the per-replica occurrence counter still makes it
    deterministic under a single-replica pool)."""

    kind: str
    at: int = 0
    times: int = 1
    replica: str | None = None

    def __post_init__(self):
        if self.kind not in FAULT_CLASSES:
            raise ValueError(
                f"unknown fault kind {self.kind!r} (have {FAULT_CLASSES})"
            )
        if self.at < 0 or self.times < 1:
            raise ValueError(f"need at >= 0 and times >= 1, got {self}")


# which hook site each worker-side fault class fires at: replica_crash fires
# at the TOP of the worker loop (before any dequeue — the queue is untouched,
# like a killed process); worker_exception fires around the engine call for
# one batch (its futures get the exception).
_SITE_OF = {"replica_crash": "worker_loop", "worker_exception": "worker_batch"}


class FaultPlan:
    """Deterministic fault schedule + per-site occurrence counters.

    Thread-safe: worker threads and the chaos driver share one plan. The
    ``seed`` feeds :attr:`rng` (used by the pool's backoff jitter so chaos
    runs replay exactly); it never decides WHETHER a fault fires — that is
    the explicit ``FaultSpec`` schedule's job.
    """

    def __init__(self, specs: list[FaultSpec] | None = None, seed: int = 0):
        self.specs = list(specs or [])
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self._lock = lockdep.Lock("FaultPlan._lock")
        self._counts: dict[str, int] = {}
        self.fired: list[dict] = []  # audit trail: every fault that fired

    def describe(self) -> dict:
        """The plan as a JSON-able record (the chaos dryrun's manifest)."""
        return {
            "seed": self.seed,
            "faults": [
                {"kind": s.kind, "at": s.at, "times": s.times} for s in self.specs
            ],
        }

    def _fire(self, site: str, replica: str | None) -> tuple[FaultSpec, int] | None:
        """Occurrence counters are PER (site, replica): a fault targeted at
        one replica counts that replica's occasions only, and an untargeted
        spec consumes whichever replica reaches its scheduled occasion —
        both deterministic under the pool topologies chaos runs use."""
        key = (site, replica)
        with self._lock:
            seq = self._counts.get(key, -1) + 1  # this call's occasion
            self._counts[key] = seq
            for s in self.specs:
                if _SITE_OF.get(s.kind) != site:
                    continue
                if s.replica is not None and s.replica != replica:
                    continue
                if s.at <= seq < s.at + s.times:
                    self.fired.append(
                        {"kind": s.kind, "site": site, "seq": seq,
                         "replica": replica}
                    )
                    return s, seq
        return None

    # -- worker-side hooks (ServeLoop) --------------------------------------

    def check_worker_loop(self, replica: str | None = None) -> None:
        """Top of a worker's pump iteration with work pending (BEFORE any
        dequeue): a scheduled ``replica_crash`` raises here, so the queue is
        untouched — the crashed-process shape."""
        hit = self._fire("worker_loop", replica)
        if hit is not None:
            raise FaultInjected(hit[0].kind, "worker_loop", hit[1])

    def check_worker_batch(self, replica: str | None = None) -> None:
        """Around one batch's engine call: a scheduled ``worker_exception``
        raises here — the batch's futures must resolve with the exception."""
        hit = self._fire("worker_batch", replica)
        if hit is not None:
            raise FaultInjected(hit[0].kind, "worker_batch", hit[1])

    # -- client/file-side schedule (chaos driver) ---------------------------

    def client_fault_at(self, kind: str, request_index: int) -> bool:
        """Does the plan schedule client/file fault ``kind`` at this request
        index? (The chaos driver injects socket/file faults itself; the plan
        is the single deterministic schedule both sides read.)"""
        return any(
            s.kind == kind and s.at <= request_index < s.at + s.times
            for s in self.specs
        )


@dataclass
class RestartPolicy:
    """Supervision budget + jittered exponential backoff (ReplicaPool).

    ``delay(k, rng)`` is the sleep before restart ``k`` (0-based):
    ``base * 2^k`` scaled by a uniform jitter in ``[1, 1+jitter]`` — the
    jitter decorrelates a fleet of supervisors restarting at once, and the
    rng is injected (the FaultPlan's seeded one under chaos) so runs replay.
    A slot that has used ``budget`` restarts is quarantined instead — but
    the budget measures crash LOOPS, not lifetime totals: a slot that then
    served healthily for ``reset_after_s`` gets its count reset, so three
    unrelated transient faults spread over days can never quarantine a
    replica the way three crashes in a row do."""

    base_s: float = 0.05
    budget: int = 3
    jitter: float = 0.5
    max_s: float = 2.0
    reset_after_s: float = 30.0

    def delay(self, k: int, rng: random.Random) -> float:
        raw = self.base_s * (2.0 ** k)
        return min(self.max_s, raw) * (1.0 + self.jitter * rng.random())

    def exhausted(self, restarts: int) -> bool:
        return restarts >= self.budget

    def stale(self, since_last_restart_s: float) -> bool:
        """Has the slot been healthy long enough to forget its history?"""
        return since_last_restart_s > self.reset_after_s
