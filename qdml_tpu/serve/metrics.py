"""Serving telemetry: per-request/per-batch records, tail-latency counters.

Everything flushes through the PR-1 telemetry layer so a serving run produces
the same manifest-headed JSONL every other entry point does, and
``qdml-tpu report`` can diff it against a committed baseline:

- per-batch: a ``span`` record (``name="serve_batch"``) around each engine
  dispatch, tagged with real count, bucket, and queue depth at dequeue;
- per-request: a ``span`` record (``name="serve_request"``) whose ``dur_s``
  is the enqueue->result latency (at load-test scale every request is cheap
  to record; a production deployment would sample — docs/SERVING.md);
- rolled up: ``counters`` records (``name="serve"``) with p50/p95/p99 request
  latency, batch-fill and queue-depth distributions, shed counts, and the
  request-path compile-cache counters, flushed on demand
  (:meth:`ServeMetrics.flush`) and folded into the final ``serve_summary``
  record the report gate consumes.
"""

from __future__ import annotations

import time

from qdml_tpu.serve.types import DispatchInfo, Overloaded, Prediction
from qdml_tpu.telemetry import Histogram
from qdml_tpu.telemetry.spans import get_sink
from qdml_tpu.telemetry.tracing import PHASES


class ServeMetrics:
    """Latency/fill/depth/goodput collector for one serving window."""

    def __init__(self, sink=None, log_requests: bool = True):
        self._sink = sink
        self.log_requests = log_requests
        self.latency = Histogram()       # per-request enqueue -> result
        self.batch_fill = Histogram()    # valid/static rows per dispatch (0..1)
        self.queue_depth = Histogram()   # depth at dequeue (unitless count)
        # Per-phase latency decomposition from SAMPLED request traces
        # (telemetry/tracing.py, docs/TELEMETRY.md): one histogram per phase
        # name, raw seconds, so Histogram.merge aggregates replicas/workers
        # exactly like the end-to-end latency. The five gated phases are
        # pre-seeded; router-side auxiliary spans (pick, dedup_wait) land in
        # histograms created on first sight. ``traced`` counts predictions
        # that CARRIED a trace — the coverage fact the report states next to
        # any phase claim (a p99 over 1% of requests is not the fleet's p99).
        self.phase: dict[str, Histogram] = {p: Histogram() for p in PHASES}
        self.traced = 0
        # Goodput-first row accounting. Three row ledgers, three meanings:
        # - rows_useful: rows the client could USE — completed within their
        #   deadline, or completed with no deadline offered (the serving
        #   literature's goodput numerator: a row delivered after its SLO is
        #   throughput, not goodput); fed per prediction.
        # - rows_valid: real (non-padding) rows dispatched (DispatchInfo.n).
        # - rows_dispatched: what XLA actually computed (static bucket/tier
        #   shapes, every chunk counted) — the gap to rows_valid is padding
        #   waste, the number the ragged batching mode exists to account for
        #   and the report gate watches.
        # Kept as raw sums so windowed pollers can difference snapshots
        # exactly, like the confidence sums.
        self.rows_useful = 0
        self.rows_valid = 0
        self.rows_dispatched = 0
        self.dispatches = 0              # executable launches (chunks included)
        # classifier-confidence histogram (routed-class probability per
        # prediction; raw samples, so Histogram.merge aggregates exactly) +
        # per-scenario prediction counts and confidence SUMS. The sums exist
        # so a poller can window the stream by differencing two snapshots
        # (mean-of-window = d(sum)/d(n)) — a cumulative histogram cannot be
        # differenced, and the drift detectors (docs/CONTROL.md) live on
        # windowed per-scenario means.
        self.confidence = Histogram()
        self.scenario_counts: dict[str, int] = {}
        self.scenario_conf_sum: dict[str, float] = {}
        self.batches = 0
        self.completed = 0
        self.shed: dict[str, int] = {}
        # fault/recovery accounting (docs/RESILIENCE.md): worker crashes and
        # batch-level engine failures observed by the serve loop (injected
        # chaos faults included — FaultInjected counts under its kind), and
        # supervised replica restarts. Raw sums, snapshot-differencable.
        self.faults: dict[str, int] = {}
        self.restarts = 0
        # SLO attainment: of the requests that CARRIED a deadline, how many
        # resolved within it. Completions feed via Prediction.deadline_met;
        # a shed request that had a deadline is a miss by definition (the
        # client never got an answer in time).
        self.slo_total = 0
        self.slo_met = 0
        self._t0 = time.perf_counter()

    def _target(self):
        return self._sink if self._sink is not None else get_sink()

    def observe_batch(
        self, preds: list[Prediction], info: DispatchInfo, depth: int, dur_s: float
    ) -> None:
        """One engine dispatch's worth of results. ``info`` is the engine's
        :class:`DispatchInfo`: its static-row total keeps fill/pad accounting
        honest even for oversize batches served in chunks (``n / rows`` is
        never > 1 — the pre-ragged accounting divided by the last chunk's
        bucket alone and inflated chunked fills past 1.0)."""
        self.batches += 1
        self.completed += len(preds)
        self.rows_valid += info.n
        self.rows_dispatched += info.rows
        self.dispatches += info.chunks
        self.batch_fill.add(info.fill)
        self.queue_depth.add(float(depth))
        target = self._target()
        active = target is not None and getattr(target, "active", False)
        if active:
            target.emit(
                "span",
                name="serve_batch",
                path="serve/serve_batch",
                depth=1,
                dur_s=round(dur_s, 6),
                n=len(preds),
                bucket=info.bucket,
                rows=info.rows,
                batching=info.mode,
                queue_depth=depth,
            )
        for p in preds:
            self.observe_prediction(p)
            if active and self.log_requests:
                target.emit(
                    "span",
                    name="serve_request",
                    path="serve/serve_request",
                    depth=2,
                    dur_s=round(p.latency_s, 6),
                    rid=p.rid,
                    bucket=info.bucket,
                )

    def observe_prediction(self, p: Prediction) -> None:
        """Per-request accounting shared by :meth:`observe_batch` and the
        windowed loadgen summaries (which replay results into a fresh
        collector): latency, SLO, per-scenario counts, confidence, and — for
        the sampled traced fraction — the per-phase latency decomposition."""
        self.latency.add(p.latency_s)
        if p.trace is not None:
            self.traced += 1
            for name, dur_s in p.trace.phases:
                hist = self.phase.get(name)
                if hist is None:
                    hist = self.phase[name] = Histogram()
                hist.add(dur_s)
        # goodput numerator: a late completion is throughput, not goodput
        if p.deadline_met is not False:
            self.rows_useful += 1
        if p.deadline_met is not None:
            self.slo_total += 1
            self.slo_met += int(p.deadline_met)
        key = str(p.scenario)
        self.scenario_counts[key] = self.scenario_counts.get(key, 0) + 1
        if p.confidence is not None:
            self.confidence.add(float(p.confidence))
            self.scenario_conf_sum[key] = self.scenario_conf_sum.get(key, 0.0) + float(
                p.confidence
            )

    def observe_shed(self, o: Overloaded, had_deadline: bool = False) -> None:
        self.shed[o.reason] = self.shed.get(o.reason, 0) + 1
        if had_deadline:
            self.slo_total += 1  # shed with a deadline = an SLO miss

    def observe_fault(self, kind: str) -> None:
        """One worker-path failure (crash, batch exception, injected chaos
        fault) — the serve loop records the KIND so a chaos run's summary
        attributes every fault class it survived."""
        self.faults[kind] = self.faults.get(kind, 0) + 1

    def merge(self, other: "ServeMetrics") -> "ServeMetrics":
        """Fold another collector into this one (``Histogram.merge`` keeps
        raw samples, so the merged quantiles are exact, not approximate).
        Per-WORKER collectors aggregate this way: each serve-loop worker
        thread records into its own ServeMetrics — no cross-thread lock on
        the hot path — and snapshots merge on demand. The window start is
        the earliest of the two, so a merged ``rps`` spans the union."""
        self.latency.merge(other.latency)
        self.batch_fill.merge(other.batch_fill)
        self.queue_depth.merge(other.queue_depth)
        self.confidence.merge(other.confidence)
        for name, hist in other.phase.items():
            mine = self.phase.get(name)
            if mine is None:
                mine = self.phase[name] = Histogram()
            mine.merge(hist)
        self.traced += other.traced
        self.batches += other.batches
        self.completed += other.completed
        self.rows_useful += other.rows_useful
        self.rows_valid += other.rows_valid
        self.rows_dispatched += other.rows_dispatched
        self.dispatches += other.dispatches
        for k, v in other.shed.items():
            self.shed[k] = self.shed.get(k, 0) + v
        for k, v in other.faults.items():
            self.faults[k] = self.faults.get(k, 0) + v
        self.restarts += other.restarts
        for k, v in other.scenario_counts.items():
            self.scenario_counts[k] = self.scenario_counts.get(k, 0) + v
        for k, v in other.scenario_conf_sum.items():
            self.scenario_conf_sum[k] = self.scenario_conf_sum.get(k, 0.0) + v
        self.slo_total += other.slo_total
        self.slo_met += other.slo_met
        self._t0 = min(self._t0, other._t0)
        return self

    def slo(self) -> dict | None:
        """``{"n", "met", "attainment"}`` over deadline-carrying requests, or
        ``None`` when no request in the window had a deadline (an attainment
        over zero requests would read as a perfect-or-failed SLO that was
        never actually offered)."""
        if self.slo_total == 0:
            return None
        return {
            "n": self.slo_total,
            "met": self.slo_met,
            "attainment": round(self.slo_met / self.slo_total, 4),
        }

    def padding_waste(self) -> float | None:
        """Fraction of dispatched rows that were padding (``1 -
        valid/dispatched``), or ``None`` before any dispatch was OBSERVED
        (a window rebuilt from results alone — the loadgen external-pool
        replay — has no executable-side row counts, and a fabricated 0.0
        would read as perfect fill that was never measured)."""
        if self.rows_dispatched == 0:
            return None
        return round(1.0 - self.rows_valid / self.rows_dispatched, 4)

    def rows(self) -> dict | None:
        """The raw row ledger behind goodput/padding-waste (``None`` before
        any observed dispatch): useful vs valid vs dispatched rows and
        executable launches — snapshot-differencable, like the confidence
        sums."""
        if self.rows_dispatched == 0:
            return None
        return {
            "useful": self.rows_useful,
            "valid": self.rows_valid,
            "dispatched": self.rows_dispatched,
            "padded": self.rows_dispatched - self.rows_valid,
            "dispatches": self.dispatches,
        }

    def per_scenario(self) -> dict | None:
        """Per predicted-scenario counts + confidence stats, or ``None``
        before any prediction. ``conf_sum`` is deliberately raw (not just the
        mean): two snapshots of a live server difference to an exact window
        mean, which is what the drift detectors consume."""
        if not self.scenario_counts:
            return None
        out: dict = {}
        for k in sorted(self.scenario_counts, key=int):
            n = self.scenario_counts[k]
            rec: dict = {"n": n}
            if k in self.scenario_conf_sum and n:
                cs = self.scenario_conf_sum[k]
                rec["conf_sum"] = round(cs, 4)
                rec["conf_mean"] = round(cs / n, 4)
            out[k] = rec
        return out

    def phases(self) -> dict | None:
        """Per-phase latency summaries from the traced sample (``None``
        before any traced request): per phase, the exact quantile summary
        PLUS ``(n, sum_ms)`` — the pair the fleet router sums EXACTLY across
        backends (quantiles cannot cross a process boundary exactly; the raw
        samples live here)."""
        out: dict = {}
        for name, hist in self.phase.items():
            s = hist.summary()
            if s is None:
                continue
            s["sum_ms"] = round(hist.sum() * 1e3, 3)
            out[name] = s
        return out or None

    def trace_coverage(self) -> dict | None:
        """The sampling fact that must sit next to any phase claim: how many
        of the window's completed requests actually carried a trace. ``None``
        when nothing was traced (a phase table with no stated coverage reads
        as the whole fleet's decomposition when it may be 1% of it)."""
        if not self.traced:
            return None
        return {
            "sampled": self.traced,
            "completed": self.completed,
            "fraction": (
                round(self.traced / self.completed, 4) if self.completed else None
            ),
        }

    def flush(self, compile_cache: dict | None = None, **tags) -> None:
        """One ``counters`` record for the window; histograms keep
        accumulating (the final summary sees the whole run)."""
        target = self._target()
        if target is not None and getattr(target, "active", False):
            elapsed = time.perf_counter() - self._t0
            target.emit(
                "counters",
                name="serve",
                latency=self.latency.summary(),
                phases=self.phases(),
                trace=self.trace_coverage(),
                batch_fill=self.batch_fill.summary(unit=None),
                queue_depth=self.queue_depth.summary(unit=None),
                batches=self.batches,
                completed=self.completed,
                goodput_rps=(
                    round(self.rows_useful / elapsed, 2) if elapsed > 0 else None  # lint: disable=unwindowed-cumulative-rate(run-level summary over the full flush span, not a live window — the monitor differences snapshots for windowed rates)
                ),
                padding_waste=self.padding_waste(),
                rows=self.rows(),
                shed=dict(self.shed),
                faults=dict(self.faults),
                restarts=self.restarts,
                slo=self.slo(),
                confidence=self.confidence.summary(unit=None),
                per_scenario=self.per_scenario(),
                compile_cache=compile_cache,
                **tags,
            )

    def snapshot(self, compile_cache: dict | None = None, **extra) -> dict:
        """The live-metrics view (``{"op": "metrics"}`` serve verb): the
        summary fields without the ``serve_summary`` record kind — a poll of
        a running server is a reading, not a run artifact."""
        s = self.summary(compile_cache=compile_cache, **extra)
        s.pop("kind", None)
        return s

    def summary(self, compile_cache: dict | None = None, **extra) -> dict:
        """The run-level ``serve_summary`` record (``qdml-tpu report``'s
        serving section reads exactly this shape)."""
        elapsed = time.perf_counter() - self._t0
        return {
            "kind": "serve_summary",
            "elapsed_s": round(elapsed, 3),
            "completed": self.completed,
            "batches": self.batches,
            "shed": dict(self.shed),
            # fault-tolerance accounting (docs/RESILIENCE.md): worker-path
            # failures by kind + supervised replica restarts in this window
            "faults": dict(self.faults),
            "restarts": self.restarts,
            "rps": round(self.completed / elapsed, 2) if elapsed > 0 else None,  # lint: disable=unwindowed-cumulative-rate(run-level summary rate over the run's own span — restart-safe windowed rates live in the monitor's snapshot differencing)
            # goodput = USEFUL rows/s: completed within deadline (or with no
            # deadline offered — a request is one row here), so sheds, LATE
            # completions and the window's drain all cost goodput while mere
            # rows/s hides them; padding waste is the dispatched-row fraction
            # XLA computed for nothing — the pair the report gates,
            # docs/SERVING.md "Ragged continuous batching"
            "goodput_rps": (
                round(self.rows_useful / elapsed, 2) if elapsed > 0 else None  # lint: disable=unwindowed-cumulative-rate(run-level summary over the run's own span, paired with the rps row above)
            ),
            "padding_waste": self.padding_waste(),
            "rows": self.rows(),
            "slo": self.slo(),
            "latency_ms": self.latency.summary(),
            # the phase decomposition of that latency (traced sample only)
            # plus its coverage fact — where the time went, and how much of
            # the window actually said so (docs/TELEMETRY.md)
            "phases": self.phases(),
            "trace": self.trace_coverage(),
            "batch_fill": self.batch_fill.summary(unit=None),
            "queue_depth": self.queue_depth.summary(unit=None),
            # classifier-confidence histogram + per-scenario counts/means:
            # the drift detectors' raw input, independently useful fleet
            # observability (docs/CONTROL.md)
            "confidence": self.confidence.summary(unit=None),
            "per_scenario": self.per_scenario(),
            "compile_cache_after_warmup": compile_cache,
            **extra,
        }
