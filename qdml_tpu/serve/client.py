"""Retry-disciplined client for the newline-JSON serving protocol.

The client half of the docs/RESILIENCE.md retry contract. One
:class:`ServeClient` owns one TCP connection and gives every call the three
disciplines a fault-tolerant caller needs:

- **deadline propagation** — the request's ``deadline_ms`` rides the wire
  (the server sheds it typed if it cannot be met) AND bounds the client-side
  socket wait, so a dead server cannot pin the caller past the deadline it
  already promised its own caller;
- **per-request timeouts** — every send/receive runs under a socket timeout
  (``timeout_s``, tightened to the remaining deadline when one is set);
- **jittered-backoff retries on idempotent ids** — a connection error or
  timeout reconnects with exponential backoff (``backoff_s * 2^k``, jittered
  to decorrelate a retrying fleet) and re-sends the SAME request id: the
  server's dedup window (``serve.dedup_ttl_s``) re-attaches the retry to the
  original dispatch, so a retried request never runs twice. Ids are
  generated unique per logical request (uuid-based) when the caller does not
  pass one — an id, not a sequence number, is the idempotency key.

Counters (``reconnects``, ``retries``, ``give_ups``) accumulate on the
client and fold into the loadgen socket harness's ``serve_summary`` — a
measurement run that survived transient resets REPORTS them instead of
aborting (the pre-resilience loadgen treated one ECONNRESET as fatal).
"""

from __future__ import annotations

import json
import random
import socket
import threading

from qdml_tpu.utils import lockdep
import time
import uuid


class ServeClientError(ConnectionError):
    """The client exhausted its retries (or the deadline) for one request.
    Typed so harness code can count a give-up without catching the world."""


class ServeClient:
    """One connection + the retry/backoff/deadline discipline around it.

    Thread-safe per request (``_lock`` serializes the request/reply exchange
    on the single connection); use one client per concurrent in-flight
    request — the loadgen socket harness keeps a small pool of them.
    """

    def __init__(
        self,
        host: str,
        port: int,
        timeout_s: float = 10.0,
        retries: int = 3,
        backoff_s: float = 0.05,
        backoff_max_s: float = 2.0,
        jitter: float = 0.5,
        seed: int | None = None,
    ):
        self.host = host
        self.port = int(port)
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self._lock = lockdep.Lock("ServeClient._lock")
        self._sock: socket.socket | None = None
        self._rfile = None
        self._was_connected = False
        self.reconnects = 0
        self.retries_used = 0
        self.give_ups = 0
        # give-ups split by cause: a DEADLINE give-up means the client
        # honored its budget (typed closure inside the SLO — an SLO miss,
        # not a resilience failure); a retries-exhausted give-up against a
        # supposedly-live server is the alarming kind
        self.deadline_give_ups = 0

    # -- connection management ---------------------------------------------

    def _backoff(self, attempt: int) -> float:
        raw = min(self.backoff_max_s, self.backoff_s * (2.0 ** attempt))
        return raw * (1.0 + self.jitter * self._rng.random())

    def _connect(self, timeout_s: float) -> None:
        self.close_connection()
        sock = socket.create_connection((self.host, self.port), timeout=timeout_s)
        sock.settimeout(timeout_s)
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def _ensure_connected(self, timeout_s: float) -> None:
        if self._sock is None:
            self._connect(timeout_s)
            if self._was_connected:
                self.reconnects += 1  # the FIRST connect is not a reconnect
            self._was_connected = True

    def close_connection(self) -> None:
        """Drop the socket (the next call reconnects). Safe to call always."""
        if self._rfile is not None:
            try:
                self._rfile.close()
            except OSError:
                pass
            self._rfile = None
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    close = close_connection

    # -- the retrying exchange ---------------------------------------------

    def call(
        self,
        msg: dict,
        timeout_s: float | None = None,
        deadline_ms: float | None = None,
        idempotent: bool = True,
    ) -> dict:
        """Send one JSON line, return the matching reply dict.

        ``deadline_ms`` (for inference requests) rides the wire and CAPS the
        total client-side budget: once it has passed, the client gives up
        typed instead of retrying a request whose answer is already useless.
        ``idempotent=False`` disables the re-send (the request still gets
        ONE attempt with timeouts; used for verbs with side effects the
        caller wants to observe failing)."""
        timeout_s = self.timeout_s if timeout_s is None else float(timeout_s)
        t0 = time.monotonic()
        budget = None if deadline_ms is None else deadline_ms / 1e3
        if deadline_ms is not None:
            msg = {**msg, "deadline_ms": deadline_ms}
        if "id" not in msg:
            # every exchange gets an id so replies CORRELATE: the server can
            # interleave unsolicited notices (idle_timeout before close) with
            # replies, and a reconnecting client must never take a stale
            # buffered notice as its answer
            msg = {**msg, "id": f"op-{uuid.uuid4().hex[:12]}"}
        payload = (json.dumps(msg) + "\n").encode()
        attempts = (self.retries + 1) if idempotent else 1
        last_err: Exception | None = None
        cause = "retries"
        for attempt in range(attempts):
            remaining = (
                None if budget is None else budget - (time.monotonic() - t0)
            )
            if remaining is not None and remaining <= 0:
                cause = "deadline"
                break  # the deadline is the outer bound on the whole exchange
            per_try = timeout_s if remaining is None else min(timeout_s, remaining)
            try:
                with self._lock:
                    self._ensure_connected(per_try)  # lint: disable=blocking-under-lock(the hold IS the wire protocol: one in-flight exchange per connection — _lock serializes this client's threads over one socket, reconnect included)
                    self._sock.settimeout(per_try)
                    self._sock.sendall(payload)  # lint: disable=blocking-under-lock(the hold IS the wire protocol: one request/reply exchange owns the socket; send stays under _lock so a peer thread cannot interleave bytes)
                    while True:
                        line = self._rfile.readline()  # lint: disable=blocking-under-lock(the hold IS the wire protocol: the reply read belongs to the same exchange as the send; socket timeout bounds the wait)
                        if not line:
                            raise ConnectionResetError(
                                "server closed the connection"
                            )
                        try:
                            rep = json.loads(line)
                        except json.JSONDecodeError as e:
                            raise ConnectionResetError(
                                f"unparseable reply framing: {e}"
                            ) from e
                        if isinstance(rep, dict) and rep.get("id") == msg["id"]:
                            break
                        # an unsolicited server notice (e.g. the typed
                        # idle_timeout written before a reap) or a stale
                        # line from before a reconnect: not our reply —
                        # keep reading until ours or EOF
                if (
                    idempotent
                    and rep.get("ok") is False
                    and str(rep.get("reason", "")).startswith("server_error")
                ):
                    # a dispatch that died server-side (worker crash, chaos
                    # fault): the server already forgot the id, so a retry
                    # re-dispatches against the recovered replica — treat it
                    # like a transport failure, backoff included
                    raise ConnectionResetError(rep["reason"])
                return rep
            except (ConnectionError, socket.timeout, TimeoutError, OSError) as e:
                last_err = e
                self.close_connection()
                if attempt + 1 >= attempts:
                    break
                self.retries_used += 1
                # jittered exponential backoff between attempts: the server
                # said nothing (or vanished) — hammering it back is how a
                # retrying fleet turns a blip into an outage
                time.sleep(self._backoff(attempt))
        self.give_ups += 1
        if cause == "deadline":
            self.deadline_give_ups += 1
        err = ServeClientError(
            f"request {msg.get('id')!r} gave up ({cause}) after "
            f"{attempts} attempt(s): "
            f"{type(last_err).__name__ if last_err else 'deadline exhausted'}: "
            f"{last_err}"
        )
        err.cause = cause
        raise err

    # -- protocol verbs -----------------------------------------------------

    def request(
        self,
        x,
        rid: int | str | None = None,
        deadline_ms: float | None = None,
        timeout_s: float | None = None,
        trace: bool = False,
    ) -> dict:
        """One inference request. ``rid`` defaults to a fresh uuid — the
        idempotency key the server dedups retries on; pass your own only if
        it is unique per LOGICAL request (reuse within ``serve.dedup_ttl_s``
        intentionally returns the original result).

        ``trace=True`` sets the optional ``trace`` wire field, forcing a
        phase trace for this request (docs/TELEMETRY.md): the reply then
        carries ``trace.phases`` — server-side batch_wait/queue_wait/
        compute/fetch spans, prepended with router pick/wire spans when the
        endpoint is a fleet router. The client-observed wall time is the
        caller's to measure ON ITS OWN CLOCK; it must never be differenced
        against server timestamps (clock skew), only against the reply's
        phase DURATIONS — the loadgen reconciliation does exactly that. A
        retried id keeps its trace: the send is byte-stable per attempt and
        the dedup tiers re-attach to the original traced dispatch."""
        if rid is None:
            rid = uuid.uuid4().hex
        msg = {"id": rid, "x": x if isinstance(x, list) else x.tolist()}
        if trace:
            msg["trace"] = True
        return self.call(msg, timeout_s=timeout_s, deadline_ms=deadline_ms)

    def health(self, timeout_s: float | None = None) -> dict:
        return self.call({"op": "health"}, timeout_s=timeout_s)

    def metrics(self, timeout_s: float | None = None) -> dict:
        return self.call({"op": "metrics"}, timeout_s=timeout_s)

    def events(
        self,
        cursor: dict | None = None,
        limit: int | None = None,
        timeout_s: float | None = None,
    ) -> dict:
        """Event-spine tail (docs/TELEMETRY.md "event spine"): everything
        the endpoint published since ``cursor`` (None = from the buffer
        head), plus the explicit loss ledger. Resume by passing the reply's
        cursor back — ``{"start_seq", "seq"}`` against a serve host, the
        per-source ``cursor`` block verbatim against a router. Idempotent
        (a pure read): retries are safe, the cursor only advances when the
        CALLER passes the new one back."""
        msg: dict = {"op": "events"}
        if cursor is not None:
            msg["cursor"] = cursor
        if limit is not None:
            msg["limit"] = int(limit)
        return self.call(msg, timeout_s=timeout_s)

    def swap(self, tags: dict | None = None, timeout_s: float | None = None) -> dict:
        # NOT idempotent in the retry sense: a swap that timed out may have
        # landed — the caller must re-inspect (health.swap_epoch) rather
        # than have the client blindly re-deploy
        msg: dict = {"op": "swap"}
        if tags is not None:
            msg["tags"] = tags
        return self.call(msg, timeout_s=timeout_s, idempotent=False)

    def scale(self, replicas: int, timeout_s: float | None = None) -> dict:
        """Replica axis: resize the pools INSIDE the existing host(s)
        (docs/FLEET.md "two scaling axes")."""
        return self.call(
            {"op": "scale", "replicas": int(replicas)},
            timeout_s=timeout_s,
            idempotent=False,
        )

    def fleet(
        self, backends: int | None = None, timeout_s: float | None = None
    ) -> dict:
        """Backend-count axis, router endpoints only: the argument-free form
        reads membership/lifecycle status (always answers, ``fleet.elastic``
        says whether scaling is armed); ``backends=N`` asks the router's
        lifecycle manager to converge the serving member count (typed
        ``fleet_scale_unavailable`` when no manager is attached,
        ``fleet_scale_failed`` on non-convergence — see ``fleet.actions``).
        The scaling form is NOT retried: a spawn that timed out may still
        be warming — re-inspect with the status form instead."""
        if backends is None:
            return self.call({"op": "fleet"}, timeout_s=timeout_s)
        return self.call(
            {"op": "fleet", "backends": int(backends)},
            timeout_s=timeout_s,
            idempotent=False,
        )

    def counters(self) -> dict:
        """The client-side resilience ledger (folded into socket-loadgen
        summaries): reconnects, retries spent, give-ups."""
        return {
            "reconnects": self.reconnects,
            "retries": self.retries_used,
            "give_ups": self.give_ups,
            "deadline_give_ups": self.deadline_give_ups,
        }

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close_connection()
