"""Online inference serving: engine, micro-batcher, socket server, loadgen.

The first subsystem on the inference half of the stack (ROADMAP north star:
serve heavy traffic). A checkpoint goes online in three layers:

- :class:`~qdml_tpu.serve.engine.ServeEngine` — restores HDCE + classifier,
  fuses classify->route->estimate into one jitted function, AOT-compiles it
  per batch bucket at warmup, and proves the request path never compiles
  (compile-cache counters);
- :class:`~qdml_tpu.serve.batcher.MicroBatcher` — bounded queue, dynamic
  max-batch/max-wait coalescing into power-of-two buckets OR continuous
  admission (the ragged batching mode: dispatch whenever the engine is
  free), deadline-aware admission that sheds typed ``Overloaded`` results;
  which mode serves is the third measured-dispatch race
  (:mod:`qdml_tpu.serve.batching_autotune`, ``serve.batching=auto``) —
  bucket pad-and-slice vs traced valid-count ragged executables, raced per
  capacity tier at warmup with goodput/padding-waste accounting as
  first-class :class:`~qdml_tpu.serve.metrics.ServeMetrics`;
- :class:`~qdml_tpu.serve.server.ServeLoop` /
  :class:`~qdml_tpu.serve.server.ReplicaPool` / ``qdml-tpu serve`` — the
  worker pump, the N-replica pool sharing one warmup + one batcher feed,
  and a newline-JSON local socket front-end (live ``metrics`` and
  zero-downtime checkpoint ``swap`` verbs); ``qdml-tpu loadgen``
  (:mod:`qdml_tpu.serve.loadgen`) drives it with open-loop Poisson /
  bursty-MMPP / diurnal traffic and reports tail latency, SLO attainment
  and offline-forward parity.

With a multi-device mesh (``parallel.mesh.serve_mesh``) every bucket
executable is pjit-sharded: batch data-parallel over ``data``, params
replicated (or trunks expert-sharded over ``fed``), and checkpoint
hot-swap (``ServeEngine.swap_params``) re-places new params with the live
shardings — zero recompiles, proven by the compile-cache counters.

Architecture, bucket/warmup policy, overload semantics and telemetry record
shapes: ``docs/SERVING.md``.
"""

from qdml_tpu.serve.batcher import (  # noqa: F401
    MicroBatcher,
    pick_bucket,
    power_of_two_buckets,
)
from qdml_tpu.serve.breaker import CircuitBreaker  # noqa: F401
from qdml_tpu.serve.client import ServeClient, ServeClientError  # noqa: F401
from qdml_tpu.serve.engine import ServeEngine  # noqa: F401
from qdml_tpu.serve.faults import (  # noqa: F401
    FAULT_CLASSES,
    FaultInjected,
    FaultPlan,
    FaultSpec,
)
from qdml_tpu.serve.loadgen import (  # noqa: F401
    arrival_times,
    make_request_samples,
    run_loadgen,
    run_loadgen_socket,
)
from qdml_tpu.serve.metrics import ServeMetrics  # noqa: F401
from qdml_tpu.serve.server import (  # noqa: F401
    ExitCoordinator,
    ReplicaPool,
    ServeLoop,
    run_server,
    serve_async,
)
from qdml_tpu.serve.types import (  # noqa: F401
    Overloaded,
    Prediction,
    Request,
)
