"""Configuration system (dataclasses + CLI) for qdml_tpu.

The reference has no config/flag system at all -- every hyperparameter is a
hardcoded class attribute (``Runner_P128_QuantumNAT_onchipQNN.py:20-38``,
``Test.py:13-21``) or constructor kwarg (``Estimators_QuantumNAT_onchipQNN.py:108``).
This module centralises all of them as frozen dataclasses, provides the
BASELINE.json benchmark presets (plus the beyond-reference ``robust_qsc``),
and a small CLI override layer (``--train.lr=3e-4`` style dotted flags).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Sequence


# ---------------------------------------------------------------------------
# Data layer
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DataConfig:
    """Synthetic RIS/DeepMIMO-style dataset configuration.

    Mirrors the reference's hardcoded data constants: Pilot_num=128,
    data_len=20000, SNRdb=10, train/test split 0.9
    (``Runner_P128_QuantumNAT_onchipQNN.py:21-35``); channel dimension 1024 is
    encoded in the reference's ``.npy`` filenames (``Runner...py:49-55``).
    """

    n_ant: int = 64          # BS ULA antennas; H is (n_ant, n_sub) complex
    n_sub: int = 16          # OFDM subcarriers
    n_beam: int = 8          # sounded DFT beams -> pilot_num = n_beam * n_sub
    # Propagation scenario families (reference: 3). S > 3 appends derived
    # UMa/UMi/InH-style families from data/channels.family_table — generated
    # on device, no DeepMIMO files; rows 0..2 stay the frozen reference
    # presets (bit-identical streams).
    n_scenarios: int = 3
    # Channel-family drift trajectory (data/channels.family_table): step 0
    # (default) is the frozen table bit-identically; > 0 perturbs
    # delay-spread / K-factor / angular-spread / mobility of drift_scenario
    # (-1 = all families) as a deterministic function of the step — the
    # injected-drift axis the fleet control plane detects and adapts to
    # (docs/CONTROL.md).
    drift_step: int = 0
    drift_scenario: int = -1
    n_users: int = 3         # users per scenario (reference: 3)
    data_len: int = 20000    # training samples per (scenario, user) cell
    snr_db: float = 10.0     # training SNR (reference SNRdb=10)
    train_split: float = 0.9  # reference train_test_ratio=0.9 (Runner...py:35)
    seed: int = 2026         # base PRNG seed for the deterministic generator
    # Per-entry variance of the full-pilot LS label (Hlabel/HLS) is
    # label_noise_factor * 10**(-SNR/10); 1.9 calibrates the LS baseline to
    # the reference's published curve (~= -SNR + 2.8 dB, BASELINE.md).
    label_noise_factor: float = 1.9
    # Optional per-batch training-SNR jitter (lo, hi) dB. None = the
    # reference's fixed-SNR protocol; (5, 15) trains one estimator robust
    # across the eval grid (the generalization the published curves show).
    snr_jitter: tuple[float, float] | None = None
    # PRNG implementation for the on-device sample generator. "threefry"
    # (default) is bit-reproducible across platforms and jax versions;
    # "rbg" routes bit generation through the TPU's hardware generator
    # (XLA RngBitGenerator) — substantially cheaper when synthesis runs
    # inside the training dispatch (train.scan_steps) at the cost of
    # cross-platform bit stability (the DISTRIBUTION is identical; the
    # stream is not). Key derivation (fold_in/split) stays threefry-based
    # either way, so per-sample determinism-within-a-platform holds.
    rng_impl: str = "threefry"
    # Steering/delay phase-ramp evaluation: "direct" (default, bit-compatible
    # with all committed streams) or "split" (angle-addition factorization —
    # ~4x fewer sin/cos, the generator-tail hot spot on TPU; identical values
    # to f32 rounding, see complexops.cexp_i_ramp).
    trig_impl: str = "direct"

    @property
    def pilot_num(self) -> int:
        return self.n_beam * self.n_sub  # 128 for the default geometry

    @property
    def h_dim(self) -> int:
        return self.n_ant * self.n_sub  # 1024 for the default geometry


# ---------------------------------------------------------------------------
# Models
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class ModelConfig:
    """CNN estimator family (reference ``Estimators_QuantumNAT_onchipQNN.py:40-279``)."""

    features: int = 32       # conv channels (reference self.features=32)
    kernel_size: int = 3
    n_conv_layers: int = 3   # Conv_P128/DCE_P128 trunk depth
    dtype: str = "float32"   # activation dtype ("bfloat16" for the MXU fast path)
    # Conv lowering: "auto" (lax conv on TPU; shifted matmuls elsewhere —
    # XLA:CPU's batched-conv gradients are ~23x slower than the identical
    # work unbatched, results/perf_r4/cpu_fallback_profile.json),
    # "conv", or "shift_matmul" (models.cnn.resolve_conv_impl).
    conv_impl: str = "auto"


@dataclass(frozen=True)
class QuantumConfig:
    """Quantum scenario-classifier circuit (reference ``Estimators...py:107-149``)."""

    n_qubits: int = 6        # reference default n_qubits=6; published 4/6/8
    n_layers: int = 3        # reference default n_layers=3
    n_classes: int = 3
    use_quantumnat: bool = False      # reference ships with both OFF (Runner...py:313-316)
    use_gradient_pruning: bool = False
    noise_level: float = 0.01         # QuantumNAT sigma (Estimators...py:118)
    gradient_threshold: float = 0.1   # on-chip-QNN pruning threshold (Estimators...py:119)
    # Pruning mode: "absolute" (reference parity: zero |g| <= threshold —
    # unusable at the shipped 0.1, see results/noise_robustness/grad_prune/)
    # or "quantile" (threshold = fraction of elements pruned per step, the
    # scale-free usable form; e.g. 0.5 keeps the largest half).
    gradient_prune_mode: str = "absolute"
    # QuantumNAT sigma grid for the vmapped noise-sweep ensemble (config 5)
    noise_sweep: tuple[float, ...] = (0.0, 0.01, 0.05, 0.1)
    # Legacy simulator-backend knob: "auto" (default) defers to the
    # autotuned dispatcher below; an explicit value ("dense"/"dense_fused"/
    # "tensor"/"pallas"/"pallas_circuit"/"sharded_statevector"/"mps") forces
    # that path everywhere (see qdml_tpu.quantum.circuits.resolve_impl /
    # VALID_BACKENDS; "sharded" is the legacy alias for the mesh-sharded
    # statevector).
    backend: str = "auto"
    # Bond dimension for the "mps" impl (qdml_tpu.quantum.mps): chi >=
    # 2^(n/2) is EXACT for this circuit class; smaller chi is a controlled
    # approximation whose error is non-increasing in chi (docs/QUANTUM.md
    # "scaling past 12 qubits" has the guidance table).
    mps_chi: int = 8
    # Autotuned implementation dispatch (qdml_tpu.quantum.autotune,
    # docs/QUANTUM.md). impl: "auto" routes every circuit shape through the
    # measured selection table (falling back to XLA dense when no table
    # entry exists — the losing-kernel-on-the-hot-path failure BENCH_r05
    # exposed cannot recur); an explicit impl wins over BOTH the table and
    # the legacy backend knob.
    impl: str = "auto"
    # When the tuner itself may run (train-loop startup, serve warmup,
    # bench — never the request path): "auto" = only on a real accelerator
    # (the CPU test/fallback backend keeps the dense fallback and pays zero
    # tuning compiles), "on"/"off" force it.
    autotune: str = "auto"
    # Selection-table location; "" = results/autotune/qsc_impl.json
    # (QDML_QSC_AUTOTUNE_TABLE env overrides the default).
    autotune_table: str = ""
    # Per-sample RMS input normalization (scale-invariant angle encoding;
    # fixes low-SNR collapse of the raw-pilot QSC). OFF = reference parity.
    input_norm: bool = False


# ---------------------------------------------------------------------------
# Training
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class TrainConfig:
    """Mirrors ``Y2HRunner`` hyperparams (``Runner...py:20-46, 272-283, 320``)."""

    batch_size: int = 256        # reference batch_size_DML=256
    lr: float = 1e-3             # reference lr=1e-3
    lr_decay_epochs: int = 30    # halve every 30 epochs (Runner...py:272-283)
    lr_floor: float = 1e-6       # reference lr_threshold
    n_epochs: int = 100
    optimizer: str = "adam"      # 'adam' | 'sgd' | 'adamw' (Runner...py:40-46, :320)
    weight_decay: float = 0.01   # AdamW wd for the QSC (Runner...py:320)
    momentum: float = 0.9        # SGD momentum (Runner...py:45)
    print_freq: int = 50         # batch-loss print period (Runner...py:30)
    # Train steps fused into ONE device dispatch (lax.scan over the jitted
    # step with on-device batch synthesis inside the scan body). K=1
    # (default) ALSO runs under the scan: same donated carry, same in-program
    # synthesis, so even step-per-dispatch training pays no host-side batch
    # build and no steady-state host transfer off the probe cadence — the
    # BENCH_r05 K=1 QSC step was ~all dispatch gap. On the tunnelled
    # single-chip backend the host-side gap is ~half the step wall time
    # (docs/ROOFLINE.md), so fusing K>1 steps lifts wall MFU further toward
    # the device-busy figure. 0 = the legacy per-step placer data path
    # (also forced, with a warning, by train.checkify and multi-host sliced
    # loaders — scan.scan_eligible records the reason in the run JSONL).
    scan_steps: int = 1
    # Adam moment (m, v) storage dtype: "float32" (default, the reference's
    # torch.optim.Adam semantics) or "bfloat16" (halves the optimizer-state
    # HBM traffic; the fused head-weight grad+update is bandwidth-bound at
    # ~730 GB/s on v5e — results/perf_r5/scan_rbg.trace.json.gz,
    # multiply_add_fusion.53). Accumulation still happens in f32; only the
    # stored moments are rounded. A documented deviation, never the default.
    moments_dtype: str = "float32"
    # Numerics flight recorder (telemetry/numerics.py, docs/FLIGHTREC.md).
    # probe_every: log one on-device `numerics` probe record (grad/update
    # norms, fused NaN/Inf count) every N host-visible steps — the probe is
    # computed inside the compiled step (no extra compiles, pinned in
    # tests), only the device->host fetch follows this cadence. In the
    # scan-fused loops (the default dispatch) the per-dispatch loss fetch
    # AND the watchdog's in-loop checks ride the SAME cadence — off-cadence
    # dispatches enqueue with zero host transfers. 0 compiles the probes
    # OUT of the step program entirely (static flag) and fetches nothing in
    # steady state; the watchdog then checks the epoch-aggregate loss (one
    # existing fetch per epoch — NaN propagates through the sum, divergence
    # still raises, at epoch granularity). The first step of a run is
    # always logged when probes are on.
    probe_every: int = 100
    # Divergence watchdog: convert NaN/Inf losses/grads (and, when
    # watchdog_grad_norm_max > 0, grad-norm explosions past that ceiling)
    # into a typed DivergenceError with a flight-recorder dump
    # (results/<name>/flightrec/) instead of a silently garbage run.
    watchdog: bool = True
    watchdog_grad_norm_max: float = 0.0
    # Runtime numerics sanitizer (telemetry/sanitizer.py, docs/ANALYSIS.md):
    # thread jax.experimental.checkify (NaN/Inf, div-by-zero, index-OOB
    # checks) through the train step. OFF (default) never wraps — the traced
    # program is byte-identical to the unflagged build (zero extra compiles,
    # pinned in tests, the probe_every=0 static-flag pattern); ON adds one
    # error fetch per host-visible step and surfaces trips through the
    # flight-recorder dump + typed DivergenceError path. Debugging mode:
    # forces per-step dispatch (scan_steps is ignored with a warning).
    checkify: bool = False
    seed: int = 0
    workdir: str = "workspace"   # checkpoint root (reference ./workspace/Pn_128/HDCE)
    resume: bool = False         # reference cannot resume; we can


@dataclass(frozen=True)
class MeshConfig:
    """Device-mesh / SPMD layout. The reference's only distribution is
    ``torch.nn.DataParallel`` over 4 GPUs (``Runner...py:144-148``); here the
    mesh + sharding annotations ARE the communication layer."""

    data_axis: int = -1      # -1: all devices on the data axis
    model_axis: int = 1      # tensor/statevector-parallel axis size
    fed_axis: int = 1        # federated (scenario-grid) axis size
    # axis names used throughout qdml_tpu.parallel
    data_axis_name: str = "data"
    model_axis_name: str = "model"
    fed_axis_name: str = "fed"


@dataclass(frozen=True)
class ServeConfig:
    """Online inference serving engine (:mod:`qdml_tpu.serve`).

    The request path never compiles: the engine AOT-compiles one fused
    classifier+routing+estimator executable per batch bucket at warmup
    (``docs/SERVING.md``). Buckets default to powers of two up to
    ``max_batch``; requests coalesce in the micro-batcher until the batch
    fills or the oldest request has waited ``max_wait_ms``.
    """

    max_batch: int = 64        # largest (and last) bucket; batches never exceed it
    max_wait_ms: float = 2.0   # coalescing window before a partial batch flushes
    max_queue: int = 256       # bounded request queue; beyond it, shed Overloaded
    # Mesh sharding of the fused request-path executable: "auto" pjit-shards
    # every AOT bucket over the (fed, data, model) mesh whenever more than
    # one device is visible (batch axis data-parallel; buckets not divisible
    # by the data-axis size stay replicated), "off" pins the PR-2
    # single-device layout regardless of topology.
    shard: str = "auto"
    # Shard the stacked per-scenario trunks over the mesh "fed" axis (expert
    # parallelism for the all-trunks pass) — requires mesh.fed_axis ==
    # data.n_scenarios, exactly like federated training/eval placement.
    expert_sharding: bool = False
    # Expert-routing dispatch for the fused forward (ops/routing.py,
    # docs/SERVING.md): "auto" lets the measured dispatcher race pick
    # dense-all-trunks vs capacity-bucketed sparse per AOT bucket at warmup
    # (ops/dispatch_autotune.py — dense by construction below the sparse
    # eligibility window, so the reference S=3 grid pays zero extra warmup
    # compiles); "dense"/"sparse" force that path into every bucket.
    dispatch: str = "auto"
    # Sparse-dispatch per-expert bucket headroom: capacity = ceil(B*f/S).
    # Larger f buys fewer overflow-fallback batches under skewed routing at
    # ~f*B trunk-rows of compute; overflow is NEVER dropped (dense fallback).
    capacity_factor: float = 1.25
    # Batch-admission/executable mode (serve/batcher.py + serve/engine.py,
    # docs/SERVING.md "Ragged continuous batching"): "bucket" pads every
    # coalesced batch to its power-of-two bucket and flushes on bucket edges
    # (full batch or max_wait) — the PR-2..10 behavior; "ragged" compiles each
    # capacity tier with a TRACED valid-row count (pad rows masked inert
    # inside the program) and admits continuously — the batcher dispatches
    # whenever the engine is free instead of waiting out the coalescing
    # window; "auto" consults/fills the measured per-(platform, capacity)
    # race table (serve/batching_autotune.py) at warmup, exactly like the
    # routing and circuit-impl autotuners.
    batching: str = "auto"
    # Replica pool size: N ServeLoops sharing ONE warmup, ONE autotune table
    # and ONE MicroBatcher feed (serve/server.py ReplicaPool). Per-replica
    # ServeMetrics merge exactly via Histogram.merge.
    replicas: int = 1
    # Default per-request deadline in ms; 0 disables. Requests whose deadline
    # has passed are shed (typed Overloaded) at admission or dequeue, never
    # silently served late.
    deadline_ms: float = 0.0
    # Explicit bucket sizes; () = powers of two up to max_batch. Tests and
    # small deployments shrink this to bound warmup compile count.
    buckets: tuple[int, ...] = ()
    # Serve-loop worker threads pumping batcher -> engine. 1 (default) is the
    # PR-2 behavior; >1 overlaps host-side result handling with device
    # dispatch. Each worker keeps its own ServeMetrics; snapshots merge them
    # (telemetry Histogram.merge), so quantiles aggregate exactly.
    workers: int = 1
    # Runtime numerics sanitizer for the fused serving forward (the serve
    # twin of train.checkify): warmup AOT-compiles the checkified program per
    # bucket; a tripped check raises typed DivergenceError from infer(),
    # which the serve loop forwards into every affected request future. OFF
    # (default) compiles exactly today's program — zero extra compiles.
    checkify: bool = False
    # Loadgen arrival process: "poisson" (open-loop, PR-2), "bursty"
    # (two-state Markov-modulated Poisson — mean rate preserved, burst/lull
    # phases with rate ratio `burstiness`), or "diurnal" (replayed
    # sinusoidal-rate trace via thinning — a compressed day/night cycle).
    arrival: str = "poisson"
    # Arrival-process shape knob: the bursty lull-state rate is
    # rate/burstiness (burst state balances to keep the mean), and the
    # diurnal peak-to-trough ratio grows with it — serve/loadgen.arrival_times.
    burstiness: float = 4.0
    # Traffic-side drift injection for `qdml-tpu loadgen` (--drift-at=K):
    # requests offered from index K onward are drawn from the drifted channel
    # family (data/channels.family_table at this drift step) with the offered
    # scenario mix shifted toward drift_scenario — the loop's testable way to
    # drive "the environment changed mid-run" from the traffic side
    # (docs/CONTROL.md). 0 disables the drifted phase.
    drift_step: int = 0
    drift_scenario: int = 0
    # -- fault tolerance (docs/RESILIENCE.md) -------------------------------
    # Replica supervision: the pool's supervisor thread detects dead workers
    # (thread liveness; plus heartbeat age when stall_timeout_s > 0) and
    # auto-restarts the replica with jittered exponential backoff
    # (restart_backoff_s * 2^k, up to restart_budget restarts per slot). A
    # slot that exhausts its budget is QUARANTINED (structured
    # `replica_quarantined` event; peers keep serving).
    supervise: bool = True
    supervise_interval_s: float = 0.05
    restart_backoff_s: float = 0.05
    restart_budget: int = 3
    # Heartbeat-age stall detection: a replica whose newest worker heartbeat
    # is older than this WHILE the queue is non-empty is treated as dead
    # (a hung worker pins requests exactly like a crashed one). 0 disables —
    # thread-liveness-only supervision, the safe default on contended CI.
    stall_timeout_s: float = 0.0
    # Circuit breaker (brownout): when queue depth crosses
    # breaker_high_frac * max_queue the breaker OPENS and fast-fails new
    # submits with typed Overloaded("breaker_open") BEFORE they enqueue;
    # after breaker_open_s it goes HALF-OPEN and admits breaker_probes
    # probe requests — depth back under breaker_low_frac * max_queue closes
    # it, still-high depth re-opens. False = no breaker (PR-2..12 behavior).
    breaker: bool = False
    breaker_high_frac: float = 0.8
    breaker_low_frac: float = 0.3
    breaker_open_s: float = 0.25
    breaker_probes: int = 4
    # Per-connection protocol hardening (serve/server.py): a connection idle
    # (no complete line) for conn_timeout_s is reaped with a typed
    # idle_timeout reply (0 disables); a line longer than max_line_bytes gets
    # a typed bad_request reply and the connection closes (framing is lost
    # mid-line — resyncing would misparse the tail as fresh requests).
    conn_timeout_s: float = 30.0
    max_line_bytes: int = 8_388_608
    # Server-side idempotent-request dedup window: a retried request id
    # re-attaches to the in-flight/just-completed result instead of
    # double-dispatching (the client retry contract, docs/RESILIENCE.md).
    # Entries expire after dedup_ttl_s; 0 disables dedup.
    dedup_ttl_s: float = 30.0
    # Per-request phase tracing sample rate (telemetry/tracing.py,
    # docs/TELEMETRY.md "request tracing"): the fraction of requests that
    # carry a TraceContext decomposing enqueue->result latency into
    # batch_wait / queue_wait / compute / fetch (+ router wire) phase spans,
    # sampled deterministically on the request id so client, router and
    # backends agree without a wire bit. 0 (default) is pinned overhead-free:
    # no context objects, no clock stamps, HLO-identical executables, zero
    # extra compiles/host transfers. Tracing is host-side ONLY — it never
    # touches jitted code (graftlint rule trace-in-jit-path). The fleet
    # router reads this same knob for its wire-span sampling.
    trace_sample: float = 0.0
    # Local socket endpoint for `qdml-tpu serve`.
    host: str = "127.0.0.1"
    port: int = 8377


@dataclass(frozen=True)
class FleetConfig:
    """Fleet router tier (:mod:`qdml_tpu.fleet`, docs/FLEET.md): a front-door
    process (``qdml-tpu route``) that speaks the newline-JSON serve protocol
    on its own socket and fans requests out over N backend ``qdml-tpu
    serve`` processes ("hosts") through the :class:`~qdml_tpu.serve.client.
    ServeClient` retry/dedup/deadline contract — the tier between "one hot
    process" and "a fleet". Balancing is pluggable; per-backend health
    tracking ejects failing hosts with breaker-style state-machine semantics
    and re-admits them through half-open probes driven by the health poll;
    ``swap``/``scale``/``metrics``/``health`` verbs fan out / aggregate."""

    # Comma-separated backend endpoints ("127.0.0.1:8377,127.0.0.1:8380").
    # Empty = the single local serve endpoint at serve.host:serve.port.
    backends: str = ""
    # Balancing policy: "hash" routes each request id onto a consistent-hash
    # ring over the live backends (retries of one id land on one host, where
    # the server-side dedup window holds); "least_queue" routes to the live
    # backend with the shallowest queue as of the last health poll.
    balance: str = "hash"
    # Breaker-style ejection (serve/breaker.py semantics, per backend):
    # eject_failures CONSECUTIVE transport failures open the backend (no
    # traffic); after eject_s it goes half-open and the health poll (or a
    # routed probe request) spends readmit_probes successful probes to close
    # it again — one failure in half-open re-opens.
    eject_failures: int = 3
    eject_s: float = 1.0
    readmit_probes: int = 2
    # Health-poll cadence: drives least_queue balancing freshness, ejection
    # of silently dead hosts, and half-open re-admission probing.
    poll_interval_s: float = 0.5
    # Failover breadth: how many ALTERNATE backends a request may try after
    # its primary fails (bounded — a fleet-wide brownout must fail fast with
    # a typed reply, not sweep every host per request).
    failover: int = 2
    # Per-forward ServeClient discipline: socket timeout and SAME-BACKEND
    # retries before the router fails over to the next host.
    timeout_s: float = 10.0
    retries: int = 1
    # Router-side idempotent-id dedup window: a retried id re-attaches to
    # the in-flight (or just-served) forward instead of re-dispatching —
    # fleet-WIDE, so dedup holds across router failover, not just within one
    # backend's server-side window. 0 disables.
    dedup_ttl_s: float = 30.0
    # Front-door socket endpoint for `qdml-tpu route` (connection hardening
    # reuses serve.conn_timeout_s / serve.max_line_bytes).
    host: str = "127.0.0.1"
    port: int = 8378
    # -- elastic membership (fleet/lifecycle.py, docs/FLEET.md) --------------
    # Attach a BackendLifecycle to `qdml-tpu route`, arming the
    # {"op": "fleet", "backends": N} scaling form (spawn-and-warm admission,
    # drain-then-retire). Off by default: a fixed hand-started backend set
    # answers the scaling form with the typed fleet_scale_unavailable reason.
    elastic: bool = False
    # Comma-separated dotted-config flags every SPAWNED backend gets
    # ("--train.workdir=/ckpts,--serve.workers=2"): the spawned process must
    # restore the same checkpoints the boot-time fleet serves.
    spawn_overrides: str = ""
    # Spawn-and-warm deadline: banner + AOT warmup + autotune must complete
    # within this, or the standby is quarantined.
    spawn_timeout_s: float = 600.0
    # Retirement drain: how long a draining host may take to finish its
    # in-flight forwards before removal proceeds (stranded forwards are
    # reported — the dryrun gates on zero).
    drain_wait_s: float = 30.0
    # After removal, how long the retiring process stays alive for any
    # DIRECT-connected client's server-side dedup window before SIGINT
    # (router-mediated retries re-attach router-side regardless).
    dedup_grace_s: float = 0.0


@dataclass(frozen=True)
class ControlConfig:
    """Fleet control plane (:mod:`qdml_tpu.control`, docs/CONTROL.md): the
    closed serve -> detect -> adapt -> deploy loop. One supervised controller
    (``qdml-tpu control`` / :class:`~qdml_tpu.control.loop.FleetController`)
    polls the live ``{"op": "metrics"}`` stats, runs streaming drift
    detectors per scenario, fine-tunes ONLY the drifted trunk, canary-gates
    the candidate, hot-swaps it through the existing ``{"op": "swap"}`` path,
    watches for post-swap regression (automatic rollback), and autoscales the
    replica count against queue depth."""

    # -- controller loop ----------------------------------------------------
    interval_s: float = 1.0   # tick period between metric polls
    # Dry-run mode: the controller observes, detects and REPORTS every
    # decision (control_event records with "dry_run": true) but takes no
    # action — no fine-tune, no swap, no scaling.
    dry_run: bool = False
    # -- drift detectors (control/drift.py) ---------------------------------
    # Page–Hinkley/CUSUM drift magnitude slack and trip threshold, in the
    # units of the watched signal (classifier confidence and overflow rate
    # are fractions in [0, 1]; nmse_parity is in dB — scaled by ~10x
    # internally, see DriftMonitor). Debounce requires this many CONSECUTIVE
    # tripping windows before a drift_event fires (one noisy window must
    # never trigger a fine-tune).
    ph_delta: float = 0.01
    ph_threshold: float = 0.15
    debounce: int = 2
    # Windows with fewer than this many predictions for a scenario are not
    # fed to its detectors (a 2-sample confidence mean is noise, not signal).
    min_window: int = 8
    # -- continual fine-tuning (control/finetune.py) ------------------------
    ft_steps: int = 200       # fine-tune steps over the drifted family
    ft_lr: float = 1e-3
    ft_batch: int = 32
    # -- canary gate + rollback (control/deploy.py) -------------------------
    probe_n: int = 96         # held-out probe samples per scenario
    # Candidate must beat the live params by at least this much on the
    # drifted scenario's probes...
    min_gain_db: float = 0.3
    # ...while regressing NO un-drifted scenario by more than this.
    tol_db: float = 0.5
    # Post-swap watch window: ticks the deployer watches served stats after
    # a deploy; a parity/confidence regression beyond rollback_db inside the
    # window rolls the previous checkpoint back automatically.
    watch_ticks: int = 3
    rollback_db: float = 1.0
    # -- autoscaler (control/autoscale.py) ----------------------------------
    autoscale: bool = True
    min_replicas: int = 1
    max_replicas: int = 4
    # Queue-depth hysteresis band (in requests at dequeue): sustained depth
    # above `queue_high` for `scale_debounce` consecutive ticks scales up,
    # below `queue_low` scales down; `cooldown_ticks` must pass between
    # actions so the scaler never flaps on its own transient.
    queue_high: float = 16.0
    queue_low: float = 2.0
    scale_debounce: int = 2
    cooldown_ticks: int = 3
    # -- fleet autoscaler (control/fleet_scale.py, docs/FLEET.md) ------------
    # The backend-COUNT axis, mirroring the replica autoscaler's hysteresis
    # discipline one tier up: sustained fleet-total queue depth above
    # fleet_queue_high for fleet_debounce consecutive ticks admits one warmed
    # backend (<= max_backends); below fleet_queue_low with healthy SLO
    # retires one (>= min_backends); fleet_cooldown_ticks between actions
    # (spawn-and-warm is seconds-to-minutes — the cooldown must outlast it).
    # A planner target (plan --emit-target JSON) overrides the watermark
    # policy when loaded. Requires a lifecycle-armed poller (fleet.elastic).
    fleet_autoscale: bool = False
    min_backends: int = 1
    max_backends: int = 4
    fleet_queue_high: float = 32.0
    fleet_queue_low: float = 2.0
    fleet_debounce: int = 2
    fleet_cooldown_ticks: int = 5


@dataclass(frozen=True)
class EvalConfig:
    """Mirrors ``model_val`` config (``Test.py:11-21, 66``)."""

    snr_grid: tuple[float, ...] = (5.0, 7.0, 9.0, 11.0, 13.0, 15.0)
    test_len: int = 10000     # reference data_len_for_test
    batch_size: int = 200     # reference batch_size=200
    indicator: int = -1       # -1 = all scenarios mixed (Test.py:18)
    results_dir: str = "results"


@dataclass(frozen=True)
class ExperimentConfig:
    name: str = "default"
    data: DataConfig = field(default_factory=DataConfig)
    model: ModelConfig = field(default_factory=ModelConfig)
    quantum: QuantumConfig = field(default_factory=QuantumConfig)
    train: TrainConfig = field(default_factory=TrainConfig)
    mesh: MeshConfig = field(default_factory=MeshConfig)
    eval: EvalConfig = field(default_factory=EvalConfig)
    serve: ServeConfig = field(default_factory=ServeConfig)
    fleet: FleetConfig = field(default_factory=FleetConfig)
    control: ControlConfig = field(default_factory=ControlConfig)

    # Geometry-derived model dimensions. Single-sourced from DataConfig so a
    # non-default geometry (e.g. the tiny multichip dryrun) can never silently
    # desynchronize the CNN input image and head width from the channel shape
    # (reference hardcodes (2,16,8) and Linear(4096, 2048):
    # ``Runner...py:108``, ``Estimators...py:275``).

    @property
    def image_hw(self) -> tuple[int, int]:
        """CNN input spatial dims: (n_sub, n_beam) with 2 (re/im) channels."""
        return (self.data.n_sub, self.data.n_beam)

    @property
    def h_out_dim(self) -> int:
        """Estimation-head width: n_ant * n_sub * 2 real outputs."""
        return self.data.h_dim * 2

    @property
    def feat_dim(self) -> int:
        """Flattened trunk feature width: features * n_sub * n_beam."""
        return self.model.features * self.data.n_sub * self.data.n_beam


# ---------------------------------------------------------------------------
# BASELINE.json benchmark presets
# ---------------------------------------------------------------------------


def _preset(name: str, **overrides: Any) -> ExperimentConfig:
    cfg = ExperimentConfig(name=name)
    for dotted, value in overrides.items():
        cfg = override(cfg, dotted, value)
    return cfg


def presets() -> dict[str, ExperimentConfig]:
    """The five ``BASELINE.json`` benchmark configurations plus the
    beyond-reference ``robust_qsc`` config (results/robust/)."""
    return {
        # 1. Runner_P128 single-worker, 4-qubit QuantumNAT classifier (CPU ref)
        "single_4q": _preset(
            "single_4q",
            **{"quantum.n_qubits": 4, "quantum.use_quantumnat": True, "mesh.data_axis": 1},
        ),
        # 2. 8-qubit QNN + CNN estimator, data-parallel over the mesh
        "dp_8q": _preset("dp_8q", **{"quantum.n_qubits": 8, "mesh.data_axis": -1}),
        # 3. 16-qubit QNN, pjit model-sharded statevector
        "sharded_16q": _preset(
            "sharded_16q",
            **{
                "quantum.n_qubits": 16,
                "quantum.backend": "sharded",
                "mesh.model_axis": 4,
                "mesh.data_axis": 1,
            },
        ),
        # 4. Federated RIS: per-BS local QNN + psum aggregation
        "federated": _preset("federated", **{"mesh.fed_axis": 3, "mesh.data_axis": 1}),
        # 5. Noise-aware training sweep batched over hosts. Pruning is OFF:
        # at the reference's threshold (0.1) magnitude pruning zeroes every
        # Adam-scale NLL gradient and freezes training at chance
        # (results/noise_robustness/grad_prune/); enable it explicitly with
        # --quantum.use_gradient_pruning=true and a calibrated
        # --quantum.gradient_threshold.
        "nat_sweep": _preset("nat_sweep", **{"quantum.use_quantumnat": True}),
        # 6. (beyond BASELINE.json) robust quantum classifier: scale-invariant
        # angle encoding + SNR-jittered training — fixes the raw-pilot QSC's
        # low-SNR collapse and beats the classical CNN at SNR 5
        # (results/robust/).
        "robust_qsc": _preset(
            "robust_qsc",
            **{"quantum.input_norm": True, "data.snr_jitter": (5.0, 15.0)},
        ),
    }


# ---------------------------------------------------------------------------
# Dotted-path overrides + CLI
# ---------------------------------------------------------------------------


def override(cfg: Any, dotted: str, value: Any) -> Any:
    """Return a copy of a (nested, frozen) dataclass with ``dotted`` replaced.

    ``override(cfg, "train.lr", 3e-4)`` -> new ExperimentConfig.
    """
    head, _, rest = dotted.partition(".")
    if not dataclasses.is_dataclass(cfg):
        raise TypeError(f"cannot override {dotted!r} on non-dataclass {type(cfg)}")
    names = {f.name: f for f in dataclasses.fields(cfg)}
    if head not in names:
        raise KeyError(f"unknown config field {head!r} (have {sorted(names)})")
    if rest:
        new_sub = override(getattr(cfg, head), rest, value)
        return dataclasses.replace(cfg, **{head: new_sub})
    return dataclasses.replace(cfg, **{head: _coerce(value, names[head])})


def _coerce(value: Any, fld: dataclasses.Field) -> Any:
    if not isinstance(value, str):
        return value
    t = fld.type
    if t in ("int", int):
        return int(value)
    if t in ("float", float):
        return float(value)
    if t in ("bool", bool):
        return value.lower() in ("1", "true", "yes", "on")
    if isinstance(t, str) and t.startswith("tuple"):
        items = [v for v in value.replace("(", "").replace(")", "").split(",") if v.strip()]
        return tuple(float(v) if "." in v else int(v) for v in items)
    return value


def from_args(argv: Sequence[str], base: ExperimentConfig | None = None) -> ExperimentConfig:
    """Parse ``--preset=NAME`` plus ``--a.b.c=value`` dotted overrides."""
    cfg = base or ExperimentConfig()
    rest = []
    for arg in argv:
        if arg.startswith("--preset="):
            cfg = presets()[arg.split("=", 1)[1]]
        else:
            rest.append(arg)
    for arg in rest:
        if not arg.startswith("--") or "=" not in arg:
            raise SystemExit(f"unrecognised argument {arg!r}; expected --path.to.field=value")
        dotted, value = arg[2:].split("=", 1)
        cfg = override(cfg, dotted, value)
    return cfg
