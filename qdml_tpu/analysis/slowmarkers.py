"""Slow-marker rule: every test over the tier-1 wall-clock threshold must be
``@pytest.mark.slow`` or grandfathered in the committed allowlist.

Folded into graftlint from ``scripts/lint_markers.py`` (which is now a thin
shim over this module) so the repo has ONE lint entry point: the rule is
data-driven — it needs a ``pytest --durations=0`` report from a real run —
so ``qdml-tpu lint`` includes it only when given ``--durations=FILE``.

The allowlist (``scripts/tier1_slow_allowlist.txt``) exists because "slow" is
not the same as "optional": the XLA-compile-dominated training e2e tests
exceed any per-test threshold on the 1-core builder host yet ARE the tier-1
acceptance coverage — marking them ``slow`` would deselect the gate itself.
New offenders outside that committed set fail the lint, so unbudgeted
slowness cannot land silently.
"""

from __future__ import annotations

import ast
import os
import re

from qdml_tpu.analysis.engine import Finding

RULE_ID = "slow-marker"
DEFAULT_THRESHOLD_S = 5.0
DEFAULT_ALLOWLIST = os.path.join("scripts", "tier1_slow_allowlist.txt")

# "12.34s call     tests/test_x.py::test_y[param]" — only the call phase
# counts (setup/teardown time belongs to fixtures, which the marker on the
# test cannot deselect on its own).
_DURATION_RE = re.compile(
    r"^\s*(?P<secs>\d+(?:\.\d+)?)s\s+call\s+(?P<nodeid>\S+)\s*$"
)


def parse_durations(text: str) -> dict[str, float]:
    """nodeid -> call seconds, max over parametrizations."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        m = _DURATION_RE.match(line)
        if not m:
            continue
        nodeid = m.group("nodeid").split("[", 1)[0]  # fold parametrizations
        secs = float(m.group("secs"))
        out[nodeid] = max(secs, out.get(nodeid, 0.0))
    return out


def _decorators_mark_slow(dec_list) -> bool:
    for dec in dec_list:
        target = dec.func if isinstance(dec, ast.Call) else dec
        # pytest.mark.slow -> Attribute(attr='slow', value=Attribute(attr='mark'))
        if isinstance(target, ast.Attribute) and target.attr == "slow":
            v = target.value
            if isinstance(v, ast.Attribute) and v.attr == "mark":
                return True
    return False


def has_slow_marker(path: str, test_name: str) -> bool:
    """True when the test function (or its class / module pytestmark) carries
    pytest.mark.slow. Source-level check: no pytest import, no collection."""
    try:
        with open(path) as fh:
            tree = ast.parse(fh.read())
    except (OSError, SyntaxError):
        return False

    def module_marked() -> bool:
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "pytestmark" for t in node.targets
            ):
                vals = (
                    node.value.elts if isinstance(node.value, (ast.List, ast.Tuple))
                    else [node.value]
                )
                if _decorators_mark_slow(vals):
                    return True
        return False

    def walk(body, inherited: bool) -> bool | None:
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name == test_name:
                    return inherited or _decorators_mark_slow(node.decorator_list)
            elif isinstance(node, ast.ClassDef):
                found = walk(
                    node.body, inherited or _decorators_mark_slow(node.decorator_list)
                )
                if found is not None:
                    return found
        return None

    found = walk(tree.body, module_marked())
    return bool(found)


def load_allowlist(path: str | None) -> set[str]:
    if not path or not os.path.exists(path):
        return set()
    out = set()
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if line:
                out.add(line)
    return out


def check_durations(
    root: str,
    durations_text: str,
    threshold_s: float = DEFAULT_THRESHOLD_S,
    allowlist_path: str | None = None,
) -> list[Finding]:
    """Findings (rule ``slow-marker``) for every over-threshold test lacking
    the marker and absent from the allowlist. An empty/unparseable durations
    report is itself a finding: the caller asked for the check but fed it
    nothing (run pytest with ``--durations=0``)."""
    durations = parse_durations(durations_text)
    if not durations:
        return [
            Finding(
                rule=RULE_ID,
                path="(durations report)",
                line=0,
                message=(
                    "no '<secs>s call <nodeid>' lines found — run pytest with "
                    "--durations=0 and feed that output"
                ),
            )
        ]
    allow = load_allowlist(
        allowlist_path
        if allowlist_path is not None
        else os.path.join(root, DEFAULT_ALLOWLIST)
    )
    out: list[Finding] = []
    for nodeid, secs in sorted(durations.items(), key=lambda kv: -kv[1]):
        if secs <= threshold_s:
            continue
        relpath, test_name = nodeid.split("::", 1)
        test_name = test_name.split("::")[-1]
        if has_slow_marker(os.path.join(root, relpath), test_name):
            continue
        if nodeid in allow:
            continue
        out.append(
            Finding(
                rule=RULE_ID,
                path=relpath,
                line=0,
                message=(
                    f"{nodeid} took {secs:.2f}s (> {threshold_s:g}s) without "
                    "@pytest.mark.slow — mark it slow, or add it to "
                    f"{DEFAULT_ALLOWLIST} with a reason"
                ),
                context=test_name,
                text=nodeid,  # stable fingerprint input: the nodeid itself
            )
        )
    return out
