"""graftlint rule set: ~10 JAX/TPU hazard classes this repo has shipped.

Each rule is a callable ``(ModuleContext) -> list[Finding]`` registered in
:data:`RULES` with its id and a one-line rationale (docs/ANALYSIS.md carries
the full catalog, with the shipped bug each rule would have caught).

Rules are deliberately precise over exhaustive: a lint that cries wolf gets
disabled; one that encodes the exact shape of a bug we shipped gets trusted.
Every heuristic documents what it intentionally does NOT catch.
"""

from __future__ import annotations

import ast
import re
from typing import Callable

from qdml_tpu.analysis.engine import Finding, ModuleContext, dotted_name
from qdml_tpu.analysis import project

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


# ---------------------------------------------------------------------------
# 1. jit-mutable-global — jitted code closing over module-level mutable state
# ---------------------------------------------------------------------------


def rule_jit_mutable_global(ctx: ModuleContext) -> list[Finding]:
    """A traced function reading a module-level dict/list/set closes over a
    value jit BAKES IN at trace time: later mutations are silently ignored
    (or worse, retrigger a retrace via a non-hashable static). Reads of
    immutable module constants (tuples, numbers, strings) are fine and not
    flagged."""
    out: list[Finding] = []
    if not ctx.mutable_globals:
        return out
    for fn in ctx.traced:
        params = {a.arg for a in _all_args(fn)}
        local_stores: set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, (ast.AnnAssign, ast.AugAssign)):
                targets = [sub.target]
            else:
                continue
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        local_stores.add(n.id)
        seen: set[str] = set()
        for sub in ast.walk(fn):
            if not (isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Load)):
                continue
            name = sub.id
            if (
                name in ctx.mutable_globals
                and name not in params
                and name not in local_stores
                and name not in seen
            ):
                seen.add(name)
                out.append(
                    ctx.finding(
                        "jit-mutable-global",
                        sub,
                        f"jit-reachable {ctx.qualname(fn)!r} reads module-level "
                        f"mutable {name!r}: the traced program freezes its value "
                        "at first compile — pass it as an argument or make it "
                        "immutable",
                    )
                )
    return out


def _all_args(fn: ast.AST) -> list[ast.arg]:
    a = fn.args
    return [*a.posonlyargs, *a.args, *a.kwonlyargs] + (
        [a.vararg] if a.vararg else []
    ) + ([a.kwarg] if a.kwarg else [])


# ---------------------------------------------------------------------------
# 2. train-step-jit-audit — makers must declare donation/static intent
# ---------------------------------------------------------------------------

_TRAIN_MAKER_RE = re.compile(project.TRAIN_MAKER_PATTERN)


def rule_train_step_jit_audit(ctx: ModuleContext) -> list[Finding]:
    """A train-step maker jitting without ``donate_argnums``/``static_*`` is
    how the double-HBM-footprint step ships: the optimizer state and params
    are both live across the update unless donated. Eval-step makers are
    exempt (nothing to donate); makers that delegate jitting elsewhere (the
    scan machinery) carry no jit and are not flagged."""
    out: list[Finding] = []
    audit_kws = {"donate_argnums", "donate_argnames", "static_argnums", "static_argnames"}
    for fn, qual in ctx.functions:
        if not _TRAIN_MAKER_RE.match(fn.name):
            continue
        for sub in ast.walk(fn):
            jit_call = None
            if isinstance(sub, ast.Call):
                callee = ctx.canonical(sub.func)
                if callee and callee.rsplit(".", 1)[-1] == "jit":
                    jit_call = sub
                elif callee and callee.rsplit(".", 1)[-1] == "partial" and any(
                    (ctx.canonical(a) or "").rsplit(".", 1)[-1] == "jit" for a in sub.args
                ):
                    jit_call = sub
            elif isinstance(sub, _FuncNode) and sub is not fn:
                for dec in sub.decorator_list:
                    callee = ctx.canonical(dec)
                    if callee and callee.rsplit(".", 1)[-1] == "jit":
                        out.append(
                            ctx.finding(
                                "train-step-jit-audit",
                                dec,
                                f"train-step maker {qual!r} jits with no "
                                "donate_argnums/static_* audit — donate the "
                                "state (utils.platform.donation_argnums) or "
                                "declare statics explicitly",
                            )
                        )
            if jit_call is not None and not (
                {kw.arg for kw in jit_call.keywords} & audit_kws
            ):
                out.append(
                    ctx.finding(
                        "train-step-jit-audit",
                        jit_call,
                        f"train-step maker {qual!r} jits with no "
                        "donate_argnums/static_* audit — donate the state "
                        "(utils.platform.donation_argnums) or declare statics "
                        "explicitly",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 3. tracer-branch — Python control flow on traced values
# ---------------------------------------------------------------------------


def rule_tracer_branch(ctx: ModuleContext) -> list[Finding]:
    """``if``/``while`` on a value produced by a jnp/jax op inside a traced
    function raises TracerBoolConversionError at best and silently freezes a
    branch at worst. Static Python flags (``if probes:`` bound before jit)
    are NOT flagged — only tests referencing locals assigned from jnp/jax
    calls, or containing such a call directly."""
    out: list[Finding] = []
    for fn in ctx.traced:
        device_locals: set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and _mentions_jax_call(ctx, sub.value):
                for t in sub.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            device_locals.add(n.id)
        for sub in ast.walk(fn):
            if not isinstance(sub, (ast.If, ast.While)):
                continue
            test = sub.test
            bad = _mentions_jax_call(ctx, test) or any(
                isinstance(n, ast.Name) and n.id in device_locals
                for n in ast.walk(test)
            )
            if bad:
                kind = "if" if isinstance(sub, ast.If) else "while"
                out.append(
                    ctx.finding(
                        "tracer-branch",
                        sub,
                        f"Python `{kind}` on a traced value inside jit-reachable "
                        f"{ctx.qualname(fn)!r} — use jnp.where/lax.cond/"
                        "lax.while_loop (host branching cannot see device values)",
                    )
                )
    return out


def _mentions_jax_call(ctx: ModuleContext, node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            callee = ctx.canonical(sub.func)
            if callee and (
                callee.startswith("jax.numpy.")
                or callee.startswith("jax.lax.")
                or callee.startswith("jax.nn.")
            ):
                return True
    return False


# ---------------------------------------------------------------------------
# 4. host-sync-hot-path — device->host syncs in step/request paths
# ---------------------------------------------------------------------------


def rule_host_sync_hot_path(ctx: ModuleContext) -> list[Finding]:
    """``.item()`` / ``float()`` / ``np.asarray`` / ``jax.device_get`` inside
    a traced step body breaks tracing outright; inside the serve request path
    (project.HOT_HOST_FUNCS) each one is a dispatch stall that must be
    deliberate — the audit is the point: intentional syncs carry a
    suppression with the reason written next to them."""
    out: list[Finding] = []
    hot_host = project.HOT_HOST_FUNCS.get(ctx.path, ())
    targets: list[tuple[ast.AST, str, str]] = []  # (fn, qual, kind)
    for fn, qual in ctx.functions:
        if fn in ctx.traced:
            targets.append((fn, qual, "jit-reachable"))
        elif qual in hot_host:
            targets.append((fn, qual, "serve-request-path"))
    for fn, qual, kind in targets:
        nested = {
            sub for sub in ast.walk(fn) if isinstance(sub, _FuncNode) and sub is not fn
        }
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            if any(sub in ast.walk(n) for n in nested) and kind == "serve-request-path":
                continue  # nested defs in host funcs judged on their own merits
            label = None
            callee = ctx.canonical(sub.func)
            if isinstance(sub.func, ast.Attribute) and sub.func.attr in project.HOST_SYNC_ATTRS:
                label = f".{sub.func.attr}()"
            elif callee in ("numpy.asarray", "numpy.array"):
                label = callee.replace("numpy", "np")
            elif (
                kind == "jit-reachable"  # float()/int() on host values in the
                # serve request path is plain Python; on a tracer it breaks
                # the trace — only the traced bodies get this check
                and isinstance(sub.func, ast.Name)
                and sub.func.id in project.HOST_SYNC_NAMES
                and sub.args
                and not isinstance(sub.args[0], ast.Constant)
            ):
                label = f"{sub.func.id}()"
            if label:
                out.append(
                    ctx.finding(
                        "host-sync-hot-path",
                        sub,
                        f"host sync {label} in {kind} {qual!r} — a device->host "
                        "transfer here stalls the dispatch pipeline (or breaks "
                        "tracing); move it off the hot path or suppress with the "
                        "reason the sync is deliberate",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 5. wall-clock-in-jit — time frozen into the traced program
# ---------------------------------------------------------------------------


def rule_wall_clock_in_jit(ctx: ModuleContext) -> list[Finding]:
    """``time.time()``/``datetime.now()`` inside a traced function evaluates
    ONCE at trace time and compiles to a constant — every later step reuses
    the first step's timestamp. Timing belongs outside the step (StepClock)."""
    out: list[Finding] = []
    for fn in ctx.traced:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            callee = ctx.canonical(sub.func)
            if not callee:
                continue
            head, _, tail = callee.rpartition(".")
            if tail in project.WALL_CLOCK_CALLS and head.split(".")[0] in (
                "time",
                "datetime",
            ):
                out.append(
                    ctx.finding(
                        "wall-clock-in-jit",
                        sub,
                        f"{callee}() inside jit-reachable {ctx.qualname(fn)!r} "
                        "freezes to a trace-time constant — time the dispatch "
                        "from the host (telemetry.StepClock)",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 6. primary-only-collective — multihost deadlock by is_primary guard
# ---------------------------------------------------------------------------


def rule_primary_only_collective(ctx: ModuleContext) -> list[Finding]:
    """A collective (orbax save, psum, multihost broadcast) reached by the
    primary process only: every other process never joins and the primary
    blocks at the collective's barrier forever — the exact shape PR 3
    review-hardened in the flight-recorder dump. Two forms: the collective
    lexically inside ``if is_primary():``, and the early-return form
    (``if not is_primary(): return`` followed by a collective)."""
    out: list[Finding] = []

    def is_primary_test(test: ast.AST) -> bool:
        for sub in ast.walk(test):
            name = dotted_name(sub.func) if isinstance(sub, ast.Call) else None
            if name and name.rsplit(".", 1)[-1] in project.PRIMARY_GUARDS:
                return True
        return False

    def collectives_in(node: ast.AST):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = dotted_name(sub.func)
                if name and name.rsplit(".", 1)[-1] in project.COLLECTIVE_CALLS:
                    yield sub, name

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.If) or not is_primary_test(node.test):
            continue
        # form 1: collective inside the guarded body (either branch)
        for branch in (node.body, node.orelse):
            for stmt in branch:
                for call, name in collectives_in(stmt):
                    out.append(
                        ctx.finding(
                            "primary-only-collective",
                            call,
                            f"collective {name!r} guarded by a primary-process "
                            "check: non-primary processes never join and the "
                            "primary deadlocks at the barrier — run the "
                            "collective on ALL processes, guard only the "
                            "host-side write",
                        )
                    )
        # form 2: `if <primary test>: return/raise` then a collective later
        body_exits = any(isinstance(s, (ast.Return, ast.Raise)) for s in node.body)
        if not body_exits:
            continue
        fn = ctx.enclosing_function(node)
        if fn is None:
            continue
        for call, name in collectives_in(fn):
            if call.lineno > node.body[-1].lineno:
                out.append(
                    ctx.finding(
                        "primary-only-collective",
                        call,
                        f"collective {name!r} after a primary-gated early "
                        f"return (line {node.lineno}): non-primary processes "
                        "exit before joining — move the collective above the "
                        "guard (PR 3's flight-recorder fix)",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 7. serve-lock-discipline — thread-shared state touched outside its lock
# ---------------------------------------------------------------------------


def rule_serve_lock_discipline(ctx: ModuleContext) -> list[Finding]:
    """The project lock map (analysis/project.py) names the serve-path
    attributes that are shared across threads and the lock that owns each.
    Any ``self.<attr>`` access outside ``with self.<lock>:`` (except in
    ``__init__``, which happens-before sharing) is a data race of the shape
    the PR-2 soak test caught hanging."""
    lock_map = project.LOCK_MAP.get(ctx.path)
    if not lock_map:
        return []
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ClassDef) or node.name not in lock_map:
            continue
        attr_locks = lock_map[node.name]
        for fn_node in ast.walk(node):
            if not isinstance(fn_node, _FuncNode) or fn_node.name == "__init__":
                continue
            for sub in ast.walk(fn_node):
                if not (
                    isinstance(sub, ast.Attribute)
                    and isinstance(sub.value, ast.Name)
                    and sub.value.id == "self"
                    and sub.attr in attr_locks
                ):
                    continue
                lock = attr_locks[sub.attr]
                if not _under_lock(ctx, sub, lock):
                    out.append(
                        ctx.finding(
                            "serve-lock-discipline",
                            sub,
                            f"self.{sub.attr} accessed outside `with "
                            f"self.{lock}:` in {node.name}.{fn_node.name} — "
                            "thread-shared serve state must hold its lock "
                            "(lock map: analysis/project.py)",
                        )
                    )
    return out


def _under_lock(ctx: ModuleContext, node: ast.AST, lock_attr: str) -> bool:
    cur = ctx.parent.get(node)
    while cur is not None:
        if isinstance(cur, ast.With):
            for item in cur.items:
                expr = item.context_expr
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                    and expr.attr == lock_attr
                ):
                    return True
        if isinstance(cur, _FuncNode):
            return False
        cur = ctx.parent.get(cur)
    return False


# ---------------------------------------------------------------------------
# 8. stranded-future — dequeue without guaranteed resolution
# ---------------------------------------------------------------------------


def rule_stranded_future(ctx: ModuleContext) -> list[Finding]:
    """A function that pops requests off a queue AND resolves futures must
    guarantee resolution on every exit path: an exception between the pop and
    ``set_result`` strands the client forever (the PR-2 soak-test hang). The
    check requires a ``try`` whose handler or ``finally`` resolves
    (``set_result``/``set_exception``) in any function that both dequeues and
    touches ``.future``."""
    out: list[Finding] = []
    for fn, qual in ctx.functions:
        dequeues = [
            sub
            for sub in ast.walk(fn)
            if isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr in ("next_batch", "popleft", "get_nowait")
        ]
        if not dequeues:
            continue
        touches_future = any(
            isinstance(sub, ast.Attribute) and sub.attr == "future"
            for sub in ast.walk(fn)
        )
        if not touches_future:
            continue
        guarded = False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Try):
                continue
            resolve_zones = list(sub.finalbody)
            for h in sub.handlers:
                resolve_zones.extend(h.body)
            for stmt in resolve_zones:
                for call in ast.walk(stmt):
                    if (
                        isinstance(call, ast.Call)
                        and isinstance(call.func, ast.Attribute)
                        and call.func.attr in ("set_result", "set_exception")
                    ):
                        guarded = True
        if not guarded:
            out.append(
                ctx.finding(
                    "stranded-future",
                    dequeues[0],
                    f"{qual!r} dequeues requests and resolves futures with no "
                    "try/except/finally that resolves on failure — an exception "
                    "between the pop and set_result hangs the client forever",
                )
            )
    return out


# ---------------------------------------------------------------------------
# 9. broad-except — typed errors silently swallowed
# ---------------------------------------------------------------------------


def rule_broad_except(ctx: ModuleContext) -> list[Finding]:
    """``except:`` / ``except Exception`` / ``except BaseException`` swallow
    the project's typed failures (DivergenceError carries the flight-recorder
    dump; KeyboardInterrupt under BaseException kills ctrl-C). Handlers that
    unconditionally re-raise (a bare ``raise`` anywhere in the handler) are
    inspect-and-forward patterns and are not flagged."""
    out: list[Finding] = []
    broad = {"Exception", "BaseException"}
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names: list[str] = []
        if node.type is None:
            names = ["(bare)"]
        elif isinstance(node.type, ast.Name) and node.type.id in broad:
            names = [node.type.id]
        elif isinstance(node.type, ast.Tuple):
            names = [e.id for e in node.type.elts if isinstance(e, ast.Name) and e.id in broad]
        if not names:
            continue
        if any(isinstance(sub, ast.Raise) and sub.exc is None for sub in ast.walk(node)):
            continue  # inspect-and-re-raise
        swallows = ", ".join(project.TYPED_EXCEPTIONS)
        if names == ["Exception"]:
            swallows = project.TYPED_EXCEPTIONS[0]
        out.append(
            ctx.finding(
                "broad-except",
                node,
                f"broad `except {names[0]}` can swallow typed {swallows} — "
                "narrow to the exceptions this site expects, or suppress with "
                "the reason the catch-all is load-bearing",
            )
        )
    return out


# ---------------------------------------------------------------------------
# 10. import-time-jnp — device ops at module import
# ---------------------------------------------------------------------------


def rule_import_time_jnp(ctx: ModuleContext) -> list[Finding]:
    """A ``jnp.`` op at module scope allocates device buffers (and may
    initialize the backend) the moment anything imports the module — before
    distributed init, before platform pinning, in processes (the bench
    parent) that must never touch jax. Constants belong in numpy or inside
    functions."""
    out: list[Finding] = []
    # walk the module but never descend into function/class bodies: what's
    # left executes at import time (including top-level if/try/for blocks)
    stack: list[ast.AST] = list(ctx.tree.body)
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (*_FuncNode, ast.ClassDef)):
            continue
        for sub in ast.iter_child_nodes(stmt):
            stack.append(sub)
        if isinstance(stmt, ast.Call):
            callee = ctx.canonical(stmt.func)
            if callee and (
                callee.startswith("jax.numpy.") or callee.startswith("jax.lax.")
            ):
                out.append(
                    ctx.finding(
                        "import-time-jnp",
                        stmt,
                        f"{callee} called at module import time — device "
                        "allocation/backend init as an import side effect; "
                        "build device constants inside the function that "
                        "uses them",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 11/12. Pallas kernel discipline — host-loop launches, interpret left on
# ---------------------------------------------------------------------------


def _pallas_call_sites(ctx: ModuleContext) -> list[ast.Call]:
    return [
        node
        for node in ast.walk(ctx.tree)
        if isinstance(node, ast.Call)
        and (ctx.canonical(node.func) or "").rsplit(".", 1)[-1] == "pallas_call"
    ]


def rule_pallas_host_loop(ctx: ModuleContext) -> list[Finding]:
    """``pallas_call`` inside a host-side Python ``for``/``while`` (the v1
    per-layer circuit shape: one kernel launch per gate/layer, bouncing the
    operand through HBM between iterations) — the loop belongs INSIDE the
    kernel (``jax.lax.fori_loop`` with the state pinned in VMEM) or inside
    one ``lax.scan``. Loops inside a nested function (a kernel body, a scan
    body) are not host loops and are not flagged."""
    out: list[Finding] = []
    for call in _pallas_call_sites(ctx):
        cur = ctx.parent.get(call)
        while cur is not None and not isinstance(cur, _FuncNode):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.append(
                    ctx.finding(
                        "pallas-host-loop",
                        call,
                        "pallas_call launched from a host-side Python loop — "
                        "each iteration is a separate kernel launch with an "
                        "HBM round-trip between them; move the loop into the "
                        "kernel (fori_loop over VMEM-resident state, see "
                        "quantum/pallas_kernels.fused_circuit_expvals) or "
                        "under one lax.scan",
                    )
                )
                break
            cur = ctx.parent.get(cur)
    return out


def rule_pallas_interpret_literal(ctx: ModuleContext) -> list[Finding]:
    """``interpret=True`` hardcoded in a ``pallas_call``: the kernel silently
    runs on the Pallas interpreter EVERYWHERE — including on a real TPU —
    turning a production kernel into an emulation benchmark. Production code
    must route the decision through the one config-driven knob
    (``utils.platform.pallas_interpret``); test/fixture paths are outside the
    gate's scan roots by design."""
    out: list[Finding] = []
    for call in _pallas_call_sites(ctx):
        for kw in call.keywords:
            if (
                kw.arg == "interpret"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
            ):
                out.append(
                    ctx.finding(
                        "pallas-interpret-literal",
                        call,
                        "pallas_call(interpret=True) left enabled outside "
                        "test/fixture paths — this compiles the interpreter "
                        "in unconditionally (TPU included); pass "
                        "interpret=utils.platform.pallas_interpret() so the "
                        "eager/jit/interpret choice stays config-driven",
                    )
                )
    return out


# ---------------------------------------------------------------------------
# 13. gate-matrix-in-loop — per-gate matrix construction inside a layer loop
# ---------------------------------------------------------------------------


def rule_gate_matrix_in_loop(ctx: ModuleContext) -> list[Finding]:
    """A gate-matrix constructor (project.GATE_MATRIX_CONSTRUCTORS: the 2x2
    builders ``rot_gate``/``gate_h``/``gate_rx``) called inside a host-side
    Python ``for``/``while`` rebuilds the per-gate matrix every iteration —
    the exact shape Qandle-style gate-matrix caching removed from the dense/
    tensor hot paths (one vectorized trig shot + ``fused_layer_unitaries``
    instead of 2Ln scalar gate builds). Loops inside a nested function (a
    scan body judged on its own) are not host loops here, mirroring
    ``pallas-host-loop``. Deliberately NOT caught: ad-hoc ``jnp.stack``-built
    matrices (no name to match — the constructors are the project's single
    sanctioned entry points) and loops that merely APPLY a precomputed
    matrix (``apply_1q``/``apply_perm``), which is the fix, not the bug."""
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)):
            continue
        callee = ctx.canonical(node.func) or dotted_name(node.func) or ""
        if callee.rsplit(".", 1)[-1] not in project.GATE_MATRIX_CONSTRUCTORS:
            continue
        cur = ctx.parent.get(node)
        while cur is not None and not isinstance(cur, _FuncNode):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                out.append(
                    ctx.finding(
                        "gate-matrix-in-loop",
                        node,
                        f"per-gate matrix constructor {callee!r} called inside "
                        "a Python loop — the gate matrices are rebuilt every "
                        "iteration; derive the whole circuit's trig in one "
                        "vectorized shot and fuse the layer unitary "
                        "(quantum/circuits.py fused_layer_unitaries / "
                        "apply_ansatz_tensor's cached trig table)",
                    )
                )
                break
            cur = ctx.parent.get(cur)
    return out


def rule_data_dependent_shape_in_jit(ctx: ModuleContext) -> list[Finding]:
    """A value-dependent-shape op inside a jit-reachable function: the shape
    of ``jnp.nonzero``/``jnp.unique``/one-arg ``jnp.where`` (and of
    boolean-mask indexing, which lowers to nonzero+gather) depends on runtime
    VALUES, which XLA's static-shape compilation cannot express — a
    ConcretizationTypeError at best, a silent host fallback at worst. The
    hazard class capacity-bucketed sparse dispatch (``ops/routing.py``) is
    built to avoid: rank with a one-hot cumsum, pack into FIXED-capacity
    buckets, scatter/gather by computed slots.

    Three shapes are caught: (a) calls to the ``project.DATA_DEP_SHAPE_CALLS``
    jnp functions, (b) ``jnp.where`` with exactly one argument (the nonzero
    form — the 3-arg select is the FIX, never flagged), (c) subscripts whose
    index is a comparison (``x[y > 0]``) or a local assigned from one
    (``mask = y > 0; x[mask]``). Deliberately NOT caught: the same ops in
    host-side code (eval scripts aggregate with np.unique legitimately),
    integer-array gathers (``x[idx]`` is shape-static), and masks consumed
    by ``jnp.where``/arithmetic (masking VALUES is fine; masking SHAPE is
    the bug)."""
    out: list[Finding] = []
    for fn in ctx.traced:
        # locals assigned from a bare comparison: the mask-indexing feeders
        mask_locals: set[str] = set()
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Compare):
                for t in sub.targets:
                    if isinstance(t, ast.Name):
                        mask_locals.add(t.id)
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Call):
                callee = ctx.canonical(sub.func) or ""
                if callee.startswith("jax.numpy."):
                    tail = callee.rsplit(".", 1)[-1]
                    if tail in project.DATA_DEP_SHAPE_CALLS and any(
                        kw.arg == "size" for kw in sub.keywords
                    ):
                        # jnp.nonzero(x, size=k) / jnp.unique(x, size=k):
                        # jax's documented static-shape escape hatch — the
                        # output shape is the literal k, not a runtime value
                        continue
                    if tail in project.DATA_DEP_SHAPE_CALLS:
                        out.append(
                            ctx.finding(
                                "data-dependent-shape-in-jit",
                                sub,
                                f"{callee} inside jit-reachable "
                                f"{ctx.qualname(fn)!r}: its output shape "
                                "depends on runtime values — XLA needs static "
                                "shapes; pack into fixed-capacity buckets "
                                "with computed slots instead "
                                "(ops/routing.sparse_dispatch is the worked "
                                "example)",
                            )
                        )
                    elif (
                        tail == "where"
                        and len(sub.args) == 1
                        and not sub.keywords
                    ):
                        out.append(
                            ctx.finding(
                                "data-dependent-shape-in-jit",
                                sub,
                                "one-argument jnp.where (the nonzero form) "
                                f"inside jit-reachable {ctx.qualname(fn)!r} "
                                "returns value-dependent shapes — use the "
                                "3-argument select, or fixed-capacity "
                                "slot packing",
                            )
                        )
            elif isinstance(sub, ast.Subscript):
                idx = sub.slice
                masked = isinstance(idx, ast.Compare) or (
                    isinstance(idx, ast.Name) and idx.id in mask_locals
                )
                if masked:
                    out.append(
                        ctx.finding(
                            "data-dependent-shape-in-jit",
                            sub,
                            "boolean-mask indexing inside jit-reachable "
                            f"{ctx.qualname(fn)!r} lowers to nonzero+gather "
                            "(value-dependent shape) — select with "
                            "jnp.where(mask, a, b), or pack fixed-capacity "
                            "buckets (ops/routing.sparse_dispatch)",
                        )
                    )
    return out


def rule_collective_outside_shardmap(ctx: ModuleContext) -> list[Finding]:
    """A named-axis collective (``ppermute``/``psum``/``axis_index``/...,
    project.SHARD_AXIS_CALLS) in ``quantum/`` traced outside a ``shard_map``
    region. The mesh-sharded statevector keeps EVERY collective inside the
    one ``shard_map`` region so XLA schedules the exchanges; the same call
    reached from outside is the subsystem's multihost-deadlock shape — an
    unbound-axis trace error at best, and inside a pjit program a collective
    some devices never join at worst.

    "Inside the region" is judged by local reachability: the functions
    passed to ``shard_map(...)`` (directly or through ``functools.partial``)
    seed a closure over same-module calls, and a collective in any function
    OUTSIDE that closure — or at module level — is a finding. Deliberately
    NOT caught: cross-module call chains (the sharded subsystem is
    single-module by design — a helper that needs the axis lives next to the
    region that binds it) and collectives under an explicit axis-bound
    transform other than shard_map (``pmap`` is not used in quantum/)."""
    path = ctx.path.replace("\\", "/")
    if "quantum/" not in path and not path.startswith("quantum"):
        return []

    defs: dict[str, ast.AST] = {
        node.name: node for node in ast.walk(ctx.tree) if isinstance(node, _FuncNode)
    }

    def fn_names_in(node: ast.AST):
        """Local function names referenced by a shard_map argument: a bare
        Name, or threaded through functools.partial(...)."""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in defs:
                yield sub.id

    seeds: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if callee.rsplit(".", 1)[-1] != "shard_map":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            seeds.update(fn_names_in(arg))

    # transitive closure over same-module calls from the seeded region bodies
    region = set()
    frontier = list(seeds)
    while frontier:
        name = frontier.pop()
        if name in region:
            continue
        region.add(name)
        for sub in ast.walk(defs[name]):
            if isinstance(sub, ast.Call):
                callee = dotted_name(sub.func) or ""
                tail = callee.rsplit(".", 1)[-1]
                if tail in defs and tail not in region:
                    frontier.append(tail)

    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = dotted_name(node.func) or ""
        if callee.rsplit(".", 1)[-1] not in project.SHARD_AXIS_CALLS:
            continue
        fn = ctx.enclosing_function(node)
        fn_name = getattr(fn, "name", None)
        if fn_name in region:
            continue
        where = f"in {fn_name!r}" if fn_name else "at module level"
        out.append(
            ctx.finding(
                "collective-outside-shardmap",
                node,
                f"named-axis collective {callee!r} {where}, outside every "
                "shard_map region in this module — the axis name is unbound "
                "there (trace error single-host, potential collective "
                "deadlock multihost); move the call into a function the "
                "shard_map region reaches (quantum/sharded.py keeps all "
                "exchanges inside the one region)",
            )
        )
    return out


# ---------------------------------------------------------------------------
# 16. pad-to-bucket-in-serve — request batches padded to static buckets
#     outside the sanctioned batcher path
# ---------------------------------------------------------------------------


def rule_pad_to_bucket_in_serve(ctx: ModuleContext) -> list[Finding]:
    """A function that picks a static bucket (``pick_bucket``) AND pads data
    into a fresh zeros/empty allocation via slice assignment (``xp[:n] = x``)
    is re-implementing the serve engine's pad-to-bucket step outside the one
    sanctioned path — exactly the shape the ragged batching mode exists to
    account for (every such pad is compute on rows nobody asked for, and a
    second pad site dodges the DispatchInfo goodput/padding-waste ledger the
    report gates watch). The engine's own ``infer`` carries the suppression
    with the reason written next to it; anything else is a finding.

    Deliberately NOT caught: picking a bucket without padding (shape-table
    readers, metrics labels), padding without a bucket pick (fixed-shape
    scratch buffers), and jnp-level ``.at[].set`` scatter (the in-program
    packing ``sparse_dispatch`` does is the fix, not the bug)."""
    out: list[Finding] = []
    for fn, qual in ctx.functions:
        picks = [
            sub
            for sub in ast.walk(fn)
            if isinstance(sub, ast.Call)
            and (ctx.canonical(sub.func) or dotted_name(sub.func) or "").rsplit(
                ".", 1
            )[-1] == "pick_bucket"
        ]
        if not picks:
            continue
        allocates = any(
            isinstance(sub, ast.Call)
            and (ctx.canonical(sub.func) or dotted_name(sub.func) or "").rsplit(
                ".", 1
            )[-1] in ("zeros", "empty", "zeros_like", "empty_like")
            for sub in ast.walk(fn)
        )
        pad_assign = any(
            isinstance(sub, ast.Assign)
            and any(
                isinstance(t, ast.Subscript) and isinstance(t.slice, ast.Slice)
                for t in sub.targets
            )
            for sub in ast.walk(fn)
        )
        if allocates and pad_assign:
            out.append(
                ctx.finding(
                    "pad-to-bucket-in-serve",
                    picks[0],
                    f"{qual!r} picks a static bucket and pads a batch into it "
                    "outside the sanctioned batcher path "
                    "(serve/engine.ServeEngine.infer) — route the batch "
                    "through the engine so the pad rows land in the "
                    "DispatchInfo goodput/padding-waste ledger (or serve the "
                    "tier ragged), instead of burning unaccounted FLOPs on "
                    "padding",
                )
            )
    return out


# ---------------------------------------------------------------------------
# 17/18. Resilience discipline — retry loops without backoff, unbounded reads
# ---------------------------------------------------------------------------


def rule_retry_without_backoff(ctx: ModuleContext) -> list[Finding]:
    """A host-side loop that (a) re-attempts a socket/stream IO call
    (``project.RETRY_IO_CALLS``) inside a ``try``, (b) catches a
    transient-IO error (``ConnectionError``/``OSError``/``TimeoutError``
    family, or a broad except) WITHOUT leaving the loop (no raise/return/
    break in the handler — falling through IS the retry), and (c) contains
    no backoff call (``project.BACKOFF_CALLS``: sleep/wait) anywhere in its
    body. Hammering a struggling peer in a tight loop is how a retrying
    client turns a blip into an outage — the repo's sanctioned shape is
    ``ServeClient.call``'s jittered exponential backoff. Deliberately NOT
    caught: loops whose handler exits (raise/return/break — give-up, not
    retry), IO loops with any sleep/wait (the fix), and generic
    ``.result()``/``.get()`` drains (far too common to flag)."""
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            continue
        # loops inside a nested function body belong to that function's own
        # analysis pass; ast.walk of the module reaches each exactly once
        has_backoff = any(
            isinstance(sub, ast.Call)
            and (
                (ctx.canonical(sub.func) or dotted_name(sub.func) or "").rsplit(
                    ".", 1
                )[-1]
                in project.BACKOFF_CALLS
            )
            for sub in ast.walk(node)
        )
        if has_backoff:
            continue
        for t in ast.walk(node):
            if not isinstance(t, ast.Try):
                continue
            io_calls = [
                sub
                for stmt in t.body
                for sub in ast.walk(stmt)
                if isinstance(sub, ast.Call)
                and (
                    (ctx.canonical(sub.func) or dotted_name(sub.func) or "")
                    .rsplit(".", 1)[-1]
                    in project.RETRY_IO_CALLS
                )
            ]
            if not io_calls:
                continue
            retrying = False
            for h in t.handlers:
                names: list[str] = []
                if h.type is None:
                    names = ["Exception"]
                else:
                    for e in ast.walk(h.type):
                        nm = dotted_name(e)
                        if nm:
                            names.append(nm.rsplit(".", 1)[-1])
                transient = any(
                    nm in project.TRANSIENT_IO_EXCEPTIONS
                    or nm in ("Exception", "BaseException")
                    for nm in names
                )
                exits = any(
                    isinstance(sub, (ast.Raise, ast.Return, ast.Break))
                    for sub in ast.walk(h)
                )
                if transient and not exits:
                    retrying = True
            if retrying:
                out.append(
                    ctx.finding(
                        "retry-without-backoff",
                        io_calls[0],
                        "loop retries an IO call after a transient "
                        "connection error with NO sleep/backoff between "
                        "attempts — a tight retry loop turns a peer's blip "
                        "into an outage; back off jittered-exponentially "
                        "between attempts (serve/client.ServeClient.call is "
                        "the sanctioned shape)",
                    )
                )
                break  # one finding per loop: the loop is the unit of fix
    return out


def rule_unbounded_readline(ctx: ModuleContext) -> list[Finding]:
    """A bare ``await reader.readline()`` (or readexactly/readuntil,
    ``project.UNBOUNDED_READ_CALLS``) in a serve-path module: with no
    timeout, one dead or slow-loris peer pins a connection slot (and its
    handler task) forever — the exact shape ``serve.conn_timeout_s`` exists
    to bound. The sanctioned form awaits ``asyncio.wait_for(...)`` around
    the read (``serve/server._read_line``), which this rule recognizes
    because the ``await``'s direct operand is then ``wait_for``, not the
    read. Scoped to ``serve/`` paths — async reads elsewhere (test drivers,
    offline tooling) bound their own lifetimes."""
    path = ctx.path.replace("\\", "/")
    if "serve/" not in path:
        return []
    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Await) or not isinstance(node.value, ast.Call):
            continue
        callee = (
            ctx.canonical(node.value.func) or dotted_name(node.value.func) or ""
        ).rsplit(".", 1)[-1]
        if callee in project.UNBOUNDED_READ_CALLS:
            out.append(
                ctx.finding(
                    "unbounded-readline",
                    node,
                    f"bare `await ...{callee}()` in a serve path — with no "
                    "timeout one dead peer pins this connection slot "
                    "forever; wrap in asyncio.wait_for with "
                    "serve.conn_timeout_s (serve/server._read_line is the "
                    "sanctioned helper)",
                )
            )
    return out


# ---------------------------------------------------------------------------
# 19. trace-in-jit-path — request-tracing stamps reachable from compiled code
# ---------------------------------------------------------------------------


def rule_trace_in_jit_path(ctx: ModuleContext) -> list[Finding]:
    """A request-tracing call (``project.TRACE_STAMP_CALLS``: TraceContext
    construction, ``trace_sampled``, ``add_phase`` stamping) inside a
    jit-reachable function OR a pallas kernel body. Tracing is host-side
    ONLY by contract (docs/TELEMETRY.md): inside a traced program the stamp
    would evaluate once at trace time and compile to a constant — the
    ``wall-clock-in-jit`` hazard — and any real data flow from it would
    change the program, breaking the ``serve.trace_sample=0`` HLO-identity
    pin. Pallas reachability is computed here (``pallas_call`` is not a
    generic tracing entry point): functions passed by name into a
    ``pallas_call`` — directly or through ``functools.partial`` — seed a
    same-module call closure, mirroring ``collective-outside-shardmap``.
    Deliberately NOT caught: stamping in host-side serve/router/loadgen code
    (the entire sanctioned surface), and cross-module call chains (the
    tracing API is never passed across modules into jitted code here — a
    helper that wants to trace belongs on the host side of the dispatch)."""
    defs: dict[str, ast.AST] = {
        node.name: node for node in ast.walk(ctx.tree) if isinstance(node, _FuncNode)
    }
    pallas_seeds: set[str] = set()
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        callee = (ctx.canonical(node.func) or dotted_name(node.func) or "")
        if callee.rsplit(".", 1)[-1] != "pallas_call":
            continue
        for arg in list(node.args) + [kw.value for kw in node.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Name) and sub.id in defs:
                    pallas_seeds.add(sub.id)
    # same-module closure from the kernel bodies (a kernel helper that
    # stamps is just as compiled as the kernel itself)
    region: set[str] = set()
    frontier = list(pallas_seeds)
    while frontier:
        name = frontier.pop()
        if name in region:
            continue
        region.add(name)
        for sub in ast.walk(defs[name]):
            if isinstance(sub, ast.Call):
                callee = dotted_name(sub.func) or ""
                tail = callee.rsplit(".", 1)[-1]
                if tail in defs and tail not in region:
                    frontier.append(tail)
    compiled: list[tuple[ast.AST, str]] = [
        (fn, "jit-reachable") for fn in ctx.traced
    ] + [
        (defs[name], "pallas-kernel") for name in sorted(region)
        if defs[name] not in ctx.traced
    ]
    out: list[Finding] = []
    for fn, kind in compiled:
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            callee = ctx.canonical(sub.func) or dotted_name(sub.func) or ""
            if callee.rsplit(".", 1)[-1] not in project.TRACE_STAMP_CALLS:
                continue
            out.append(
                ctx.finding(
                    "trace-in-jit-path",
                    sub,
                    f"request-tracing call {callee!r} in {kind} "
                    f"{ctx.qualname(fn) or fn.name!r}: tracing is host-side "
                    "only — inside compiled code the stamp freezes at trace "
                    "time (wall-clock-in-jit's shape) and breaks the "
                    "trace_sample=0 HLO-identity pin; stamp around the "
                    "dispatch, never inside it (serve/server._serve_one is "
                    "the sanctioned site)",
                )
            )
    return out


# ---------------------------------------------------------------------------
# 18. unwindowed-cumulative-rate — lifetime counter / wall-time division
# ---------------------------------------------------------------------------


def rule_unwindowed_cumulative_rate(ctx: ModuleContext) -> list[Finding]:
    """A cumulative run-lifetime counter (``project.CUMULATIVE_COUNTERS``)
    divided by a wall-clock span: the "rate" averages the counter's WHOLE
    lifetime, so a restart makes it garbage and a long run makes it inert
    (a regression in the last minute moves a week-long average by nothing).
    Windowed rates difference snapshots first
    (``telemetry/timeseries.counter_delta`` — that module is the sanctioned
    home, ``project.RATE_SANCTIONED_MODULES``). Wall-time denominators are
    direct span-clock reads (``project.WALL_TIME_CALLS``), arithmetic over
    them, or a local name assigned from such an expression (two dataflow
    passes: ``now = time.monotonic()`` then ``elapsed = now - t0``).
    Run-level SUMMARY rates over an explicit full-run span are legitimate
    and sanctioned by suppression at the site. Deliberately NOT caught:
    deltas (``d_completed / dt`` — already windowed), divisions by counts
    or config values, and cross-function flows (a span passed as an
    argument) — the shipped shape is the in-function ``counter /
    (monotonic() - t0)`` one-liner."""
    if ctx.path in project.RATE_SANCTIONED_MODULES:
        return []

    def _clock_call(sub: ast.AST) -> bool:
        if not isinstance(sub, ast.Call):
            return False
        callee = ctx.canonical(sub.func) or dotted_name(sub.func) or ""
        return callee.rsplit(".", 1)[-1] in project.WALL_TIME_CALLS

    # names bound to wall-time spans, two passes for the one-step chain
    span_names: set[str] = set()
    for _pass in (0, 1):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign):
                continue
            clockish = any(
                _clock_call(sub) or (
                    isinstance(sub, ast.Name) and sub.id in span_names
                )
                for sub in ast.walk(node.value)
            )
            if not clockish:
                continue
            # plain-name targets only: `self._t0 = monotonic()` must bind
            # nothing (walking the Attribute target would bind `self` and
            # poison the whole module's dataflow)
            for t in node.targets:
                elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                for n in elts:
                    if isinstance(n, ast.Name):
                        span_names.add(n.id)

    def _wall_time(expr: ast.AST) -> bool:
        return any(
            _clock_call(sub)
            or (isinstance(sub, ast.Name) and sub.id in span_names)
            for sub in ast.walk(expr)
        )

    def _counter(expr: ast.AST) -> str | None:
        for sub in ast.walk(expr):
            name = None
            if isinstance(sub, ast.Attribute):
                name = sub.attr
            elif isinstance(sub, ast.Name):
                name = sub.id
            if name and name.lstrip("_") in project.CUMULATIVE_COUNTERS:
                return name
        return None

    out: list[Finding] = []
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div)):
            continue
        counter = _counter(node.left)
        if counter is None or not _wall_time(node.right):
            continue
        out.append(
            ctx.finding(
                "unwindowed-cumulative-rate",
                node,
                f"cumulative counter {counter!r} divided by a wall-clock "
                "span: a lifetime average is garbage after a restart and "
                "inert on a long run — difference snapshots first "
                "(telemetry/timeseries.counter_delta) and divide the DELTA "
                "by the window width; a run-level summary rate over the "
                "full run span is sanctioned by suppression",
            )
        )
    return out


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

RULES: dict[str, tuple[Callable[[ModuleContext], list[Finding]], str]] = {
    "jit-mutable-global": (
        rule_jit_mutable_global,
        "jitted code closing over module-level mutable state",
    ),
    "train-step-jit-audit": (
        rule_train_step_jit_audit,
        "train-step makers must declare donate_argnums/static_* intent",
    ),
    "tracer-branch": (
        rule_tracer_branch,
        "Python if/while on traced values inside jit-reachable code",
    ),
    "host-sync-hot-path": (
        rule_host_sync_hot_path,
        "device->host syncs inside train-step / serve-request paths",
    ),
    "wall-clock-in-jit": (
        rule_wall_clock_in_jit,
        "time.time()/datetime.now() frozen into traced programs",
    ),
    "primary-only-collective": (
        rule_primary_only_collective,
        "collectives guarded by is_primary (multihost deadlock)",
    ),
    "serve-lock-discipline": (
        rule_serve_lock_discipline,
        "thread-shared serve state touched outside its lock",
    ),
    "stranded-future": (
        rule_stranded_future,
        "queue pop without guaranteed future resolution on all exit paths",
    ),
    "broad-except": (
        rule_broad_except,
        "bare/broad except swallowing DivergenceError/KeyboardInterrupt",
    ),
    "import-time-jnp": (
        rule_import_time_jnp,
        "jnp ops at module import time",
    ),
    "pallas-host-loop": (
        rule_pallas_host_loop,
        "pallas_call launched from a host-side Python loop over gates/layers",
    ),
    "pallas-interpret-literal": (
        rule_pallas_interpret_literal,
        "pallas_call(interpret=True) hardcoded outside test/fixture paths",
    ),
    "gate-matrix-in-loop": (
        rule_gate_matrix_in_loop,
        "per-gate jnp matrix construction inside a circuit layer loop",
    ),
    "data-dependent-shape-in-jit": (
        rule_data_dependent_shape_in_jit,
        "jnp.nonzero/unique/bool-mask indexing in jitted hot paths (value-dependent shapes)",
    ),
    "collective-outside-shardmap": (
        rule_collective_outside_shardmap,
        "ppermute/psum in quantum/ outside a shard_map region (deadlock shape)",
    ),
    "pad-to-bucket-in-serve": (
        rule_pad_to_bucket_in_serve,
        "request batch padded to a static bucket outside the sanctioned batcher path",
    ),
    "retry-without-backoff": (
        rule_retry_without_backoff,
        "IO retry loop with no sleep/backoff between attempts",
    ),
    "unbounded-readline": (
        rule_unbounded_readline,
        "await reader.readline() with no timeout in serve paths",
    ),
    "trace-in-jit-path": (
        rule_trace_in_jit_path,
        "TraceContext construction / phase stamping reachable from jit or pallas code",
    ),
    "unwindowed-cumulative-rate": (
        rule_unwindowed_cumulative_rate,
        "cumulative counter divided by wall time outside the sanctioned differencing helpers",
    ),
    # "slow-marker" is data-driven (needs a --durations report) and lives in
    # qdml_tpu.analysis.slowmarkers; the CLI folds it in when given the data.
}


def all_rules() -> list[Callable[[ModuleContext], list[Finding]]]:
    return [fn for fn, _doc in RULES.values()]
