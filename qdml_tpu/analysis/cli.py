"""``qdml-tpu lint`` — the graftlint gate entry point.

Host-side tool over source files: no jax import, no config parsing, no
workdir (dispatched before the CLI's config layer, exactly like ``report``).

    qdml-tpu lint [--paths=P1,P2,...] [--baseline[=FILE]] [--write-baseline]
                  [--json=FILE] [--durations=FILE] [--threshold=SECS]
                  [--allow=FILE] [--list-rules] [--changed-only]
                  [--lockgraph[=DIR]] [--lockgraph-check[=DIR]]

Exit codes: 0 clean (every finding fixed, suppressed with a reason, or
baselined), 1 new findings, 2 usage/parse errors.

- ``--baseline`` (flag or ``=path``) subtracts the committed baseline
  (default ``scripts/lint_baseline.json``); new findings still fail.
- ``--write-baseline`` regenerates that file from the current findings
  (inline-suppressed ones stay inline; existing baseline reasons are kept).
- ``--durations=FILE`` folds in the slow-marker rule over a
  ``pytest --durations=0`` report (``-`` reads stdin).
- ``--json=FILE`` writes the machine-readable gate record that
  ``qdml-tpu report --lint=FILE`` consumes.
- ``--changed-only`` restricts the REPORT to git-touched files (staged +
  unstaged + untracked) for fast pre-commit runs; the scan still covers the
  full path set so the whole-program concurrency pass sees every caller.
- ``--lockgraph[=DIR]`` writes the static lock-order graph artifact
  (default ``results/lockgraph/``: JSON + DOT + markdown hierarchy);
  ``--lockgraph-check`` instead verifies the committed artifact matches a
  regenerated one (the tier-1 freshness gate) and exits 1 on staleness.
"""

from __future__ import annotations

import json
import os
import sys

from qdml_tpu.analysis.engine import (
    BASELINE_DEFAULT,
    LintEngine,
    LintResult,
    load_baseline,
    save_baseline,
)
from qdml_tpu.analysis.project import DEFAULT_PATHS

EXIT_OK = 0
EXIT_FINDINGS = 1
EXIT_USAGE = 2


def repo_root() -> str:
    """The repo the package lives in (qdml_tpu/analysis/cli.py -> repo)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def changed_files(root: str) -> list[str]:
    """Repo-relative .py files git considers touched: staged, unstaged, and
    untracked (`git status --porcelain` — renames report their new name)."""
    import subprocess

    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True,
            text=True,
            timeout=30,
            check=True,
        ).stdout
    except (OSError, subprocess.SubprocessError):
        return []
    files: list[str] = []
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:]
        if " -> " in path:  # rename: "R  old -> new"
            path = path.split(" -> ", 1)[1]
        path = path.strip().strip('"')
        if path.endswith(".py"):
            files.append(path)
    return sorted(set(files))


def _format_text(result: LintResult, baseline_path: str | None) -> str:
    lines: list[str] = []
    for f in result.new:
        lines.append(f"{f.location()}: [{f.rule}] {f.message}")
        if f.text:
            lines.append(f"    > {f.text}")
    for err in result.errors:
        lines.append(f"PARSE ERROR: {err}")
    n_sup, n_base = len(result.suppressed), len(result.baselined)
    if result.ok:
        lines.append(
            f"qdml-tpu lint: OK — 0 new findings "
            f"({n_sup} suppressed inline with reasons, {n_base} baselined)"
        )
    else:
        lines.append(
            f"qdml-tpu lint: {len(result.new)} new finding(s) "
            f"({n_sup} suppressed, {n_base} baselined)"
            + (f", {len(result.errors)} parse error(s)" if result.errors else "")
        )
        lines.append(
            "fix each finding, or suppress on the line with "
            "`# lint: disable=<rule>(reason)`"
            + (
                f", or regenerate {baseline_path} with --write-baseline"
                if baseline_path
                else ""
            )
        )
    return "\n".join(lines)


def lint_main(argv: list[str]) -> int:
    paths: list[str] = []
    baseline_path: str | None = None
    write_baseline = False
    json_out: str | None = None
    durations: str | None = None
    threshold = 5.0
    allow: str | None = None
    changed_only = False
    lockgraph_dir: str | None = None
    lockgraph_check: str | None = None
    root = repo_root()
    for arg in argv:
        if arg.startswith("--paths="):
            paths += [p for p in arg.split("=", 1)[1].split(",") if p]
        elif arg == "--baseline":
            baseline_path = os.path.join(root, BASELINE_DEFAULT)
        elif arg.startswith("--baseline="):
            baseline_path = arg.split("=", 1)[1]
        elif arg == "--write-baseline":
            write_baseline = True
        elif arg.startswith("--json="):
            json_out = arg.split("=", 1)[1]
        elif arg.startswith("--durations="):
            durations = arg.split("=", 1)[1]
        elif arg.startswith("--threshold="):
            try:
                threshold = float(arg.split("=", 1)[1])
            except ValueError:
                print(f"lint: --threshold must be a number, got {arg!r}")
                return EXIT_USAGE
        elif arg.startswith("--allow="):
            allow = arg.split("=", 1)[1]
        elif arg == "--changed-only":
            changed_only = True
        elif arg == "--lockgraph":
            lockgraph_dir = os.path.join(root, "results", "lockgraph")
        elif arg.startswith("--lockgraph="):
            lockgraph_dir = arg.split("=", 1)[1]
        elif arg == "--lockgraph-check":
            lockgraph_check = os.path.join(root, "results", "lockgraph")
        elif arg.startswith("--lockgraph-check="):
            lockgraph_check = arg.split("=", 1)[1]
        elif arg == "--list-rules":
            from qdml_tpu.analysis.concurrency import CONCURRENCY_RULES
            from qdml_tpu.analysis.rules import RULES
            from qdml_tpu.analysis.slowmarkers import RULE_ID

            for rule_id, (_fn, doc) in sorted(RULES.items()):
                print(f"{rule_id:26s} {doc}")
            for rule_id, doc in sorted(CONCURRENCY_RULES.items()):
                print(f"{rule_id:26s} {doc}")
            print(f"{RULE_ID:26s} >5s tests must be @pytest.mark.slow (needs --durations)")
            return EXIT_OK
        else:
            print(f"lint: unrecognised argument {arg!r}")
            print(__doc__)
            return EXIT_USAGE
    paths = paths or list(DEFAULT_PATHS)

    extra = []
    if durations is not None:
        from qdml_tpu.analysis.slowmarkers import check_durations

        try:
            text = sys.stdin.read() if durations == "-" else open(durations).read()
        except OSError as e:
            print(f"lint: cannot read durations report: {e}")
            return EXIT_USAGE
        extra = check_durations(root, text, threshold_s=threshold, allowlist_path=allow)

    engine = LintEngine(root)
    previous = load_baseline(baseline_path) if baseline_path else {}
    if write_baseline:
        target = baseline_path or os.path.join(root, BASELINE_DEFAULT)
        # Baseline the AST findings only (new + already-baselined: a
        # regenerate keeps matching entries and their reasons). Slow-marker
        # findings are data-driven and grandfather through
        # tier1_slow_allowlist.txt, never the AST baseline; bare-suppression
        # findings are policy violations that must be fixed, not frozen.
        raw = engine.run(paths, baseline=None)
        if raw.errors:
            for e in raw.errors:
                print(f"lint: {e}")
            print("lint: refusing to write a baseline from an incomplete scan")
            return EXIT_FINDINGS
        baselineable = [f for f in raw.new if f.rule != "bare-suppression"]
        skipped = len(raw.new) - len(baselineable)
        n = save_baseline(target, baselineable, previous=load_baseline(target))
        print(f"lint: wrote {n} baseline entr{'y' if n == 1 else 'ies'} to {target}")
        if skipped:
            print(
                f"lint: {skipped} bare-suppression finding(s) NOT baselined — "
                "add the missing (reason)s instead"
            )
        return EXIT_OK
    restrict: list[str] | None = None
    if changed_only:
        restrict = changed_files(root)
        if not restrict and not (lockgraph_dir or lockgraph_check):
            print("qdml-tpu lint: OK — --changed-only and no touched .py files")
            return EXIT_OK
    result = engine.run(
        paths, baseline=previous, extra_findings=extra, restrict_to=restrict
    )
    print(_format_text(result, baseline_path))
    rc = EXIT_OK if result.ok else EXIT_FINDINGS
    if (lockgraph_dir or lockgraph_check) and engine.model is not None:
        from qdml_tpu.analysis import concurrency

        if lockgraph_dir:
            graph = concurrency.write_lockgraph(engine.model, lockgraph_dir)
            print(
                f"lint: wrote lock graph to {lockgraph_dir} "
                f"({len(graph['nodes'])} locks, {len(graph['edges'])} edges, "
                f"{len(graph['cycles'])} cycles)"
            )
        if lockgraph_check:
            problems = concurrency.check_lockgraph(engine.model, lockgraph_check)
            for p in problems:
                print(f"lint: {p}")
            if problems:
                rc = EXIT_FINDINGS
            else:
                print(f"lint: lock graph {lockgraph_check} is fresh")
    if json_out:
        payload = result.to_json()
        payload["exit_code"] = rc
        payload["baseline"] = baseline_path
        payload["paths"] = paths
        os.makedirs(os.path.dirname(json_out) or ".", exist_ok=True)
        with open(json_out, "w") as fh:
            json.dump(payload, fh, indent=2)
    return rc


if __name__ == "__main__":
    raise SystemExit(lint_main(sys.argv[1:]))
