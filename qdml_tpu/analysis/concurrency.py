"""Whole-program concurrency analyzer: static lock-order graph + the four
concurrency rules (docs/ANALYSIS.md "concurrency").

The per-module rules in :mod:`qdml_tpu.analysis.rules` check that LOCK_MAP'd
attributes are touched under *their* lock inside *their* class; nothing there
sees ACROSS locks or modules. This pass builds one model of the whole scanned
tree — every lock construction site, every held-lock region, an
interprocedural call closure (same-class ``self.m()``, attribute-typed
``self._x.m()``, same-module and imported-module calls) — and derives:

- **lock-order-inversion** — the static acquisition-order graph (edge A→B =
  lock B acquired somewhere while A is held, directly or through the call
  closure) contains a cycle. Two threads walking the cycle from different
  ends deadlock; the runtime twin (:mod:`qdml_tpu.utils.lockdep`) witnesses
  the same edge set under real execution.
- **blocking-under-lock** — a call that can block for unbounded time
  (``time.sleep``, socket/subprocess IO, ``Event.wait``, ``.result()``
  drains, ``block_until_ready``/``device_get`` device fences —
  ``project.BLOCKING_CALLS``) reachable inside a held-lock region. Every
  peer of that lock serializes behind the slow call; sanctioned sites (the
  hot-swap's off-request-path fence) carry reasoned suppressions.
- **sync-io-in-async** — a synchronous blocking call reachable from an
  ``async def`` handler in the serving event-loop files
  (``project.ASYNC_SCOPED_FILES``) without an executor hop: a stalled loop
  stops EVERY connection, not one request. Callables passed into
  ``run_in_executor``/``to_thread`` are the sanctioned escape and are not
  descended into; ``asyncio.*`` calls are awaited loop citizens and exempt.
- **unmapped-shared-state** — an instance attribute written outside
  ``__init__`` from ≥2 distinct thread entry points (``Thread(target=...)``
  roots, done-callbacks, async handlers, plus the caller's own thread) in
  the concurrent packages, with NO LOCK_MAP row: the candidate set LOCK_MAP
  should grow from, so the map stops being a hand-maintained allowlist.
- **dead-lock-map-entry** — LOCK_MAP staleness: a mapped file/class/attr/
  lock that no longer exists in the tree silently disarms
  ``serve-lock-discipline``; a rename must update the map.

Findings flow through the SAME suppression/baseline machinery as the
per-module rules (the engine merges them before suppression processing), so
``# lint: disable=blocking-under-lock(reason)`` works and a stale comment is
flagged ``dead-suppression`` like any other.

The graph renders to ``results/lockgraph/`` (DOT + JSON + a markdown
hierarchy table) via :func:`write_lockgraph`; ``scripts/run_tier1.sh``
re-generates and byte-compares it so the documented hierarchy is generated,
never asserted.

Deliberately NOT caught (precision over recall, like every graftlint rule):
conditional acquisition paths are merged (may-hold, not must-hold — a
spurious edge is a review prompt, a missed one is a deadlock); ``.acquire()``
held-ranges are tracked to the end of the enclosing block, not across
early releases in sibling branches; duck-typed calls through untyped
attributes do not resolve (annotate the ``__init__`` parameter to opt in).
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Iterable

from qdml_tpu.analysis import project
from qdml_tpu.analysis.engine import (
    Finding,
    ModuleContext,
    dotted_name,
    iter_python_files,
)

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)

# rule id -> one-line doc (folded into `qdml-tpu lint --list-rules`)
CONCURRENCY_RULES: dict[str, str] = {
    "lock-order-inversion": (
        "cycle in the static lock acquisition-order graph (deadlock shape)"
    ),
    "blocking-under-lock": (
        "sleep/socket/subprocess/fence/.result() reachable inside a held lock"
    ),
    "sync-io-in-async": (
        "sync blocking call reachable from an async handler without an executor hop"
    ),
    "unmapped-shared-state": (
        "attribute written from >=2 thread entry points with no LOCK_MAP row"
    ),
    "dead-lock-map-entry": (
        "LOCK_MAP names a file/class/attr/lock that no longer exists"
    ),
}

_LOCK_CTORS = {"Lock", "RLock"}
# thread-safe primitives whose internal state needs no LOCK_MAP row
_THREADSAFE_CTORS = {
    "Lock",
    "RLock",
    "Event",
    "Condition",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Queue",
    "SimpleQueue",
    "local",
}


def _last(name: str | None) -> str:
    return (name or "").rsplit(".", 1)[-1]


# canonically-qualified blockers whose bare tail is too generic to list in
# project.BLOCKING_CALLS (every `x.run()` is not a subprocess)
_BLOCKING_CANONICAL = frozenset({"subprocess.run"})


def _is_blocking(
    ctx: ModuleContext,
    call: ast.Call,
    tail: str,
    table: frozenset[str] = None,  # type: ignore[assignment]
) -> bool:
    """True when ``call`` can block the calling thread for unbounded time.

    ``join`` is exempted for the two string shapes (``os.path.join``,
    ``"sep".join``) — a thread/process join it is not; ``asyncio.*`` calls
    are loop citizens, not thread blockers."""
    canon = ctx.canonical(call.func) or dotted_name(call.func) or ""
    if canon in _BLOCKING_CANONICAL:
        return True
    if tail not in (project.BLOCKING_CALLS if table is None else table):
        return False
    if canon.startswith("asyncio."):
        return False
    if tail == "join":
        if canon.endswith("path.join"):
            return False
        if isinstance(call.func, ast.Attribute) and isinstance(
            call.func.value, ast.Constant
        ):
            return False
    return True


@dataclass
class LockDecl:
    """One lock identity: ``Class._attr`` (instance) or ``module:NAME``."""

    lock_id: str
    kind: str            # "lock" | "rlock"
    path: str
    line: int
    cls: str | None      # declaring class, None for module-level
    mapped: bool = False  # appears as a required lock in LOCK_MAP


@dataclass
class _FnInfo:
    """Per-function facts the interprocedural fixpoints consume."""

    key: tuple[str, str]                 # (path, qualname)
    node: ast.AST
    ctx: ModuleContext
    cls: str | None
    # locks this function acquires in its own body: lock_id -> first line
    acquires: dict[str, int] = field(default_factory=dict)
    # blocking calls in its own body: name -> first (line, text)
    blocks: dict[str, int] = field(default_factory=dict)
    # resolved outgoing calls: (callee_key, call line)
    calls: list[tuple[tuple[str, str], int]] = field(default_factory=list)
    # (held lock_id, acquired lock_id, line) direct nesting edges
    edges: list[tuple[str, str, int]] = field(default_factory=list)
    # calls made while >=1 lock is held: (held ids, call node, callee key|None)
    held_calls: list[tuple[tuple[str, ...], ast.Call, tuple[str, str] | None]] = field(
        default_factory=list
    )
    # direct blocking calls under a held lock: (held ids, node, op name)
    held_blocks: list[tuple[tuple[str, ...], ast.Call, str]] = field(
        default_factory=list
    )


class ConcurrencyModel:
    """The whole-program model: locks, held regions, call closure, graph."""

    def __init__(
        self,
        ctxs: list[ModuleContext],
        lock_map: dict[str, dict[str, dict[str, str]]] | None = None,
    ):
        self.ctxs = ctxs
        self.lock_map = project.LOCK_MAP if lock_map is None else lock_map
        self.by_path: dict[str, ModuleContext] = {c.path: c for c in ctxs}

        # class registry: name -> (ctx, ClassDef). Class names are unique
        # across this repo; a duplicate keeps the first and the second
        # simply fails attribute-type resolution (conservative: no edges).
        self.classes: dict[str, tuple[ModuleContext, ast.ClassDef]] = {}
        for ctx in ctxs:
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.ClassDef):
                    self.classes.setdefault(node.name, (ctx, node))

        self.locks: dict[str, LockDecl] = {}
        self.class_locks: dict[str, dict[str, LockDecl]] = {}   # cls -> attr -> decl
        self.module_locks: dict[str, dict[str, LockDecl]] = {}  # path -> name -> decl
        self._collect_locks()

        # cls -> attr -> class name (for self._x.m() resolution)
        self.attr_types: dict[str, dict[str, str]] = {}
        self._collect_attr_types()

        # function table + per-function facts
        self.fns: dict[tuple[str, str], _FnInfo] = {}
        self._collect_functions()
        for info in self.fns.values():
            self._scan_function(info)

        # interprocedural fixpoints: lock_id -> via chain / op -> via chain
        self.may_acquire: dict[tuple[str, str], dict[str, str]] = {}
        self.may_block: dict[tuple[str, str], dict[str, str]] = {}
        self._fixpoints()

        # the acquisition-order graph: (src, dst) -> list of site dicts
        self.edges: dict[tuple[str, str], list[dict]] = {}
        self._build_edges()

    # -- lock inventory ------------------------------------------------------

    def _lock_ctor_kind(self, ctx: ModuleContext, value: ast.AST) -> str | None:
        """'lock'/'rlock' when ``value`` constructs one (threading.Lock(),
        lockdep.Lock("name"), threading.RLock(), ...), else None."""
        if not isinstance(value, ast.Call):
            return None
        tail = _last(ctx.canonical(value.func) or dotted_name(value.func))
        if tail not in _LOCK_CTORS:
            return None
        return "rlock" if tail == "RLock" else "lock"

    def _collect_locks(self) -> None:
        mapped: set[tuple[str, str]] = set()  # (class, lock_attr)
        for _path, cls_map in self.lock_map.items():
            for cls, attrs in cls_map.items():
                for lock_attr in attrs.values():
                    mapped.add((cls, lock_attr))
        for ctx in self.ctxs:
            if ctx.path == "qdml_tpu/utils/lockdep.py":
                continue  # the witness's own guard is a leaf by construction
            mod = os.path.basename(ctx.path).removesuffix(".py")
            # module-level locks
            for node in ctx.tree.body:
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    kind = self._lock_ctor_kind(ctx, node.value)
                    if kind and isinstance(t, ast.Name):
                        decl = LockDecl(
                            f"{mod}:{t.id}", kind, ctx.path, node.lineno, None
                        )
                        self.locks[decl.lock_id] = decl
                        self.module_locks.setdefault(ctx.path, {})[t.id] = decl
            # instance locks (any self.X = <lock ctor> inside the class)
            for cnode in ast.walk(ctx.tree):
                if not isinstance(cnode, ast.ClassDef):
                    continue
                for sub in ast.walk(cnode):
                    if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
                        continue
                    t = sub.targets[0]
                    if not (
                        isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"
                    ):
                        continue
                    kind = self._lock_ctor_kind(ctx, sub.value)
                    if kind is None:
                        continue
                    decl = LockDecl(
                        f"{cnode.name}.{t.attr}",
                        kind,
                        ctx.path,
                        sub.lineno,
                        cnode.name,
                        mapped=(cnode.name, t.attr) in mapped,
                    )
                    self.locks[decl.lock_id] = decl
                    self.class_locks.setdefault(cnode.name, {})[t.attr] = decl

    # -- attribute types -----------------------------------------------------

    @staticmethod
    def _ann_name(ann: ast.AST | None) -> str | None:
        """The class name inside an annotation: C, 'C', C | None, Optional[C]."""
        if ann is None:
            return None
        if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
            return ann.value.split("|")[0].strip().rsplit(".", 1)[-1] or None
        if isinstance(ann, ast.Name):
            return ann.id
        if isinstance(ann, ast.Attribute):
            return ann.attr
        if isinstance(ann, ast.BinOp):  # C | None
            return ConcurrencyModel._ann_name(ann.left)
        if isinstance(ann, ast.Subscript):  # Optional[C]
            return ConcurrencyModel._ann_name(ann.slice)
        return None

    def _collect_attr_types(self) -> None:
        for ctx in self.ctxs:
            for cnode in ast.walk(ctx.tree):
                if not isinstance(cnode, ast.ClassDef):
                    continue
                types = self.attr_types.setdefault(cnode.name, {})
                for fn in cnode.body:
                    if not (isinstance(fn, _FuncNode) and fn.name == "__init__"):
                        continue
                    param_types = {
                        a.arg: self._ann_name(a.annotation)
                        for a in fn.args.args + fn.args.kwonlyargs
                    }
                    for sub in ast.walk(fn):
                        if not (
                            isinstance(sub, ast.Assign) and len(sub.targets) == 1
                        ):
                            continue
                        t = sub.targets[0]
                        if not (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            continue
                        v = sub.value
                        name: str | None = None
                        if isinstance(v, ast.Call):
                            name = _last(dotted_name(v.func))
                        elif isinstance(v, ast.Name):
                            name = param_types.get(v.id)
                        if name in self.classes:
                            types[t.attr] = name  # type: ignore[assignment]

    # -- function table ------------------------------------------------------

    def _collect_functions(self) -> None:
        for ctx in self.ctxs:
            for node, qual in ctx.functions:
                cls = qual.rsplit(".", 1)[0] if "." in qual else None
                if cls is not None and cls not in self.classes:
                    cls = None  # nested function, not a method
                self.fns[(ctx.path, qual)] = _FnInfo(
                    key=(ctx.path, qual), node=node, ctx=ctx, cls=cls
                )

    def _module_dotted(self, path: str) -> str:
        return path.removesuffix(".py").removesuffix("/__init__").replace("/", ".")

    def _resolve_call(
        self, info: _FnInfo, call: ast.Call
    ) -> tuple[str, str] | None:
        """(path, qualname) of the callee when it resolves to a scanned
        function; None for stdlib/duck-typed/unresolvable calls."""
        func = call.func
        ctx = info.ctx
        # self.m() -> method of the enclosing class
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id == "self"
            and info.cls
        ):
            key = (ctx.path, f"{info.cls}.{func.attr}")
            return key if key in self.fns else None
        # self._x.m() -> method of the attribute's resolved class
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Attribute)
            and isinstance(func.value.value, ast.Name)
            and func.value.value.id == "self"
            and info.cls
        ):
            owner = self.attr_types.get(info.cls, {}).get(func.value.attr)
            if owner:
                octx, _ = self.classes[owner]
                key = (octx.path, f"{owner}.{func.attr}")
                return key if key in self.fns else None
            return None
        # f() / imported f() / mod.f()
        canon = ctx.canonical(func)
        if canon is None:
            return None
        if "." not in canon:
            key = (ctx.path, canon)
            return key if key in self.fns else None
        mod_dotted, _, fn_name = canon.rpartition(".")
        for cpath in self.by_path:
            if self._module_dotted(cpath) == mod_dotted:
                key = (cpath, fn_name)
                return key if key in self.fns else None
        return None

    # -- per-function scan ---------------------------------------------------

    def _lock_id_of(self, info: _FnInfo, expr: ast.AST) -> str | None:
        """The lock identity a with-item / .acquire() target names."""
        # with self._lock:
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and info.cls
        ):
            decl = self.class_locks.get(info.cls, {}).get(expr.attr)
            return decl.lock_id if decl else None
        # with MODULE_LOCK:
        if isinstance(expr, ast.Name):
            decl = self.module_locks.get(info.ctx.path, {}).get(expr.id)
            return decl.lock_id if decl else None
        return None

    def _scan_function(self, info: _FnInfo) -> None:
        def note_acquire(lid: str, line: int, held: tuple[str, ...]) -> None:
            info.acquires.setdefault(lid, line)
            for h in held:
                if h != lid:
                    info.edges.append((h, lid, line))
                elif self.locks[lid].kind != "rlock":
                    # re-acquiring a non-reentrant lock on the same thread is
                    # an immediate self-deadlock: a self-edge -> cycle
                    info.edges.append((h, lid, line))

        def visit_call(call: ast.Call, held: tuple[str, ...]) -> None:
            tail = _last(dotted_name(call.func))
            # lock method calls: acquire/release on a known lock
            if isinstance(call.func, ast.Attribute) and tail in (
                "acquire",
                "release",
            ):
                lid = self._lock_id_of(info, call.func.value)
                if lid and tail == "acquire":
                    note_acquire(lid, call.lineno, held)
                if lid:
                    return  # never treat lock methods as blocking/callees
            if _is_blocking(info.ctx, call, tail):
                info.blocks.setdefault(tail, call.lineno)
                if held:
                    info.held_blocks.append((held, call, tail))
            callee = self._resolve_call(info, call)
            if callee is not None and callee != info.key:
                info.calls.append((callee, call.lineno))
                if held:
                    info.held_calls.append((held, call, callee))
            elif held and isinstance(call.func, (ast.Name, ast.Attribute)):
                info.held_calls.append((held, call, None))

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in node.items:
                    # the context expr itself evaluates under the locks
                    # already held, not the one it acquires
                    visit(item.context_expr, new_held)
                    lid = self._lock_id_of(info, item.context_expr)
                    if lid is not None:
                        note_acquire(lid, node.lineno, new_held)
                        if lid not in new_held:
                            new_held = new_held + (lid,)
                for child in node.body:
                    visit(child, new_held)
                return
            if isinstance(node, _FuncNode) and node is not info.node:
                return  # nested defs are their own _FnInfo
            if isinstance(node, ast.Call):
                visit_call(node, held)
            # .acquire() extends the held set for the REST of the enclosing
            # statement list (block-scoped approximation; `with` is the
            # sanctioned shape everywhere in this repo)
            body_fields = ("body", "orelse", "finalbody")
            for name, value in ast.iter_fields(node):
                if name in body_fields and isinstance(value, list):
                    blk_held = held
                    for child in value:
                        visit(child, blk_held)
                        blk_held = _extend_with_acquires(child, blk_held)
                elif isinstance(value, list):
                    for child in value:
                        if isinstance(child, ast.AST):
                            visit(child, held)
                elif isinstance(value, ast.AST):
                    visit(value, held)

        def _extend_with_acquires(
            stmt: ast.AST, held: tuple[str, ...]
        ) -> tuple[str, ...]:
            if not isinstance(stmt, ast.Expr) or not isinstance(
                stmt.value, ast.Call
            ):
                return held
            call = stmt.value
            tail = _last(dotted_name(call.func))
            if tail not in ("acquire", "release") or not isinstance(
                call.func, ast.Attribute
            ):
                return held
            lid = self._lock_id_of(info, call.func.value)
            if lid is None:
                return held
            if tail == "acquire" and lid not in held:
                return held + (lid,)
            if tail == "release":
                return tuple(h for h in held if h != lid)
            return held

        for child in ast.iter_child_nodes(info.node):
            if child in getattr(info.node, "decorator_list", []):
                continue
            visit(child, ())

    # -- interprocedural fixpoints -------------------------------------------

    def _fixpoints(self) -> None:
        for key, info in self.fns.items():
            self.may_acquire[key] = {lid: "" for lid in info.acquires}
            self.may_block[key] = {op: "" for op in info.blocks}
        changed = True
        while changed:
            changed = False
            for key, info in self.fns.items():
                for callee, _line in info.calls:
                    cq = self.fns[callee].ctx.qualname(self.fns[callee].node)
                    for lid, via in self.may_acquire[callee].items():
                        if lid not in self.may_acquire[key]:
                            self.may_acquire[key][lid] = (
                                cq if not via else f"{cq} -> {via}"
                            )
                            changed = True
                    for op, via in self.may_block[callee].items():
                        if op not in self.may_block[key]:
                            self.may_block[key][op] = (
                                cq if not via else f"{cq} -> {via}"
                            )
                            changed = True

    # -- graph ---------------------------------------------------------------

    def _add_edge(self, src: str, dst: str, site: dict) -> None:
        self.edges.setdefault((src, dst), []).append(site)

    def _build_edges(self) -> None:
        for key, info in self.fns.items():
            qual = info.ctx.qualname(info.node)
            for src, dst, line in info.edges:
                self._add_edge(
                    src, dst, {"path": info.ctx.path, "line": line, "fn": qual, "via": ""}
                )
            for held, call, callee in info.held_calls:
                if callee is None:
                    continue
                for lid, via in self.may_acquire[callee].items():
                    cq = self.fns[callee].ctx.qualname(self.fns[callee].node)
                    chain = cq if not via else f"{cq} -> {via}"
                    for h in held:
                        if h == lid and self.locks[lid].kind == "rlock":
                            continue  # RLock re-entry through the closure
                        self._add_edge(
                            h,
                            lid,
                            {
                                "path": info.ctx.path,
                                "line": call.lineno,
                                "fn": qual,
                                "via": chain,
                            },
                        )

    def cycles(self) -> list[list[str]]:
        """Elementary cycles in the acquisition-order graph (SCC-based:
        each SCC with >1 node reports one representative cycle; self-edges
        report themselves)."""
        adj: dict[str, set[str]] = {}
        for (src, dst) in self.edges:
            adj.setdefault(src, set()).add(dst)
            adj.setdefault(dst, set())
        index: dict[str, int] = {}
        low: dict[str, int] = {}
        on: set[str] = set()
        stack: list[str] = []
        sccs: list[list[str]] = []
        counter = [0]

        def strongconnect(v: str) -> None:
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            for w in sorted(adj.get(v, ())):
                if w not in index:
                    strongconnect(w)
                    low[v] = min(low[v], low[w])
                elif w in on:
                    low[v] = min(low[v], index[w])
            if low[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)

        for v in sorted(adj):
            if v not in index:
                strongconnect(v)
        out: list[list[str]] = []
        for comp in sccs:
            if len(comp) > 1:
                out.append(sorted(comp))
            elif (comp[0], comp[0]) in self.edges:
                out.append(comp)
        return sorted(out)

    # -- helpers -------------------------------------------------------------

    def finding(
        self, rule: str, ctx: ModuleContext, line: int, message: str
    ) -> Finding:
        """A Finding anchored like ctx.finding() but from a raw line."""
        fn = None
        for node, _qual in ctx.functions:
            if (
                getattr(node, "lineno", 1)
                <= line
                <= getattr(node, "end_lineno", 10**9)
            ):
                if fn is None or node.lineno >= fn.lineno:  # innermost
                    fn = node
        return Finding(
            rule=rule,
            path=ctx.path,
            line=line,
            message=message,
            context=ctx.qualname(fn) if fn is not None else "",
            text=ctx.line_text(line),
        )


# ---------------------------------------------------------------------------
# Rules over the model
# ---------------------------------------------------------------------------


def _findings_lock_order(model: ConcurrencyModel) -> list[Finding]:
    out: list[Finding] = []
    for cyc in model.cycles():
        # anchor each cycle at every participating edge's first site: any
        # one of them is the line a fix (or a reasoned suppression) lands on
        ring = " -> ".join(cyc + [cyc[0]])
        sites = []
        for i, a in enumerate(cyc):
            b = cyc[(i + 1) % len(cyc)]
            if (a, b) in model.edges:
                sites.append((a, b, model.edges[(a, b)][0]))
        for a, b, site in sites:
            ctx = model.by_path[site["path"]]
            via = f" (via {site['via']})" if site["via"] else ""
            out.append(
                model.finding(
                    "lock-order-inversion",
                    ctx,
                    site["line"],
                    f"lock-order cycle {ring}: {b} acquired while holding "
                    f"{a} here{via} — another path acquires them in the "
                    "opposite order; two threads walking the cycle from "
                    "different ends deadlock (static lock graph: "
                    "results/lockgraph/)",
                )
            )
    return out


def _findings_blocking_under_lock(model: ConcurrencyModel) -> list[Finding]:
    out: list[Finding] = []
    for key, info in model.fns.items():
        qual = info.ctx.qualname(info.node)
        for held, call, op in info.held_blocks:
            out.append(
                model.finding(
                    "blocking-under-lock",
                    info.ctx,
                    call.lineno,
                    f"{op}() under held lock {held[-1]} in {qual} — every "
                    f"peer of {held[-1]} serializes behind this call; move "
                    "it outside the region or suppress with the reason the "
                    "hold is safe",
                )
            )
        for held, call, callee in info.held_calls:
            if callee is None:
                continue
            blocked = model.may_block.get(callee, {})
            if not blocked:
                continue
            cinfo = model.fns[callee]
            cq = cinfo.ctx.qualname(cinfo.node)
            op, via = sorted(blocked.items())[0]
            chain = cq if not via else f"{cq} -> {via}"
            out.append(
                model.finding(
                    "blocking-under-lock",
                    info.ctx,
                    call.lineno,
                    f"call to {cq} under held lock {held[-1]} in {qual} "
                    f"reaches blocking {op}() (through {chain}) — every "
                    f"peer of {held[-1]} serializes behind it",
                )
            )
    return out


def _findings_sync_io_in_async(model: ConcurrencyModel) -> list[Finding]:
    out: list[Finding] = []
    for ctx in model.ctxs:
        if ctx.path not in project.ASYNC_SCOPED_FILES:
            continue
        for node, qual in ctx.functions:
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            info = model.fns[(ctx.path, qual)]

            skip: set[ast.AST] = set()  # executor-hopped subtrees
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and _last(
                    dotted_name(sub.func)
                ) in project.EXECUTOR_CALLS:
                    for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                        for inner in ast.walk(arg):
                            skip.add(inner)

            for sub in ast.walk(node):
                if not isinstance(sub, ast.Call) or sub in skip:
                    continue
                fn_parent = ctx.enclosing_function(sub)
                if fn_parent is not node:
                    continue  # nested defs (incl. lambdas' bodies) are theirs
                tail = _last(dotted_name(sub.func))
                if _is_blocking(ctx, sub, tail, project.ASYNC_BLOCKING_CALLS):
                    out.append(
                        model.finding(
                            "sync-io-in-async",
                            ctx,
                            sub.lineno,
                            f"synchronous {tail}() inside async {qual} — it "
                            "parks the event loop (EVERY connection stalls, "
                            "not this request); hop through "
                            "loop.run_in_executor or an asyncio equivalent",
                        )
                    )
                    continue
                callee = model._resolve_call(info, sub)
                if callee is None:
                    continue
                cinfo = model.fns[callee]
                if isinstance(cinfo.node, ast.AsyncFunctionDef):
                    continue  # awaited coroutine: a loop citizen
                blocked = model.may_block.get(callee, {})
                if blocked:
                    cq = cinfo.ctx.qualname(cinfo.node)
                    op, via = sorted(blocked.items())[0]
                    chain = cq if not via else f"{cq} -> {via}"
                    out.append(
                        model.finding(
                            "sync-io-in-async",
                            ctx,
                            sub.lineno,
                            f"async {qual} calls sync {cq}, which reaches "
                            f"blocking {op}() ({chain}) — the event loop "
                            "parks for the duration; hop through "
                            "loop.run_in_executor",
                        )
                    )
    return out


_SHARED_STATE_SCOPES = (
    "qdml_tpu/serve/",
    "qdml_tpu/fleet/",
    "qdml_tpu/control/",
    "qdml_tpu/telemetry/",
)


def _findings_unmapped_shared_state(model: ConcurrencyModel) -> list[Finding]:
    out: list[Finding] = []
    for ctx in model.ctxs:
        if not ctx.path.startswith(_SHARED_STATE_SCOPES):
            continue
        for cnode in ast.walk(ctx.tree):
            if not isinstance(cnode, ast.ClassDef):
                continue
            mapped_attrs = set(
                model.lock_map.get(ctx.path, {}).get(cnode.name, {})
            )
            lock_attrs = set(model.class_locks.get(cnode.name, ()))
            safe_attrs = {
                a
                for a, t in _ctor_types(ctx, cnode).items()
                if t in _THREADSAFE_CTORS
            }

            methods = {
                n.name: n for n in cnode.body if isinstance(n, _FuncNode)
            }
            roots = _thread_roots(model, ctx, cnode, methods)

            # same-class call closure per root
            def closure(seed: str) -> set[str]:
                seen, frontier = set(), [seed]
                while frontier:
                    m = frontier.pop()
                    if m in seen or m not in methods:
                        continue
                    seen.add(m)
                    for sub in ast.walk(methods[m]):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == "self"
                            and sub.func.attr in methods
                        ):
                            frontier.append(sub.func.attr)
                return seen

            root_closures = {r: closure(r) for r in roots}
            rooted_methods = set().union(*root_closures.values()) if root_closures else set()

            # writes per entry: each root is one entry; every method NOT in
            # any root closure collectively forms the "caller thread" entry
            writers: dict[str, set[str]] = {}  # attr -> entry labels
            sites: dict[str, tuple[int, str]] = {}  # attr -> (line, method)
            for mname, mnode in methods.items():
                if mname == "__init__":
                    continue
                entries = [
                    f"thread:{r}" for r, cl in root_closures.items() if mname in cl
                ]
                if mname not in rooted_methods:
                    entries.append("caller")
                for sub in ast.walk(mnode):
                    for t in _assign_targets(sub):
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            attr = t.attr
                            if (
                                attr in mapped_attrs
                                or attr in lock_attrs
                                or attr in safe_attrs
                            ):
                                continue
                            writers.setdefault(attr, set()).update(entries)
                            if attr not in sites or sub.lineno < sites[attr][0]:
                                sites[attr] = (sub.lineno, mname)
            for attr, entries in sorted(writers.items()):
                if len(entries) < 2:
                    continue
                line, mname = sites[attr]
                names = ", ".join(sorted(entries))
                out.append(
                    model.finding(
                        "unmapped-shared-state",
                        ctx,
                        line,
                        f"{cnode.name}.{attr} is written from {len(entries)} "
                        f"distinct thread entry points ({names}) but has no "
                        "LOCK_MAP row — add the row (analysis/project.py) so "
                        "serve-lock-discipline guards it, or suppress with "
                        "the reason it is single-threaded after all",
                    )
                )
    return out


def _ctor_types(ctx: ModuleContext, cnode: ast.ClassDef) -> dict[str, str]:
    """attr -> constructor tail for ``self.x = Ctor()`` assignments."""
    out: dict[str, str] = {}
    for sub in ast.walk(cnode):
        if not (isinstance(sub, ast.Assign) and len(sub.targets) == 1):
            continue
        t = sub.targets[0]
        if (
            isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
            and isinstance(sub.value, ast.Call)
        ):
            out[t.attr] = _last(
                ctx.canonical(sub.value.func) or dotted_name(sub.value.func)
            )
    return out


def _thread_roots(
    model: ConcurrencyModel,
    ctx: ModuleContext,
    cnode: ast.ClassDef,
    methods: dict[str, ast.AST],
) -> set[str]:
    """Methods of ``cnode`` that run on another thread: Thread targets,
    done-callbacks, call_soon_threadsafe callables (searched module-wide —
    the pool that spawns the thread may be another class) plus every
    ``async def`` method (the event-loop context)."""
    roots = {
        name
        for name, node in methods.items()
        if isinstance(node, ast.AsyncFunctionDef)
    }
    for sub in ast.walk(ctx.tree):
        if not isinstance(sub, ast.Call):
            continue
        if _last(dotted_name(sub.func)) not in project.THREAD_ROOT_CALLS:
            continue
        for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
            for inner in ast.walk(arg):
                if (
                    isinstance(inner, ast.Attribute)
                    and inner.attr in methods
                    and isinstance(inner.value, ast.Name)
                ):
                    roots.add(inner.attr)
    return roots


def _findings_dead_lock_map(model: ConcurrencyModel) -> list[Finding]:
    out: list[Finding] = []
    # anchor file/class-level misses at the LOCK_MAP literal itself
    proj_ctx = model.by_path.get("qdml_tpu/analysis/project.py")
    map_line = 1
    if proj_ctx is not None:
        for node in proj_ctx.tree.body:
            targets = (
                node.targets
                if isinstance(node, ast.Assign)
                else [node.target]
                if isinstance(node, ast.AnnAssign)
                else []
            )
            if any(
                isinstance(t, ast.Name) and t.id == "LOCK_MAP" for t in targets
            ):
                map_line = node.lineno

    def map_finding(message: str) -> Finding | None:
        if proj_ctx is None:
            return None
        return model.finding(
            "dead-lock-map-entry", proj_ctx, map_line, message
        )

    for path, cls_map in sorted(model.lock_map.items()):
        ctx = model.by_path.get(path)
        if ctx is None:
            f = map_finding(
                f"LOCK_MAP names {path!r}, which is not in the scanned tree "
                "— the rename/delete silently disarmed serve-lock-discipline "
                "for every row under it"
            )
            if f:
                out.append(f)
            continue
        class_nodes = {
            n.name: n for n in ast.walk(ctx.tree) if isinstance(n, ast.ClassDef)
        }
        for cls, attrs in sorted(cls_map.items()):
            cnode = class_nodes.get(cls)
            if cnode is None:
                f = map_finding(
                    f"LOCK_MAP names class {cls!r} in {path}, which no "
                    "longer exists — update or drop the rows"
                )
                if f:
                    out.append(f)
                continue
            assigned = {
                t.attr
                for sub in ast.walk(cnode)
                for t in _assign_targets(sub)
                if isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            }
            for attr, lock in sorted(attrs.items()):
                if attr not in assigned:
                    out.append(
                        model.finding(
                            "dead-lock-map-entry",
                            ctx,
                            cnode.lineno,
                            f"LOCK_MAP row {cls}.{attr} -> {lock}: "
                            f"self.{attr} is never assigned in {cls} — the "
                            "attribute was renamed/removed and the row is "
                            "dead",
                        )
                    )
                if attr in assigned and lock not in model.class_locks.get(
                    cls, {}
                ):
                    out.append(
                        model.finding(
                            "dead-lock-map-entry",
                            ctx,
                            cnode.lineno,
                            f"LOCK_MAP row {cls}.{attr} -> {lock}: "
                            f"self.{lock} is not constructed as a lock in "
                            f"{cls} — the lock was renamed/removed and the "
                            "row cannot be enforced",
                        )
                    )
    return out


def _assign_targets(node: ast.AST) -> list[ast.expr]:
    """Flattened assignment targets — `self._a, self._b = f()` counts both."""
    if isinstance(node, ast.Assign):
        targets = list(node.targets)
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    else:
        return []
    flat: list[ast.expr] = []
    stack = targets
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        elif isinstance(t, ast.Starred):
            stack.append(t.value)
        else:
            flat.append(t)
    return flat


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def load_contexts(
    root: str, files: Iterable[str]
) -> tuple[list[ModuleContext], list[str]]:
    """Parse ``files`` (repo-relative) into ModuleContexts; unparseable files
    come back as error strings (the per-module pass reports them too — the
    concurrency model just skips them)."""
    ctxs: list[ModuleContext] = []
    errors: list[str] = []
    for relpath in files:
        abspath = os.path.join(root, relpath)
        try:
            with open(abspath, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=relpath)
        except (OSError, SyntaxError, ValueError) as e:
            errors.append(f"{relpath}: {type(e).__name__}: {e}")
            continue
        ctxs.append(ModuleContext(abspath, relpath, source, tree))
    return ctxs, errors


def analyze_modules(
    ctxs: list[ModuleContext],
    lock_map: dict[str, dict[str, dict[str, str]]] | None = None,
) -> tuple[dict[str, list[Finding]], ConcurrencyModel]:
    """Run the whole-program pass over parsed modules. Returns findings
    grouped by path (for the engine to merge BEFORE suppression processing)
    plus the model (for lock-graph rendering)."""
    model = ConcurrencyModel(ctxs, lock_map=lock_map)
    findings: list[Finding] = []
    findings += _findings_lock_order(model)
    findings += _findings_blocking_under_lock(model)
    findings += _findings_sync_io_in_async(model)
    findings += _findings_unmapped_shared_state(model)
    findings += _findings_dead_lock_map(model)
    grouped: dict[str, list[Finding]] = {}
    for f in findings:
        grouped.setdefault(f.path, []).append(f)
    return grouped, model


def analyze_files(
    root: str,
    paths: Iterable[str] | None = None,
    lock_map: dict[str, dict[str, dict[str, str]]] | None = None,
) -> tuple[dict[str, list[Finding]], ConcurrencyModel]:
    paths = list(paths) if paths is not None else list(project.DEFAULT_PATHS)
    files = iter_python_files(root, paths)
    ctxs, _errors = load_contexts(root, files)
    return analyze_modules(ctxs, lock_map=lock_map)


# ---------------------------------------------------------------------------
# Lock-graph artifact (results/lockgraph/)
# ---------------------------------------------------------------------------


def lockgraph_json(model: ConcurrencyModel) -> dict:
    """Deterministic JSON-able graph record — byte-stable across runs so the
    tier-1 freshness check can literal-compare regenerated vs committed."""
    nodes = [
        {
            "id": d.lock_id,
            "kind": d.kind,
            "path": d.path,
            "line": d.line,
            "class": d.cls,
            "mapped": d.mapped,
        }
        for d in sorted(model.locks.values(), key=lambda d: d.lock_id)
    ]
    edges = []
    for (src, dst), sites in sorted(model.edges.items()):
        uniq = sorted(
            {(s["path"], s["line"], s["fn"], s["via"]) for s in sites}
        )
        edges.append(
            {
                "src": src,
                "dst": dst,
                "sites": [
                    {"path": p, "line": ln, "fn": fn, "via": via}
                    for p, ln, fn, via in uniq
                ],
            }
        )
    return {
        "schema": 1,
        "kind": "lockgraph",
        "tool": "qdml-tpu lint --lockgraph",
        "nodes": nodes,
        "edges": edges,
        "cycles": model.cycles(),
    }


def _levels(graph: dict) -> dict[str, int]:
    """Longest-path layering of the (acyclic) edge set: level 0 = acquired
    first. Nodes in a cycle (should never be committed) share level -1."""
    cyc_nodes = {n for cyc in graph["cycles"] for n in cyc}
    adj: dict[str, list[str]] = {}
    indeg: dict[str, int] = {n["id"]: 0 for n in graph["nodes"]}
    for e in graph["edges"]:
        if e["src"] in cyc_nodes or e["dst"] in cyc_nodes:
            continue
        adj.setdefault(e["src"], []).append(e["dst"])
        indeg.setdefault(e["src"], indeg.get(e["src"], 0))
        indeg[e["dst"]] = indeg.get(e["dst"], 0) + 1
    level = {n: 0 for n in indeg}
    frontier = sorted(n for n, d in indeg.items() if d == 0)
    while frontier:
        v = frontier.pop()
        for w in adj.get(v, ()):
            level[w] = max(level[w], level[v] + 1)
            indeg[w] -= 1
            if indeg[w] == 0:
                frontier.append(w)
    for n in cyc_nodes:
        level[n] = -1
    return level


def lockgraph_dot(graph: dict) -> str:
    lines = [
        "// generated by `qdml-tpu lint --lockgraph` — do not edit",
        "digraph lockgraph {",
        "  rankdir=LR;",
        '  node [shape=box, fontname="monospace"];',
    ]
    for n in graph["nodes"]:
        shape = ' style="rounded"' if n["kind"] == "rlock" else ""
        fill = ' fillcolor="lightyellow" style="filled"' if not n["mapped"] else ""
        lines.append(
            f'  "{n["id"]}" [label="{n["id"]}\\n({n["kind"]})"{shape}{fill}];'
        )
    for e in graph["edges"]:
        s = e["sites"][0]
        lines.append(
            f'  "{e["src"]}" -> "{e["dst"]}" '
            f'[label="{s["path"]}:{s["line"]}"];'
        )
    lines.append("}")
    return "\n".join(lines) + "\n"


def lockgraph_markdown(graph: dict) -> str:
    level = _levels(graph)
    by_level: dict[int, list[dict]] = {}
    for n in graph["nodes"]:
        by_level.setdefault(level.get(n["id"], 0), []).append(n)
    out_edges: dict[str, list[dict]] = {}
    for e in graph["edges"]:
        out_edges.setdefault(e["src"], []).append(e)
    lines = [
        "# Lock hierarchy (generated)",
        "",
        "Generated by `qdml-tpu lint --lockgraph=results/lockgraph` — do not",
        "edit by hand; `scripts/run_tier1.sh` byte-compares a regenerated",
        "graph against this directory. Level = longest acquisition chain",
        "leading here: a level-N lock may only be acquired while holding",
        "locks of level < N (edges point acquired-while-holding).",
        "",
        "| level | lock | kind | declared | LOCK_MAP | acquired while holding it |",
        "|---|---|---|---|---|---|",
    ]
    for lvl in sorted(by_level):
        for n in sorted(by_level[lvl], key=lambda n: n["id"]):
            dsts = sorted({e["dst"] for e in out_edges.get(n["id"], ())})
            lines.append(
                f"| {lvl} | `{n['id']}` | {n['kind']} | "
                f"`{n['path']}:{n['line']}` | "
                f"{'yes' if n['mapped'] else 'no'} | "
                f"{', '.join(f'`{d}`' for d in dsts) if dsts else '—'} |"
            )
    lines += [
        "",
        f"Edges: {len(graph['edges'])} · locks: {len(graph['nodes'])} · "
        f"cycles: {len(graph['cycles'])} (the lint gate pins this at 0)",
        "",
    ]
    return "\n".join(lines)


def write_lockgraph(model: ConcurrencyModel, out_dir: str) -> dict:
    graph = lockgraph_json(model)
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "lockgraph.json"), "w") as fh:
        json.dump(graph, fh, indent=2, sort_keys=True)
        fh.write("\n")
    with open(os.path.join(out_dir, "lockgraph.dot"), "w") as fh:
        fh.write(lockgraph_dot(graph))
    with open(os.path.join(out_dir, "LOCKGRAPH.md"), "w") as fh:
        fh.write(lockgraph_markdown(graph))
    return graph


def check_lockgraph(model: ConcurrencyModel, out_dir: str) -> list[str]:
    """Freshness check: regenerated graph must equal the committed one.
    Returns human-readable mismatch strings (empty = fresh)."""
    problems: list[str] = []
    graph = lockgraph_json(model)
    path = os.path.join(out_dir, "lockgraph.json")
    if not os.path.exists(path):
        return [f"{path}: missing — run `qdml-tpu lint --lockgraph={out_dir}`"]
    with open(path) as fh:
        committed = json.load(fh)
    if committed != graph:
        problems.append(
            f"{path}: stale — the committed lock graph does not match the "
            f"tree (run `qdml-tpu lint --lockgraph={out_dir}` and commit)"
        )
    for name, render in (
        ("lockgraph.dot", lockgraph_dot(graph)),
        ("LOCKGRAPH.md", lockgraph_markdown(graph)),
    ):
        p = os.path.join(out_dir, name)
        if not os.path.exists(p):
            problems.append(f"{p}: missing")
            continue
        with open(p) as fh:
            if fh.read() != render:
                problems.append(f"{p}: stale (regenerate with --lockgraph)")
    return problems
