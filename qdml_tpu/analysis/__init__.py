"""graftlint: the project-aware JAX/TPU static analysis suite.

``qdml-tpu lint`` (and ``scripts/run_lint.sh``) runs an AST-based rule set
derived from bugs this repo has actually shipped or review-hardened —
recompile traps, host syncs in hot paths, primary-only collectives that
deadlock multihost, serve-path lock/future discipline, broad excepts that
swallow the project's typed errors — plus the slow-marker budget rule folded
in from ``scripts/lint_markers.py``. Per-line
``# lint: disable=rule(reason)`` suppressions and a checked-in baseline
(``scripts/lint_baseline.json``) keep the gate zero-findings-or-allowlisted.

Rule catalog with the shipped bug behind each rule: ``docs/ANALYSIS.md``.
The runtime complement (``jax.experimental.checkify`` threaded through the
train steps and serve engine) lives in :mod:`qdml_tpu.telemetry.sanitizer`.
"""

from qdml_tpu.analysis.engine import (  # noqa: F401
    Finding,
    LintEngine,
    LintResult,
    ModuleContext,
    load_baseline,
    parse_suppressions,
    save_baseline,
)
from qdml_tpu.analysis.rules import RULES, all_rules  # noqa: F401
