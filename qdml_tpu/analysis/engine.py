"""graftlint engine: AST module model, suppressions, baseline, orchestration.

The engine parses each file once into a :class:`ModuleContext` carrying the
project-aware facts every rule shares — jit-reachability (which functions XLA
will trace), module-level mutable state, import aliases, a parent map — then
runs the rule set (:mod:`qdml_tpu.analysis.rules`) over it.

Two allowlist layers keep the gate zero-findings-from-day-one without hiding
new regressions:

- per-line suppressions: ``# lint: disable=rule-id(written reason)`` on the
  offending line. A reason is REQUIRED — a suppression without one does not
  suppress (the policy is "allowlist with reason or fix", never "allowlist");
- a checked-in baseline (``scripts/lint_baseline.json``): fingerprinted
  grandfathered findings (rule + file + enclosing def + normalized source
  text — line-number free, so unrelated edits don't invalidate entries).
  ``--baseline`` subtracts it; anything NOT in it is a *new* finding and
  fails the gate.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Callable, Iterable


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclass
class Finding:
    """One lint violation, anchored to a source line."""

    rule: str
    path: str           # repo-relative, forward slashes
    line: int           # 1-based
    message: str
    context: str = ""   # enclosing qualname ("Class.method"), "" at module level
    text: str = ""      # stripped source line (fingerprint input)
    suppressed: bool = False
    reason: str | None = None  # suppression/baseline reason when allowlisted

    @property
    def fingerprint(self) -> str:
        """Line-number-free identity for baseline matching: unrelated edits
        that shift lines must not invalidate grandfathered entries, while
        editing the offending line itself (or moving it to another function)
        re-arms the gate."""
        key = f"{self.rule}|{self.path}|{self.context}|{self.text}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "context": self.context,
            "message": self.message,
            "text": self.text,
            "fingerprint": self.fingerprint,
            "suppressed": self.suppressed,
            "reason": self.reason,
        }


# ---------------------------------------------------------------------------
# Per-line suppressions
# ---------------------------------------------------------------------------

_SUPPRESS_RE = re.compile(r"#\s*lint:\s*disable=(?P<items>.+?)\s*$")
_ITEM_RE = re.compile(r"(?P<rule>[\w.-]+)\s*(?:\((?P<reason>.*)\))?", re.DOTALL)


def _split_items(items: str) -> list[str]:
    """Split ``rule-a(reason),rule-b(reason)`` on top-level commas only —
    reasons may themselves contain parenthesized asides and commas."""
    out, depth, cur = [], 0, []
    for ch in items:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [s.strip() for s in out if s.strip()]


def parse_suppressions(source: str) -> dict[int, dict[str, str | None]]:
    """``{line -> {rule-id -> reason}}`` from trailing lint-disable comments.

    Syntax: ``# lint: disable=<rule-a>(<reason>),<rule-b>(<reason>)`` (angle
    brackets are placeholders — they keep this very docstring from parsing
    as a suppression, since the scan is line-based and cannot see string
    literals). The reason is mandatory for the suppression to take effect; a
    missing one is recorded as ``None`` and the engine converts it into a
    ``bare-suppression`` finding instead of honoring it.
    """
    out: dict[int, dict[str, str | None]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        rules: dict[str, str | None] = {}
        for item in _split_items(m.group("items")):
            im = _ITEM_RE.fullmatch(item)
            if not im:
                continue
            reason = im.group("reason")
            rules[im.group("rule")] = reason.strip() if reason and reason.strip() else None
        if rules:
            out[i] = rules
    return out


# ---------------------------------------------------------------------------
# Module model
# ---------------------------------------------------------------------------


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_MUTABLE_CTORS = {"dict", "list", "set", "deque", "defaultdict", "Counter", "OrderedDict"}

_FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


class ModuleContext:
    """Parsed module + the shared project-aware facts rules consume."""

    def __init__(self, abspath: str, relpath: str, source: str, tree: ast.Module):
        self.abspath = abspath
        self.path = relpath.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self.suppressions = parse_suppressions(source)

        self.parent: dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node

        # function defs with qualnames
        self.functions: list[tuple[ast.AST, str]] = []
        self._qualname: dict[ast.AST, str] = {}
        self._collect_functions(tree, prefix="")
        self._by_name: dict[str, list[ast.AST]] = {}
        for node, qual in self.functions:
            self._by_name.setdefault(node.name, []).append(node)

        self.aliases = self._collect_aliases()
        self.mutable_globals = self._collect_mutable_globals()
        self.traced = self._collect_traced()

    # -- construction helpers ------------------------------------------------

    def _collect_functions(self, node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FuncNode):
                qual = f"{prefix}{child.name}"
                self.functions.append((child, qual))
                self._qualname[child] = qual
                self._collect_functions(child, prefix=f"{qual}.")
            elif isinstance(child, ast.ClassDef):
                self._collect_functions(child, prefix=f"{prefix}{child.name}.")
            else:
                self._collect_functions(child, prefix=prefix)

    def _collect_aliases(self) -> dict[str, str]:
        """local name -> canonical dotted module/object it refers to."""
        out: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    out[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    out[a.asname or a.name] = f"{node.module}.{a.name}"
        return out

    def canonical(self, node: ast.AST) -> str | None:
        """Dotted name with the leading alias resolved through the imports
        (``jnp.mean`` -> ``jax.numpy.mean``)."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        full = self.aliases.get(head, head)
        return f"{full}.{rest}" if rest else full

    def _collect_mutable_globals(self) -> set[str]:
        out: set[str] = set()
        for node in self.tree.body:
            targets: list[ast.expr] = []
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            else:
                continue
            mutable = isinstance(
                value, (ast.Dict, ast.List, ast.Set, ast.DictComp, ast.ListComp, ast.SetComp)
            ) or (
                isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in _MUTABLE_CTORS
            )
            if not mutable:
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        return out

    def _collect_traced(self) -> set[ast.AST]:
        """Functions XLA will trace: @jax.jit-decorated (directly or through
        ``partial(jax.jit, ...)``), passed by name into a tracing entry point
        (jit/vmap/scan/checkify/make_scan_steps/... — including through
        nested ``partial(...)`` calls), plus every same-module function a
        traced function calls (fixpoint)."""
        from qdml_tpu.analysis.project import TRACING_ENTRY_POINTS

        traced: set[ast.AST] = set()

        def is_jit_expr(expr: ast.AST) -> bool:
            name = self.canonical(expr)
            if name and name.rsplit(".", 1)[-1] == "jit":
                return True
            if isinstance(expr, ast.Call):
                return any(is_jit_expr(a) for a in expr.args) or is_jit_expr(expr.func)
            return False

        # decorator roots
        for node, _qual in self.functions:
            for dec in node.decorator_list:
                if is_jit_expr(dec):
                    traced.add(node)

        # names passed (possibly through nested calls like partial(...))
        # into tracing entry points
        def arg_names(call: ast.Call) -> Iterable[str]:
            for sub in ast.walk(call):
                if isinstance(sub, ast.Name):
                    yield sub.id

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            if callee.rsplit(".", 1)[-1] not in TRACING_ENTRY_POINTS:
                continue
            for name in arg_names(node):
                for fn in self._by_name.get(name, []):
                    traced.add(fn)

        # propagate through same-module direct calls
        changed = True
        while changed:
            changed = False
            for fn in list(traced):
                for sub in ast.walk(fn):
                    if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                        for callee_fn in self._by_name.get(sub.func.id, []):
                            if callee_fn not in traced:
                                traced.add(callee_fn)
                                changed = True
        return traced

    # -- rule helpers --------------------------------------------------------

    def qualname(self, node: ast.AST) -> str:
        return self._qualname.get(node, "")

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        cur = self.parent.get(node)
        while cur is not None and not isinstance(cur, _FuncNode):
            cur = self.parent.get(cur)
        return cur

    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        fn = self.enclosing_function(node)
        return Finding(
            rule=rule,
            path=self.path,
            line=line,
            message=message,
            context=self.qualname(fn) if fn is not None else "",
            text=self.line_text(line),
        )


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_DEFAULT = os.path.join("scripts", "lint_baseline.json")
GRANDFATHER_REASON = "grandfathered at gate introduction (see docs/ANALYSIS.md)"


def load_baseline(path: str) -> dict[str, dict]:
    """fingerprint -> entry. Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path) as fh:
        data = json.load(fh)
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def save_baseline(path: str, findings: list[Finding], previous: dict[str, dict] | None = None) -> int:
    """Write the baseline for ``findings``; reasons from ``previous`` entries
    that still match are preserved (a regenerate must not erase triage
    notes). Returns the entry count."""
    previous = previous or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        old = previous.get(f.fingerprint)
        entries.append(
            {
                "fingerprint": f.fingerprint,
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "text": f.text,
                "reason": (old or {}).get("reason") or GRANDFATHER_REASON,
            }
        )
    payload = {
        "version": 1,
        "tool": "qdml-tpu lint",
        "note": (
            "Grandfathered findings (fingerprint = rule+file+def+line text; "
            "line-number free). Regenerate with `qdml-tpu lint "
            "--write-baseline`; existing reasons are preserved."
        ),
        "entries": entries,
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as fh:
        json.dump(payload, fh, indent=2)
        fh.write("\n")
    return len(entries)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


@dataclass
class LintResult:
    new: list[Finding] = field(default_factory=list)          # fail the gate
    suppressed: list[Finding] = field(default_factory=list)   # inline-allowlisted
    baselined: list[Finding] = field(default_factory=list)    # grandfathered
    errors: list[str] = field(default_factory=list)           # unparseable files

    @property
    def ok(self) -> bool:
        return not self.new and not self.errors

    def to_json(self) -> dict:
        per_rule: dict[str, int] = {}
        for f in self.new:
            per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
        return {
            "schema": 1,
            "kind": "lint_gate",
            "ok": self.ok,
            "new_findings": len(self.new),
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "per_rule": dict(sorted(per_rule.items())),
            "errors": self.errors,
            "findings": [f.to_json() for f in self.new],
        }


def iter_python_files(
    root: str, paths: Iterable[str], missing: list[str] | None = None
) -> list[str]:
    """Repo-relative *.py files under the given paths (files or directories),
    sorted, __pycache__ excluded. Paths that exist as neither are appended to
    ``missing`` — a typo'd --paths (or a renamed DEFAULT_PATHS entry) must
    fail the gate, not scan nothing and report green."""
    out: list[str] = []
    for p in paths:
        absp = os.path.join(root, p)
        if os.path.isfile(absp) and p.endswith(".py"):
            out.append(p)
            continue
        if not os.path.isdir(absp):
            if missing is not None:
                missing.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(absp):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(os.path.join(dirpath, fn), root))
    return sorted(set(out))


class LintEngine:
    """Run the rule set over a file list, apply suppressions and baseline."""

    def __init__(self, root: str, rules: list[Callable[[ModuleContext], list[Finding]]] | None = None):
        self.root = root
        if rules is None:
            from qdml_tpu.analysis.rules import all_rules

            rules = all_rules()
        self.rules = rules
        # the concurrency model from the last whole_program run() —
        # consumed by the CLI's --lockgraph rendering/freshness check
        self.model = None

    def lint_file(
        self, relpath: str, pre: Iterable[Finding] = ()
    ) -> tuple[list[Finding], str | None]:
        """Run the per-module rules over one file. ``pre`` carries findings a
        whole-program pass (analysis/concurrency.py) already produced for
        this path — merged BEFORE suppression processing so an inline
        ``# lint: disable=...`` works on them and a stale one is flagged
        dead-suppression like any other."""
        abspath = os.path.join(self.root, relpath)
        try:
            with open(abspath, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=relpath)
        except (OSError, SyntaxError, ValueError) as e:
            return [], f"{relpath}: {type(e).__name__}: {e}"
        ctx = ModuleContext(abspath, relpath, source, tree)
        findings: list[Finding] = []
        seen_lines: set[tuple[str, int]] = set()
        for rule in self.rules:
            for f in rule(ctx):
                # one finding per (rule, line): nested calls on one line
                # (np.asarray(jax.device_get(x))) share a fingerprint, and a
                # duplicate would double-count in the gate while a single
                # baseline entry silently absorbed both
                if (f.rule, f.line) in seen_lines:
                    continue
                seen_lines.add((f.rule, f.line))
                findings.append(f)
        for f in pre:
            if (f.rule, f.line) in seen_lines:
                continue
            seen_lines.add((f.rule, f.line))
            findings.append(f)
        # apply per-line suppressions; reason-less ones become findings
        for f in findings:
            sup = ctx.suppressions.get(f.line, {})
            if f.rule in sup:
                reason = sup[f.rule]
                if reason:
                    f.suppressed = True
                    f.reason = reason
                else:
                    f.message += (
                        "  [a lint-disable comment matched but carries no "
                        "(reason) — reasons are mandatory, see docs/ANALYSIS.md]"
                    )
        # Suppressions that never matched anything are dead weight: flag
        # reason-less ones as bare-suppression (the '(reason)' policy stays
        # machine-enforced even when the finding is gone) and reasoned ones
        # as dead-suppression (a stale comment claims a hazard the rule no
        # longer sees — either the code was fixed, so remove it, or the rule
        # can't see the hazard, so the comment is false documentation).
        for line, rules in ctx.suppressions.items():
            for rule_id, reason in rules.items():
                if any(f.line == line and f.rule == rule_id for f in findings):
                    continue
                if reason is None:
                    findings.append(
                        Finding(
                            rule="bare-suppression",
                            path=ctx.path,
                            line=line,
                            message=(
                                f"lint-disable for {rule_id!r} has no (reason); "
                                "suppressions without a written reason do not count"
                            ),
                            text=ctx.line_text(line),
                        )
                    )
                else:
                    findings.append(
                        Finding(
                            rule="dead-suppression",
                            path=ctx.path,
                            line=line,
                            message=(
                                f"lint-disable for {rule_id!r} matches no "
                                "finding on this line — remove the stale "
                                "comment (or fix the rule if the hazard is real)"
                            ),
                            text=ctx.line_text(line),
                        )
                    )
        return findings, None

    def run(
        self,
        paths: Iterable[str],
        baseline: dict[str, dict] | None = None,
        extra_findings: Iterable[Finding] = (),
        whole_program: bool = True,
        restrict_to: Iterable[str] | None = None,
    ) -> LintResult:
        """``whole_program`` additionally runs the interprocedural
        concurrency pass over the full scanned set (the resulting model is
        kept on ``self.model`` for lock-graph rendering). ``restrict_to``
        filters the REPORT to the given repo-relative paths without
        narrowing the scan — `--changed-only` needs the whole program to
        resolve the call closure, but only the touched files' findings."""
        result = LintResult()
        all_findings: list[Finding] = list(extra_findings)
        missing: list[str] = []
        files = iter_python_files(self.root, paths, missing=missing)
        pre_by_path: dict[str, list[Finding]] = {}
        if whole_program:
            from qdml_tpu.analysis import concurrency

            ctxs, _errs = concurrency.load_contexts(self.root, files)
            pre_by_path, self.model = concurrency.analyze_modules(ctxs)
        for relpath in files:
            findings, err = self.lint_file(
                relpath, pre=pre_by_path.get(relpath, ())
            )
            if err is not None:
                result.errors.append(err)
            all_findings.extend(findings)
        for p in missing:
            result.errors.append(
                f"{p}: no such file or directory — a gate that scans nothing "
                "must not pass"
            )
        if restrict_to is not None:
            keep = set(restrict_to)
            all_findings = [f for f in all_findings if f.path in keep]
            result.errors = [
                e for e in result.errors if e.split(":", 1)[0] in keep
            ]
        baseline = baseline or {}
        for f in sorted(all_findings, key=lambda f: (f.path, f.line, f.rule)):
            if f.suppressed:
                result.suppressed.append(f)
            elif f.fingerprint in baseline:
                f.reason = baseline[f.fingerprint].get("reason")
                result.baselined.append(f)
            else:
                result.new.append(f)
        return result
