"""Project-aware lint configuration: the maps that make graftlint *this
repo's* linter instead of a generic JAX style checker.

Every entry here encodes a hazard this codebase has actually shipped or
review-hardened (docs/ANALYSIS.md carries the full catalog with the history):

- :data:`DEFAULT_PATHS` — what ``qdml-tpu lint`` scans. ``tests/`` is
  deliberately excluded from the AST rules (fixture files under
  ``tests/fixtures/lint/`` contain intentional violations; test modules run
  device ops at import time by design) — test wall-clock budgets are covered
  by the separate slow-marker rule over a ``--durations`` report instead.
- :data:`LOCK_MAP` — the serve-path lock discipline: thread-shared attributes
  and the lock that must be held to touch them (the PR-2 soak-test race
  shape: ``MicroBatcher._q`` mutated while a worker drains it).
- :data:`HOT_HOST_FUNCS` — host-side request-path functions where every
  device→host sync must be deliberate (audited via suppression, never
  incidental).
- :data:`COLLECTIVE_CALLS` — calls that are multi-host collectives (orbax
  saves above all): guarding them behind ``is_primary()`` deadlocks every
  non-primary process at the collective's barrier — the exact bug
  review-hardened in PR 3's flight-recorder dump path.
- :data:`TYPED_EXCEPTIONS` — the project's typed error contracts that a
  broad ``except`` can silently swallow (``DivergenceError`` exits the CLI
  with code 4; serving sheds via typed ``Overloaded`` results).
"""

from __future__ import annotations

# Paths scanned by default (repo-relative; directories recurse over *.py).
DEFAULT_PATHS: tuple[str, ...] = (
    "qdml_tpu",
    "scripts",
    "bench.py",
    "__graft_entry__.py",
)

# Thread-shared state -> required lock, per file and class. Attribute reads
# AND writes outside a ``with self.<lock>:`` block are findings (``__init__``
# is exempt: construction happens-before any sharing).
LOCK_MAP: dict[str, dict[str, dict[str, str]]] = {
    "qdml_tpu/serve/batcher.py": {"MicroBatcher": {"_q": "_lock"}},
    # hot-swap epoch state: the live (hdce, clf) param tuple and its epoch
    # counter swap atomically between batches — a read outside the lock can
    # see a torn checkpoint mid-swap. The sparse-dispatch overflow counters
    # are incremented by every worker thread's infer() and read by
    # dispatch_summary(): unlocked access would drop counts under the same
    # multi-worker interleaving the PR-2 soak test caught.
    "qdml_tpu/serve/engine.py": {
        "ServeEngine": {
            "_live": "_swap_lock",
            "_swap_epoch": "_swap_lock",
            "_overflow_rows": "_dispatch_lock",
            "_routed_rows": "_dispatch_lock",
        }
    },
    # pool-wide worker-exit accounting: every replica's workers share one
    # coordinator, and an unlocked read is exactly the "crashed worker sheds
    # a queue its peers are draining" race the counter exists to prevent.
    # The elastic replica list: resized by the autoscaler thread while
    # loadgen/metrics threads iterate it — an unlocked read can see a
    # half-popped list exactly like the PR-2 queue race (retired replicas
    # ride the same lock: merged_metrics must never miss a scale-down's
    # served history)
    "qdml_tpu/serve/server.py": {
        "ExitCoordinator": {"_live": "_lock"},
        # _quarantined rides _pool_lock like the replica/retired lists: the
        # supervisor thread moves crash-looping replicas there while health/
        # metrics readers iterate; the dedup cache's entry map is shared
        # between the event loop (inserts) and worker threads (the
        # forget-unless-served done-callbacks)
        "ReplicaPool": {
            "_replicas": "_pool_lock",
            "_retired": "_pool_lock",
            "_quarantined": "_pool_lock",
        },
        "DedupCache": {"_entries": "_lock"},
    },
    # breaker state machine: every submit (any thread) runs allow() and the
    # health/metrics paths read summary() — all transitions and counters
    # live under the one lock
    "qdml_tpu/serve/breaker.py": {
        "CircuitBreaker": {
            "_state": "_lock",
            "_opens": "_lock",
            "_fast_fails": "_lock",
        }
    },
    # fleet-router cross-thread state (docs/FLEET.md): the per-backend
    # ejection state machine is driven by request executor threads AND the
    # health poll thread at once (an unlocked transition could re-admit a
    # host mid-ejection); the fleet-wide dedup table is shared by every
    # front-door request thread (the server-side DedupCache race, one tier
    # up); the wire-metrics ledger and the connection pool are touched by
    # every concurrent forward.
    "qdml_tpu/fleet/router.py": {
        "BackendState": {
            "_state": "_lock",
            "_fails": "_lock",
            "_oks": "_lock",
            "_opened_at": "_lock",
            "_ejections": "_lock",
            "_readmissions": "_lock",
        },
        "Backend": {
            "_latency": "_mlock",
            "_forwarded": "_mlock",
            "_failed": "_mlock",
            # in-flight forward count: incremented by request executors,
            # read by the retirement drain wait — an unlocked read could
            # terminate a backend with a forward still on the wire
            "_inflight": "_mlock",
            "_clients": "_clients_lock",
            "_made": "_clients_lock",
        },
        "RouterDedup": {"_entries": "_lock"},
        # traced-request net-wire histogram: fed by every request executor
        # thread that traced a forward, read by the metrics aggregation;
        # the consistent-hash ring + member table are REPLACED (never
        # mutated) under _ring_lock on admission/retirement while every
        # request thread snapshots them — an unlocked swap could hand a
        # reader a ring indexed against the wrong member list
        "FleetRouter": {
            "_trace_wire": "_trace_lock",
            "_ring": "_ring_lock",
            "_ring_idx": "_ring_lock",
        },
    },
    # elastic-fleet lifecycle state (docs/FLEET.md "elastic fleet"): the
    # member/process tables are written by scale operations (controller
    # thread) while status() serves concurrent front-door reads
    "qdml_tpu/fleet/lifecycle.py": {
        "BackendLifecycle": {
            "_members": "_lock",
            "_procs": "_lock",
        },
    },
    # fleet-control shared state (docs/CONTROL.md): the controller tick
    # thread writes these while status/report paths read them
    "qdml_tpu/control/drift.py": {
        # detector windows: per-(scenario, signal) PH state + debounce/latch
        "DriftMonitor": {"_windows": "_lock"},
    },
    "qdml_tpu/control/autoscale.py": {
        # the autoscaler's current target replica count (hysteresis state)
        "Autoscaler": {"_target": "_lock"},
    },
    "qdml_tpu/control/fleet_scale.py": {
        # fleet-tier twin: target backend count + streaks + planner pin
        "FleetAutoscaler": {"_target": "_lock", "_planner": "_lock"},
    },
    "qdml_tpu/control/deploy.py": {
        # the post-deploy rollback watch window
        "Deployer": {"_watch": "_lock"},
    },
    # event-spine ring state (docs/TELEMETRY.md "event spine"): publishers
    # are request workers, supervisors and poll threads while tails come
    # from the asyncio verb handlers — an unlocked append/evict pair could
    # tear seq/dropped accounting and make loss silent, the one thing the
    # spine exists to prevent
    "qdml_tpu/telemetry/events.py": {
        "EventBus": {
            "_ring": "_lock",
            "_seq": "_lock",
            "_dropped": "_lock",
        },
    },
}

# (file, ClassName.method) host-side hot paths audited for device->host
# syncs. Traceable (jit-reachable) functions are detected automatically; this
# map adds the host-side serve request path, where a sync is sometimes THE
# point (the reply fetch) but must carry a written justification.
HOT_HOST_FUNCS: dict[str, tuple[str, ...]] = {
    "qdml_tpu/serve/engine.py": ("ServeEngine.infer",),
    "qdml_tpu/serve/server.py": ("ServeLoop._serve_one",),
}

# Call names that are (or wrap) multi-host collectives. save_checkpoint /
# save_train_state wrap orbax saves, which are collective across processes.
COLLECTIVE_CALLS: frozenset[str] = frozenset(
    {
        "save_checkpoint",
        "save_train_state",
        "broadcast_one_to_all",
        "sync_global_devices",
        "process_allgather",
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "all_gather",
        "all_to_all",
    }
)

# Guard predicates that make a block primary-only.
PRIMARY_GUARDS: frozenset[str] = frozenset({"is_primary", "process_index"})

# Named-axis collectives/queries that only mean something inside a
# ``shard_map`` region. In ``quantum/`` (the mesh-sharded statevector
# subsystem) one of these traced OUTSIDE a shard_map-wrapped function is the
# multihost-deadlock shape: an unbound-axis error at best, and in a pjit
# program a collective some devices never join at worst (rule
# collective-outside-shardmap).
SHARD_AXIS_CALLS: frozenset[str] = frozenset(
    {
        "ppermute",
        "pshuffle",
        "psum",
        "pmean",
        "pmax",
        "pmin",
        "psum_scatter",
        "all_gather",
        "all_to_all",
        "axis_index",
    }
)

# Typed exceptions a broad except can swallow (rule broad-except's message
# names them so the fix is obvious).
TYPED_EXCEPTIONS: tuple[str, ...] = ("DivergenceError", "KeyboardInterrupt")

# Names whose call is a host-side device sync when it appears in a traced
# (jit-reachable) function or a HOT_HOST_FUNCS request path.
HOST_SYNC_ATTRS: frozenset[str] = frozenset({"item", "device_get", "block_until_ready"})
HOST_SYNC_NAMES: frozenset[str] = frozenset({"float", "int", "bool"})
HOST_SYNC_NP: frozenset[str] = frozenset({"asarray", "array"})

# Wall-clock sources that silently freeze into a jitted program as constants.
WALL_CLOCK_CALLS: frozenset[str] = frozenset(
    {"time", "monotonic", "perf_counter", "process_time", "now", "utcnow", "today"}
)

# Entry points whose function-valued arguments get traced by JAX (used to
# seed jit-reachability beyond literal @jax.jit decorators). Matched on the
# last attribute segment of the callee.
TRACING_ENTRY_POINTS: frozenset[str] = frozenset(
    {
        "jit",
        "vmap",
        "pmap",
        "scan",
        "cond",
        "while_loop",
        "fori_loop",
        "shard_map",
        "checkify",
        "checkify_step",
        "remat",
        "checkpoint",
        "grad",
        "value_and_grad",
        "make_scan_steps",
        "custom_vjp",
        "custom_jvp",
    }
)

# Train-step maker naming convention: these must audit their jit for
# donate_argnums/static_* (eval-step makers are exempt — nothing to donate).
TRAIN_MAKER_PATTERN = r"^make_\w*(train|scan)\w*step"

# jnp calls whose OUTPUT SHAPE depends on input VALUES: under jit these
# either raise (nonzero/unique without a static size=) or silently force a
# host fallback/concretization — the hazard class capacity-bucketed sparse
# dispatch exists to avoid (rule data-dependent-shape-in-jit). Matched on the
# callee's last attribute segment under the jax.numpy namespace; jnp.where is
# handled separately (only its ONE-argument nonzero form is data-dependent).
DATA_DEP_SHAPE_CALLS: frozenset[str] = frozenset(
    {
        "nonzero",
        "flatnonzero",
        "argwhere",
        "unique",
        "unique_all",
        "unique_counts",
        "unique_inverse",
        "unique_values",
    }
)

# Socket/stream IO calls a retry loop re-attempts (rule retry-without-backoff):
# matched on the callee's last attribute segment inside a try body inside a
# host-side loop. Deliberately narrow — `result`/`get` are far too generic,
# and flagging them would make the rule cry wolf on every future drain.
RETRY_IO_CALLS: frozenset[str] = frozenset(
    {
        "create_connection",
        "connect",
        "connect_ex",
        "open_connection",
        "sendall",
        "send",
        "recv",
        "recv_into",
        "readline",
        "readexactly",
        "readuntil",
        "urlopen",
    }
)

# Calls that count as backoff between retry attempts (rule
# retry-without-backoff looks for ANY of these in the loop body; the repo's
# sanctioned shape is ServeClient._backoff -> time.sleep).
BACKOFF_CALLS: frozenset[str] = frozenset({"sleep", "wait", "backoff", "_backoff"})

# Exception names whose catch marks a loop's try as a transient-IO retry.
TRANSIENT_IO_EXCEPTIONS: frozenset[str] = frozenset(
    {
        "ConnectionError",
        "ConnectionResetError",
        "ConnectionRefusedError",
        "BrokenPipeError",
        "OSError",
        "IOError",
        "TimeoutError",
        "timeout",
        "ServeClientError",
    }
)

# Async stream reads that must be timeout-bounded in serve paths (rule
# unbounded-readline): a bare `await reader.readline()` is how one dead peer
# pins a connection slot forever — the sanctioned form routes through
# asyncio.wait_for (serve/server._read_line).
UNBOUNDED_READ_CALLS: frozenset[str] = frozenset(
    {"readline", "readexactly", "readuntil"}
)

# Request-tracing construction/stamping API (telemetry/tracing.py). Tracing
# is HOST-SIDE ONLY by contract: a TraceContext built — or a phase stamped —
# inside jit-compiled or pallas code would freeze its wall-clock value at
# trace time (the wall-clock-in-jit hazard wearing a tracing hat) and break
# the serve.trace_sample=0 HLO-identity pin (rule trace-in-jit-path).
# Matched on the callee's last name/attribute segment.
TRACE_STAMP_CALLS: frozenset[str] = frozenset(
    {"TraceContext", "trace_sampled", "add_phase"}
)

# Per-gate matrix constructors (quantum/circuits.py, quantum/statevector.py):
# calling one of these inside a host-side Python loop over layers/gates
# rebuilds the gate matrix every iteration — the shape the Qandle-style
# gate-matrix-caching refactor removed from the hot paths (the whole
# circuit's trig comes from one vectorized shot; per-layer unitaries from
# fused_layer_unitaries). Matched on the callee's last attribute segment.
GATE_MATRIX_CONSTRUCTORS: frozenset[str] = frozenset(
    {"rot_gate", "gate_h", "gate_rx"}
)

# Cumulative run-lifetime counters (serve/metrics.py ServeMetrics,
# fleet/router.py, serve/breaker.py): dividing one by a wall-clock span is
# an UNWINDOWED rate — it averages the counter's entire lifetime, so a
# restarted process reports garbage (negative deltas upstream, wildly
# smoothed rates here) and a long-running one can never surface a
# regression. Windowed rates come from snapshot differencing
# (telemetry/timeseries.counter_delta — rule unwindowed-cumulative-rate;
# the differencing module itself is sanctioned, RATE_SANCTIONED_MODULES).
# Matched on the numerator's last (underscore-stripped) name segment;
# run-level SUMMARY rates over an explicit full-run span are sanctioned by
# suppression at the site.
CUMULATIVE_COUNTERS: frozenset[str] = frozenset(
    {
        "completed",
        "rows_useful",
        "rows_padded",
        "shed",
        "forwarded",
        "failed_forwards",
        "failovers",
        "fast_fails",
        "admitted",
        "dedup_hits",
        "give_ups",
        "slo_met",
        "slo_total",
        "restarts",
        "ejections",
        "readmissions",
    }
)

# Wall-time denominators for unwindowed-cumulative-rate: the clock reads
# that measure spans (subset of WALL_CLOCK_CALLS — now()/today() produce
# datetimes, not seconds) plus any local name assigned from an expression
# containing one (elapsed = time.monotonic() - t0).
WALL_TIME_CALLS: frozenset[str] = frozenset({"time", "monotonic", "perf_counter"})

# Modules allowed to divide counters by time: the snapshot-differencing
# helpers themselves (they difference FIRST, then divide the delta by the
# window width — the pattern the rule exists to funnel everything through).
RATE_SANCTIONED_MODULES: tuple[str, ...] = ("qdml_tpu/telemetry/timeseries.py",)

# ---------------------------------------------------------------------------
# Concurrency analyzer tables (analysis/concurrency.py — docs/ANALYSIS.md
# "whole-program concurrency").
# ---------------------------------------------------------------------------

# Calls that can block the calling thread for unbounded (or scheduling-
# dependent) time. Reachable inside a held-lock region they serialize every
# peer of that lock behind one slow operation (rule blocking-under-lock).
# Matched on the callee's LAST name/attribute segment; deliberately narrow —
# `.get()`/`.pop()` are far too generic to flag.
BLOCKING_CALLS: frozenset[str] = frozenset(
    {
        # host scheduling
        "sleep",
        "wait",            # Event.wait / Condition.wait / Popen.wait
        "join",            # Thread.join / Process.join
        "result",          # concurrent.futures drain
        # device fences (a lock held across a device sync serializes every
        # submit behind the fence — the swap path suppresses WITH a reason)
        "block_until_ready",
        "device_get",
        # socket / stream IO
        "create_connection",
        "connect",
        "accept",
        "recv",
        "recv_into",
        "sendall",
        "readline",
        "readexactly",
        "urlopen",
        # subprocess
        "check_output",
        "check_call",
        "communicate",
        "popen",
        "Popen",
    }
)

# Synchronous calls that stall the event loop when reached from an
# ``async def`` handler without an executor hop (rule sync-io-in-async).
# time.sleep is the classic; asyncio.sleep resolves to a different canonical
# name and is exempt. The sanctioned escape hatches are the loop's
# run_in_executor / asyncio.to_thread (the callable is PASSED, not called).
ASYNC_BLOCKING_CALLS: frozenset[str] = frozenset(
    {
        "sleep",
        "create_connection",
        "connect",
        "accept",
        "recv",
        "recv_into",
        "sendall",
        "urlopen",
        "check_output",
        "check_call",
        "communicate",
        "result",          # concurrent.futures .result() parks the loop
        "join",
        "run",             # subprocess.run
    }
)

# Files whose ``async def`` handlers are on the serving event loop and are
# therefore in scope for sync-io-in-async (a stalled loop stops EVERY
# connection, not one request).
ASYNC_SCOPED_FILES: tuple[str, ...] = (
    "qdml_tpu/serve/server.py",
    "qdml_tpu/fleet/router.py",
)

# Executor escape hatches: a callable passed INTO one of these runs off the
# event loop, so sync work inside it is sanctioned.
EXECUTOR_CALLS: frozenset[str] = frozenset(
    {"run_in_executor", "to_thread", "run_coroutine_threadsafe"}
)

# Call sites whose function-valued arguments become THREAD ENTRY POINTS —
# the roots the unmapped-shared-state rule counts distinct writers from.
THREAD_ROOT_CALLS: frozenset[str] = frozenset(
    {
        "Thread",
        "Timer",
        "add_done_callback",
        "call_soon_threadsafe",
        "submit",  # executor.submit(fn, ...)
    }
)
