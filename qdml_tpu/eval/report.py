"""Reporting: comparison plots + JSON results (reference
``create_comparison_plots``, ``Test.py:277-336``).

Reproduces the reference's two-panel figure — NMSE (dB) vs SNR for
LS / MMSE / HDCE-classical / HDCE-quantum, and classifier accuracy vs SNR —
saved to ``results/Quantum_vs_Classical_Comparison.png``, plus a detailed
results JSON (``results/quantum_classical_comparison.json``).
"""

from __future__ import annotations

import json
import os
from typing import Any

_CURVE_LABELS = {
    "ls": "LS",
    "mmse": "MMSE",
    "mmse_oracle": "MMSE (oracle prior)",
    "dce": "DCE (monolithic)",
    "hdce_classical": "HDCE (classical SC)",
    "hdce_quantum": "HDCE (quantum SC)",
}


def save_results_json(results: dict[str, Any], results_dir: str) -> str:
    os.makedirs(results_dir, exist_ok=True)
    path = os.path.join(results_dir, "quantum_classical_comparison.json")
    with open(path, "w") as fh:
        json.dump(results, fh, indent=2)
    return path


# Reference-published values read off its figures (BASELINE.md) for the
# side-by-side README table; keys match the sweep's curve names.
_REFERENCE_PUBLISHED = {
    "ls": {5.0: -2.2, 15.0: -12.0},
    "mmse": {5.0: -3.5, 15.0: -13.5},
    "hdce_classical": {5.0: -9.0, 15.0: -17.5},
    "hdce_quantum": {5.0: -9.0, 15.0: -17.5},
}
_REFERENCE_ACC = {5.0: 0.79, 15.0: 0.95}


def results_markdown_table(results: dict[str, Any]) -> str:
    """Markdown table of NMSE (dB) per curve at each SNR vs the reference's
    published figure values, plus classifier accuracies."""
    snrs = results["snr"]
    lines = [
        "| Curve | " + " | ".join(f"{s:g} dB" for s in snrs) + " | reference @5/@15 |",
        "|---|" + "---|" * (len(snrs) + 1),
    ]
    for key, vals in results["nmse_db"].items():
        ref = _REFERENCE_PUBLISHED.get(key)
        ref_s = f"{ref[5.0]:g} / {ref[15.0]:g}" if ref else "—"
        row = " | ".join(f"{v:.1f}" for v in vals)
        lines.append(f"| {_CURVE_LABELS.get(key, key)} | {row} | {ref_s} |")
    for key, vals in results.get("acc", {}).items():
        row = " | ".join(f"{v:.3f}" for v in vals)
        lines.append(
            f"| accuracy ({key} SC) | {row} | "
            f"{_REFERENCE_ACC[5.0]:g} / {_REFERENCE_ACC[15.0]:g} |"
        )
    return "\n".join(lines)


def create_comparison_plots(results: dict[str, Any], results_dir: str) -> str | None:
    """Two-panel comparison figure; returns the PNG path (None if matplotlib
    is unavailable)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:  # headless minimal images
        return None

    os.makedirs(results_dir, exist_ok=True)
    snr = results["snr"]
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4.5))

    for key, vals in results["nmse_db"].items():
        ax1.plot(snr, vals, marker="o", label=_CURVE_LABELS.get(key, key))
    ax1.set_xlabel("SNR (dB)")
    ax1.set_ylabel("NMSE (dB)")
    ax1.set_title("Channel estimation performance")
    ax1.grid(True, alpha=0.4)
    ax1.legend()

    for key, vals in results["acc"].items():
        ax2.plot(snr, vals, marker="s", label=f"{key} SC")
    ax2.set_xlabel("SNR (dB)")
    ax2.set_ylabel("Scenario classification accuracy")
    ax2.set_ylim(0.0, 1.02)
    ax2.set_title("Classifier accuracy")
    ax2.grid(True, alpha=0.4)
    ax2.legend()

    fig.tight_layout()
    path = os.path.join(results_dir, "Quantum_vs_Classical_Comparison.png")
    fig.savefig(path, dpi=130)
    plt.close(fig)
    return path
