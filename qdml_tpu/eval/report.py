"""Reporting: comparison plots + JSON results (reference
``create_comparison_plots``, ``Test.py:277-336``).

Reproduces the reference's two-panel figure — NMSE (dB) vs SNR for
LS / MMSE / HDCE-classical / HDCE-quantum, and classifier accuracy vs SNR —
saved to ``results/Quantum_vs_Classical_Comparison.png``, plus a detailed
results JSON (``results/quantum_classical_comparison.json``).
"""

from __future__ import annotations

import json
import os
from typing import Any

_CURVE_LABELS = {
    "ls": "LS",
    "mmse": "MMSE",
    "mmse_oracle": "MMSE (oracle prior)",
    "hdce_classical": "HDCE (classical SC)",
    "hdce_quantum": "HDCE (quantum SC)",
}


def save_results_json(results: dict[str, Any], results_dir: str) -> str:
    os.makedirs(results_dir, exist_ok=True)
    path = os.path.join(results_dir, "quantum_classical_comparison.json")
    with open(path, "w") as fh:
        json.dump(results, fh, indent=2)
    return path


def create_comparison_plots(results: dict[str, Any], results_dir: str) -> str | None:
    """Two-panel comparison figure; returns the PNG path (None if matplotlib
    is unavailable)."""
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:  # headless minimal images
        return None

    os.makedirs(results_dir, exist_ok=True)
    snr = results["snr"]
    fig, (ax1, ax2) = plt.subplots(1, 2, figsize=(12, 4.5))

    for key, vals in results["nmse_db"].items():
        ax1.plot(snr, vals, marker="o", label=_CURVE_LABELS.get(key, key))
    ax1.set_xlabel("SNR (dB)")
    ax1.set_ylabel("NMSE (dB)")
    ax1.set_title("Channel estimation performance")
    ax1.grid(True, alpha=0.4)
    ax1.legend()

    for key, vals in results["acc"].items():
        ax2.plot(snr, vals, marker="s", label=f"{key} SC")
    ax2.set_xlabel("SNR (dB)")
    ax2.set_ylabel("Scenario classification accuracy")
    ax2.set_ylim(0.0, 1.02)
    ax2.set_title("Classifier accuracy")
    ax2.grid(True, alpha=0.4)
    ax2.legend()

    fig.tight_layout()
    path = os.path.join(results_dir, "Quantum_vs_Classical_Comparison.png")
    fig.savefig(path, dpi=130)
    plt.close(fig)
    return path
