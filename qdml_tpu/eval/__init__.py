from qdml_tpu.eval.report import create_comparison_plots, save_results_json  # noqa: F401
from qdml_tpu.eval.sweep import make_sweep_step, run_snr_sweep  # noqa: F401
