"""Training loss-curve figure (reference ``Loss Curve.png``).

The reference's second published artifact plots classifier training loss vs
epoch for the classical CNN and the QML classifier at 4/6/8 qubits over 100
epochs (``Loss Curve.png`` legend; BASELINE.md rows "Final train loss").
The trainers here log one JSONL record per epoch (``train_loss`` key,
:class:`qdml_tpu.utils.metrics.MetricsLogger`), so the figure is a pure
post-processing step over any set of runs.
"""

from __future__ import annotations

import json
import os


def read_loss_history(jsonl_path: str) -> list[float]:
    """Per-epoch train losses from a trainer metrics JSONL (epoch-summary
    records are those carrying ``train_loss``)."""
    hist: list[float] = []
    with open(jsonl_path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "train_loss" in rec and "epoch" in rec:
                hist.append(float(rec["train_loss"]))
    return hist


def parse_curve_spec(spec: str) -> list[tuple[str, str]]:
    """``LABEL:PATH,LABEL:PATH`` -> [(label, path), ...]."""
    out = []
    for item in spec.split(","):
        if not item.strip():
            continue
        label, _, path = item.partition(":")
        if not path:
            raise ValueError(f"curve spec item {item!r} is not LABEL:PATH")
        out.append((label.strip(), path.strip()))
    return out


def create_loss_curve_plot(
    curves: list[tuple[str, list[float]]], results_dir: str
) -> str | None:
    """Loss-vs-epoch figure for the given (label, history) pairs; returns the
    PNG path (None if matplotlib is unavailable — the JSON twin is written
    regardless, it needs no plotting library)."""
    os.makedirs(results_dir, exist_ok=True)
    with open(os.path.join(results_dir, "loss_curves.json"), "w") as fh:
        json.dump({label: hist for label, hist in curves}, fh, indent=2)

    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:
        return None
    fig, ax = plt.subplots(figsize=(7.5, 4.8))
    for label, hist in curves:
        ax.plot(range(len(hist)), hist, label=label, linewidth=1.6)
    ax.set_xlabel("epoch")
    ax.set_ylabel("training loss")
    ax.set_title("Scenario-classifier training loss")
    ax.grid(True, alpha=0.4)
    ax.legend()
    fig.tight_layout()
    path = os.path.join(results_dir, "Loss_Curve.png")
    fig.savefig(path, dpi=130)
    plt.close(fig)
    return path
